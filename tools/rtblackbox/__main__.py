"""CLI: ``python -m tools.rtblackbox <events-dir>``.

Merges every flight-recorder ring file under the directory (including
rings left behind by SIGKILLed processes) into one cluster timeline.

  python -m tools.rtblackbox /tmp/rt-events
      full merged timeline, human-readable

  python -m tools.rtblackbox /tmp/rt-events --request rq-3f21-7
      one request's cross-process story: its own events plus the
      context (kill / drain / epoch bump) that explains its fate

  python -m tools.rtblackbox /tmp/rt-events --trace out.json
      Chrome trace-event export (chrome://tracing, Perfetto)

  python -m tools.rtblackbox /tmp/rt-events --spans spans.json ...
      stitch a tracing.get_spans() dump into request reconstructions

Exit code 0 on success, 1 when the directory holds no readable rings,
2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import (chrome_trace, format_timeline, load_rings, load_spans,
               merge_timeline, reconstruct_request)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.rtblackbox",
        description="merge flight-recorder rings; reconstruct requests")
    ap.add_argument("directory", help="directory holding *.evr ring files")
    ap.add_argument("--request", default=None, metavar="ID",
                    help="reconstruct one request id instead of the "
                         "full timeline")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="write Chrome trace-event JSON ('-' = stdout)")
    ap.add_argument("--spans", default=None, metavar="SPANS.json",
                    help="span dump to stitch into --request output")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output on stdout")
    ap.add_argument("--limit", type=int, default=0,
                    help="print at most N timeline events (0 = all)")
    args = ap.parse_args(argv)

    loaded = load_rings(args.directory)
    for err in loaded["errors"]:
        print(f"warning: {err['path']}: {err['error']}", file=sys.stderr)
    if not loaded["rings"]:
        print(f"no readable ring files under {args.directory}",
              file=sys.stderr)
        return 1
    timeline = merge_timeline(loaded["rings"])

    if args.trace:
        trace = chrome_trace(timeline)
        if args.trace == "-":
            json.dump(trace, sys.stdout)
            sys.stdout.write("\n")
        else:
            with open(args.trace, "w", encoding="utf-8") as f:
                json.dump(trace, f)
            print(f"wrote {len(trace)} trace events to {args.trace}",
                  file=sys.stderr)

    if args.request is not None:
        spans = load_spans(args.spans) if args.spans else None
        story = reconstruct_request(timeline, args.request, spans=spans)
        if args.json:
            json.dump(story, sys.stdout, default=str)
            sys.stdout.write("\n")
        else:
            print(f"request {story['request']}: "
                  f"{len(story['events'])} events across "
                  f"{len({e['proc'] for e in story['events']})} "
                  f"process(es); replicas={story['replicas']}")
            print(format_timeline(story["events"]))
            if story.get("spans"):
                print(f"-- {len(story['spans'])} stitched span(s):")
                for sp in story["spans"]:
                    print(f"  {sp.get('name')} "
                          f"[{sp.get('kind')}] "
                          f"{sp.get('end', 0) - sp.get('start', 0):.6f}s "
                          f"status={sp.get('status')}")
        return 0

    events = timeline["events"]
    shown = events[-args.limit:] if args.limit else events
    if args.json:
        json.dump({"events": shown, "torn": timeline["torn"],
                   "procs": timeline["procs"]}, sys.stdout, default=str)
        sys.stdout.write("\n")
    else:
        print(f"{len(events)} events from {len(timeline['procs'])} "
              f"process(es), {timeline['torn']} torn record(s) "
              f"tolerated")
        print(format_timeline(shown))
    return 0


if __name__ == "__main__":
    sys.exit(main())
