"""rtblackbox — post-mortem reconstruction from flight-recorder rings.

Every ray_tpu process with ``RT_EVENTS_DIR`` set appends structured
events to a preallocated mmap'd ring file (``ray_tpu._private.events``).
The ring survives SIGKILL: the last-N events of a dead replica are
still on disk. This package merges a directory of such rings — live
and dead processes alike — into ONE cluster timeline, and can
reconstruct a single request's cross-process story (admission →
dispatches → kill → router resume → completion) from it.

Clock model
-----------
Wall clocks lie (NTP steps, deliberate skew); ``CLOCK_MONOTONIC`` does
not, but is only comparable between processes of the SAME boot. Each
ring header carries a (wall, monotonic) anchor pair sampled at open
plus the host's ``boot_id``. The merge therefore:

1. groups rings by ``boot_id``;
2. within a group, orders events by their RAW monotonic stamps — a
   process with a skewed wall clock cannot reorder the timeline;
3. maps monotonic to a unified wall axis through ONE reference offset
   per group (the median of the rings' ``wall_anchor - mono_anchor``,
   robust to a minority of skewed processes);
4. across groups (different hosts), the per-event unified stamps are
   already wall-comparable and events merge by them.

Use ``python -m tools.rtblackbox <dir>`` for the CLI.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from ray_tpu._private.events import read_ring

# Event kinds that explain a request's fate without carrying its id:
# the kill that took the replica down, the controller noticing, the
# drain, the engine epoch bump. They join a reconstruction when they
# name a replica (or deployment) the request's own events touched.
CONTEXT_KINDS = (
    "chaos.kill",
    "controller.replica_dead",
    "controller.drain",
    "replica.drain",
    "engine.driver_restart",
)


# --------------------------------------------------------------- loading
def load_rings(directory: str) -> Dict[str, Any]:
    """Read every ``*.evr`` ring under ``directory`` (non-recursive).
    Unreadable files are collected, not fatal — a half-written header
    from a process killed at open must not sink the post-mortem."""
    rings: List[Dict[str, Any]] = []
    errors: List[Dict[str, str]] = []
    for path in sorted(glob.glob(os.path.join(directory, "*.evr"))):
        try:
            rings.append(read_ring(path))
        except Exception as e:  # noqa: BLE001 - skip, report, continue
            errors.append({"path": path, "error": f"{type(e).__name__}: {e}"})
    return {"rings": rings, "errors": errors}


# --------------------------------------------------------------- merging
def merge_timeline(rings: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge ring dicts (from :func:`load_rings` / ``read_ring``) into
    one ordered timeline. Each merged event gains:

    - ``t``     unified wall stamp (monotonic mapped through the boot
                group's reference offset — see module docstring);
    - ``proc``  the emitting process label, ``pid`` its pid.

    Ordering within a boot group follows raw monotonic stamps, so a
    process whose wall clock is hours off still lands where it really
    ran."""
    by_boot: Dict[str, List[Dict[str, Any]]] = {}
    for r in rings:
        by_boot.setdefault(r.get("boot_id") or "?", []).append(r)
    events: List[Dict[str, Any]] = []
    offsets: Dict[str, float] = {}
    for boot, group in by_boot.items():
        offs = sorted(r["wall_anchor"] - r["mono_anchor"] for r in group)
        ref = offs[len(offs) // 2]  # median: robust to skewed minority
        offsets[boot] = ref
        for r in group:
            label = f'{r.get("proc") or "proc"}-{r.get("pid", 0)}'
            for e in r["events"]:
                events.append({
                    "t": e["mono"] + ref, "mono": e["mono"],
                    "wall": e["wall"], "seq": e["seq"],
                    "kind": e["kind"], "attrs": e.get("attrs") or {},
                    "proc": label, "pid": r.get("pid", 0),
                    "boot_id": boot,
                })
    # Same-boot events share one offset, so sorting by t IS sorting by
    # monotonic there; cross-boot interleaving falls back to the
    # unified wall axis (the best any merger can do across hosts).
    events.sort(key=lambda e: (e["t"], e["proc"], e["seq"]))
    return {
        "events": events,
        "torn": sum(r.get("torn", 0) for r in rings),
        "procs": sorted({e["proc"] for e in events}),
        "offsets": offsets,
    }


# --------------------------------------------------- request reconstruction
def _replica_refs(attrs: Dict[str, Any]) -> set:
    refs = set()
    for key in ("replica", "from_replica", "to_replica"):
        v = attrs.get(key)
        if v:
            refs.add(str(v))
    for v in attrs.get("replicas") or []:
        if v:
            refs.add(str(v))
    return refs


def reconstruct_request(timeline: Dict[str, Any], request_id: str,
                        spans: Optional[List[dict]] = None
                        ) -> Dict[str, Any]:
    """One request's cross-process story. Core events carry the
    request's correlation id in ``attrs["request"]``; context events
    (:data:`CONTEXT_KINDS`) join when they name a replica the request
    touched — that is how the SIGKILL that murdered the serving
    replica lands inside the request's own narrative even though the
    killer never knew the request id.

    ``spans`` (optional, the ``util.tracing`` span dicts) are stitched
    in by correlation: any span whose attrs mention the request id
    pulls in its whole trace tree."""
    core = [e for e in timeline["events"]
            if str(e["attrs"].get("request", "")) == request_id]
    replicas: set = set()
    deployments: set = set()
    for e in core:
        replicas |= _replica_refs(e["attrs"])
        dep = e["attrs"].get("deployment")
        if dep:
            deployments.add(str(dep))
    context = []
    for e in timeline["events"]:
        if e["kind"] not in CONTEXT_KINDS:
            continue
        refs = _replica_refs(e["attrs"])
        if (refs & replicas) or (not refs and str(
                e["attrs"].get("deployment", "")) in deployments):
            context.append(e)
    seen = {id(e) for e in core}
    story = core + [e for e in context if id(e) not in seen]
    story.sort(key=lambda e: (e["t"], e["proc"], e["seq"]))
    out: Dict[str, Any] = {
        "request": request_id,
        "events": [{**e, "relevance":
                    "request" if str(e["attrs"].get("request", ""))
                    == request_id else "context"} for e in story],
        "replicas": sorted(replicas),
        "deployments": sorted(deployments),
        "kinds": sorted({e["kind"] for e in story}),
    }
    if story:
        out["first_t"] = story[0]["t"]
        out["last_t"] = story[-1]["t"]
        out["duration_s"] = round(story[-1]["t"] - story[0]["t"], 6)
    if spans:
        hit_traces = set()
        for sp in spans:
            attrs = sp.get("attrs") or {}
            if any(str(v) == request_id for v in attrs.values()):
                hit_traces.add(sp.get("trace_id"))
        tree = [sp for sp in spans if sp.get("trace_id") in hit_traces]
        tree.sort(key=lambda sp: sp.get("start", 0.0))
        out["spans"] = tree
    return out


# ---------------------------------------------------------- chrome trace
def chrome_trace(timeline: Dict[str, Any]) -> List[dict]:
    """The merged timeline as Chrome trace-event JSON (load in
    ``chrome://tracing`` / Perfetto). Events are instants on the
    unified axis; one row per process."""
    out: List[dict] = []
    named = set()
    for e in timeline["events"]:
        if e["proc"] not in named:
            named.add(e["proc"])
            out.append({"name": "process_name", "ph": "M",
                        "pid": e["pid"], "tid": 0,
                        "args": {"name": e["proc"]}})
        out.append({
            "name": e["kind"], "ph": "i", "s": "p",
            "ts": e["t"] * 1e6, "pid": e["pid"], "tid": 0,
            "cat": e["kind"].split(".", 1)[0],
            "args": dict(e["attrs"]),
        })
    return out


# -------------------------------------------------------------- rendering
def format_event(e: Dict[str, Any], t0: float = 0.0) -> str:
    attrs = " ".join(f"{k}={v}" for k, v in sorted(e["attrs"].items()))
    mark = "*" if e.get("relevance") == "context" else " "
    return (f"{e['t'] - t0:+12.6f}s {mark} {e['proc']:<28s} "
            f"{e['kind']:<24s} {attrs}")


def format_timeline(events: List[Dict[str, Any]]) -> str:
    if not events:
        return "(no events)"
    t0 = events[0]["t"]
    return "\n".join(format_event(e, t0) for e in events)


def load_spans(path: str) -> List[dict]:
    """Span dicts from a JSON file (a ``tracing.get_spans()`` dump, or
    the ``{"spans": [...]}`` wrapper ``with_meta=True`` produces)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("spans", [])
    return list(data)
