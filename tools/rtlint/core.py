"""rtlint core: module model, finding model, suppressions, baseline,
and the analysis driver.

The analyzer is a plain AST walk per file plus a handful of
whole-project rules; there is no type inference and no import
resolution. Everything a rule learns comes from three places:

- the parse tree (``Module.tree``),
- the comment map (``Module.comments``, built with ``tokenize`` so
  comments survive into analysis — ``ast`` alone drops them),
- rtlint directives parsed out of those comments.

Directive grammar (one comment, any number of ``key=value`` tokens
separated by whitespace or commas; prose after the tokens is ignored so
directives can carry a justification; the parse itself lives in
:mod:`tools.rtlint.annotations` — THE loader shared with the runtime
sanitizer, tools/rtsan)::

    # rtlint: disable=RT101,RT104   <why this is safe>
    # rtlint: disable=all
    # rtlint: owner=driver          <single-thread-owned method>
    # rtlint: holds=_lock           <every caller holds self._lock>
    # rtlint: entry=driver          <caller registers as the driver>

Placement: a ``disable`` on the finding line (or the line directly
above, for wrapped statements) suppresses that line; any directive on a
``def`` line (or the line directly above the ``def``) applies to the
whole function body. ``owner``/``holds``/``entry`` are function-level
contracts used by RT101/RT102/RT108 statically and enforced at runtime
by tools/rtsan.

Findings carry a stable **key** (``rule:path:symbol``) that does not
include the line number, so the checked-in baseline survives unrelated
edits; duplicate symbols within a file are disambiguated with ``#n``
suffixes in source order.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .annotations import (comment_map, func_directives, line_directives,
                          parse_directives)

RULE_ID_RE = re.compile(r"^RT\d{3}$")

#: Pseudo-rule for files the analyzer cannot parse: a broken file must
#: fail the gate (it would otherwise silently escape every real rule).
PARSE_ERROR_RULE = "RT999"


@dataclass(frozen=True, order=True)
class Finding:
    path: str          # repo-relative, '/'-separated
    line: int
    rule: str
    message: str
    symbol: str        # stable anchor for the baseline key

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message, "symbol": self.symbol,
                "key": self.key}

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class Module:
    """One parsed source file plus its comment/directive maps. The
    directive parse lives in :mod:`tools.rtlint.annotations` — THE
    shared loader the runtime sanitizer (tools/rtsan) reads the same
    contracts through; ``tag`` selects whose directives this module
    resolves (rtlint suppressions by default, ``"rtsan"`` for the
    sanitizer's ``# rtsan: disable=RSxxx`` suppressions)."""

    def __init__(self, path: str, relpath: str, source: str,
                 tag: str = "rtlint"):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tag = tag
        self.lines = source.splitlines()
        self.tree = ast.parse(source)       # caller handles SyntaxError
        #: line -> full comment text (without the leading '#')
        self.comments: Dict[int, str] = comment_map(source)
        #: line -> directives on that line
        self.directives: Dict[int, Dict[str, str]] = {
            ln: d for ln, c in self.comments.items()
            if (d := parse_directives(c, tag))}
        # Function-level directive intervals (innermost last so lookups
        # can prefer the tightest enclosing def).
        self._func_spans: List[Tuple[int, int, Dict[str, str]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d = self.func_directives(node)
                if d:
                    self._func_spans.append(
                        (node.lineno, node.end_lineno or node.lineno, d))
        self._func_spans.sort()

    # ----------------------------------------------------------- directives
    def line_directives(self, line: int) -> Dict[str, str]:
        """Directives attached to ``line``: on the line itself or the
        line directly above (wrapped statements)."""
        return line_directives(self.directives, line)

    def func_directives(self, funcdef) -> Dict[str, str]:
        """Directives anywhere on the (possibly multi-line) ``def``
        signature, or on the line directly above it."""
        return func_directives(self.directives, funcdef)

    def _disabled_rules(self, d: Dict[str, str]) -> Set[str]:
        raw = d.get("disable", "")
        return {r.strip() for r in raw.split(",") if r.strip()}

    def suppresses(self, line: int, rule: str) -> bool:
        """Inline or enclosing-def ``disable=`` suppression for a
        finding anchored at ``line``."""
        dis = self._disabled_rules(self.line_directives(line))
        if rule in dis or "all" in dis:
            return True
        for start, end, d in self._func_spans:
            if start <= line <= end:
                dis = self._disabled_rules(d)
                if rule in dis or "all" in dis:
                    return True
        return False


class Rule:
    """Per-module rule. Subclasses set ``id``/``summary`` and implement
    :meth:`check`; override :meth:`applies` to scope by path."""

    id = "RT000"
    summary = ""

    def applies(self, mod: Module) -> bool:
        return True

    def check(self, mod: Module) -> Iterable[Finding]:
        raise NotImplementedError


class ProjectRule(Rule):
    """Rule that needs the whole analyzed file set at once (cross-file
    consistency checks)."""

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        raise NotImplementedError

    def check(self, mod: Module) -> Iterable[Finding]:
        return ()


# --------------------------------------------------------------- baseline
def load_baseline(path: Optional[str]) -> Set[str]:
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return set(data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]):
    data = {
        "comment": (
            "rtlint grandfathered findings. Entries are finding keys "
            "(rule:path:symbol — line numbers excluded so unrelated "
            "edits don't churn this file). Remove an entry once its "
            "finding is fixed; regenerate with --update-baseline."),
        # Parse errors are never grandfatherable: a baselined broken
        # file would pass --check while escaping every real rule.
        "findings": sorted(f.key for f in findings
                           if f.rule != PARSE_ERROR_RULE),
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ----------------------------------------------------------------- driver
def collect_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """Expand files/dirs into sorted (abspath, relpath) python files."""
    out = []
    for p in paths:
        p = os.path.normpath(p)
        if os.path.isfile(p):
            out.append((os.path.abspath(p), p))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for fn in sorted(files):
                if fn.endswith(".py"):
                    fp = os.path.join(root, fn)
                    out.append((os.path.abspath(fp), fp))
    # Dedup while keeping deterministic order.
    seen, uniq = set(), []
    for ap, rp in sorted(out, key=lambda t: t[1]):
        if ap not in seen:
            seen.add(ap)
            uniq.append((ap, rp))
    return uniq


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)   # all, sorted
    new: List[Finding] = field(default_factory=list)        # not baselined
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    files_checked: int = 0

    def to_json(self) -> str:
        """Deterministic JSON: content-addressed only — no timestamps,
        no absolute paths — so two runs over the same tree are
        byte-identical."""
        return json.dumps({
            "version": 1,
            "files_checked": self.files_checked,
            "findings": [f.to_dict() for f in self.findings],
            "new": [f.key for f in self.new],
            "baselined": [f.key for f in self.baselined],
            "stale_baseline": sorted(self.stale_baseline),
        }, indent=2, sort_keys=True)


def _dedup_symbols(findings: List[Finding]) -> List[Finding]:
    """Disambiguate duplicate (rule, path, symbol) keys with ``#n``
    suffixes in source order, so every baseline key is unique."""
    counts: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings):
        k = (f.rule, f.path, f.symbol)
        n = counts.get(k, 0)
        counts[k] = n + 1
        if n:
            f = Finding(f.path, f.line, f.rule, f.message,
                        f"{f.symbol}#{n + 1}")
        out.append(f)
    return out


def run(paths: Sequence[str], rules: Sequence[Rule],
        baseline_path: Optional[str] = None,
        rule_filter: Optional[Set[str]] = None) -> Report:
    """Analyze ``paths`` with ``rules``; returns the full report with
    baseline split applied."""
    report = Report()
    if rule_filter:
        # Skip filtered-out rules up front: their findings would be
        # dropped anyway, and the rtflow ProjectRules each pay a
        # project-wide call-graph + fixpoint analysis.
        rules = [r for r in rules if r.id in rule_filter]
    mods: List[Module] = []
    raw: List[Finding] = []
    for abspath, relpath in collect_files(paths):
        report.files_checked += 1
        try:
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            mod = Module(abspath, relpath, source)
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append(Finding(
                relpath.replace(os.sep, "/"),
                getattr(e, "lineno", 0) or 0, PARSE_ERROR_RULE,
                f"file does not parse: {e.msg if hasattr(e, 'msg') else e}",
                "<parse>"))
            continue
        mods.append(mod)
    for mod in mods:
        for rule in rules:
            if isinstance(rule, ProjectRule) or not rule.applies(mod):
                continue
            for f in rule.check(mod):
                if not mod.suppresses(f.line, f.rule):
                    raw.append(f)
    by_rel = {m.relpath: m for m in mods}
    for rule in rules:
        if not isinstance(rule, ProjectRule):
            continue
        for f in rule.check_project(mods):
            mod = by_rel.get(f.path)
            if mod is None or not mod.suppresses(f.line, f.rule):
                raw.append(f)
    report.findings = _dedup_symbols(raw)
    baseline = load_baseline(baseline_path)
    seen_keys = set()
    for f in report.findings:
        seen_keys.add(f.key)
        # A parse error always fails the gate, even if a hand-edited
        # baseline carries its key — a broken file escapes every rule.
        if f.rule != PARSE_ERROR_RULE and f.key in baseline:
            report.baselined.append(f)
        else:
            report.new.append(f)
    report.stale_baseline = sorted(baseline - seen_keys)
    return report
