"""CLI: ``python -m tools.rtlint <paths...>``.

Exit code 0 when every finding is grandfathered in the baseline (or
there are none); 1 when new findings exist (or any analyzed file fails
to parse); 2 on usage errors. ``--check`` is the CI-gate spelling: it
prints only the failures. Output is deterministic — two runs over the
same tree produce byte-identical reports (pinned by the determinism
test in ``tests/test_rtlint.py``).
"""
from __future__ import annotations

import argparse
import os
import sys

from . import (DEFAULT_BASELINE, RULE_TABLE, load_baseline, run_paths,
               write_baseline)
from .core import PARSE_ERROR_RULE


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.rtlint",
        description="repo-native static analysis (rules "
                    f"{min(RULE_TABLE)}-{max(RULE_TABLE)})")
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: print only new findings")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         f"when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline entirely")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(refuses to ADD entries unless --allow-growth "
                         "is passed — the baseline is a burn-down list, "
                         "not a dumping ground)")
    ap.add_argument("--allow-growth", action="store_true",
                    help="let --update-baseline grandfather NEW "
                         "findings instead of refusing")
    args = ap.parse_args(argv)

    rule_filter = None
    if args.rules:
        rule_filter = {r.strip().upper() for r in args.rules.split(",")}
        unknown = rule_filter - set(RULE_TABLE)
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))} "
                  f"(known: {', '.join(sorted(RULE_TABLE))})",
                  file=sys.stderr)
            return 2

    baseline = None if args.no_baseline else (
        args.baseline if args.baseline is not None
        else (DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE)
              else None))

    report = run_paths(args.paths, baseline_path=baseline,
                       rule_filter=rule_filter)

    if args.update_baseline:
        if rule_filter is not None:
            # A rule-filtered report only sees a slice of the findings;
            # writing it out would silently drop every other rule's
            # grandfathered entries.
            print("refusing --update-baseline with --rules: the "
                  "baseline spans ALL rules, a filtered run cannot "
                  "rewrite it", file=sys.stderr)
            return 2
        path = args.baseline or DEFAULT_BASELINE
        old = load_baseline(path)
        grown = sorted(
            {f.key for f in report.findings
             if f.rule != PARSE_ERROR_RULE} - old)
        if grown and not args.allow_growth:
            print(f"refusing to grow the baseline: {len(grown)} "
                  f"finding{'s' if len(grown) != 1 else ''} not "
                  f"already grandfathered — fix them, suppress them "
                  f"with a justification, or pass --allow-growth:",
                  file=sys.stderr)
            for k in grown:
                print(f"  {k}", file=sys.stderr)
            return 2
        write_baseline(path, report.findings)
        print(f"baseline written: {path} "
              f"({len(report.findings)} findings)")
        return 0

    if args.json:
        print(report.to_json())
    else:
        shown = report.new if args.check else report.findings
        for f in shown:
            mark = "" if args.check else (
                " [baselined]" if f in report.baselined else "")
            print(f.render() + mark)
        if not args.check or report.new or report.stale_baseline:
            print(f"rtlint: {report.files_checked} files, "
                  f"{len(report.findings)} findings "
                  f"({len(report.new)} new, "
                  f"{len(report.baselined)} baselined)")
        if report.stale_baseline:
            print(f"rtlint: {len(report.stale_baseline)} stale baseline "
                  f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'}"
                  f" (fixed findings - remove them): ")
            for k in report.stale_baseline:
                print(f"  {k}")
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
