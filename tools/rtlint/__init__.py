"""rtlint: repo-native static analysis for ray_tpu's concurrency,
jit-recompile, and wire-protocol invariants.

Usage::

    python -m tools.rtlint ray_tpu/              # human report
    python -m tools.rtlint ray_tpu/ --json       # machine report
    python -m tools.rtlint ray_tpu/ --check      # CI gate (quiet)

Rules (see ``tools/rtlint/rules.py`` for the conventions each leans on;
RT109-RT111 are **rtflow** rules — interprocedural dataflow over the
project call graph, ``tools/rtlint/flow.py`` + ``callgraph.py``):

========  ============================================================
RT101     attribute written both with and without its guarding lock
RT102     device dispatch outside a driver-annotated engine method
RT103     unhashable / unbounded-cardinality args into jit factories
          (intra-procedural — the hazard visible AT the call site)
RT104     blocking calls (time.sleep, .get(), .result()) in async defs
RT105     retryable pushback classes out of sync with _PUSHBACK_CAUSES
RT106     metric names violating prometheus conventions (shared with
          the runtime MetricsRegistry.register lint)
RT107     bare / silently-swallowed except in serve control loops
RT108     owner=/holds= annotations naming a lock / driver
          registration that does not exist (the same contracts the
          runtime sanitizer tools/rtsan enforces dynamically)
RT109     static compiled-program-budget audit: factory entrypoints
          declare ``# rtlint: program-budget: <expr>``; rtflow bounds
          the reachable trace keys (through helpers, fields, and
          dispatch shapes) and fails on excess or unboundedness
RT110     holds=/owner=driver contracts checked at every resolved call
          EDGE (the helper-boundary blind spot of RT101/RT102; static
          twin of rtsan's RS102/RS103)
RT111     host-device sync points on dispatch results in the driver
          files must carry ``# rtlint: sync-ok=<tag> <why>`` — the
          dispatch loop's sync inventory is explicit and gated
RT112     flight-recorder emission inside owner=driver hot loops must
          use the rate-capped ``driver_emit`` helper — a plain
          ``events.emit`` at dispatch frequency floods the ring
========  ============================================================

The lint → sanitize pipeline: one annotation grammar
(:mod:`tools.rtlint.annotations`) is parsed by BOTH the static rules
above and the runtime sanitizer ``tools/rtsan`` (RS101-RS105), and
``python -m tools.rtsan --report`` prints the annotation-coverage
summary — the fraction of driver methods / locks actually carrying the
contracts — so the two enforcement layers visibly share one contract
set.

Suppression: ``# rtlint: disable=RT101[,RT104]`` on the offending line
(or the line above, the enclosing ``def`` signature, or a decorator
line of that def) — add a justification after the directive.
Grandfathered findings live in ``tools/rtlint/baseline.json``;
``--update-baseline`` regenerates it, and refuses to ADD entries
unless ``--allow-growth`` is passed (the baseline is a burn-down list).

Diagnosing an RT109 unbounded-trace-key report: the finding names the
argument (or dispatched array) whose cardinality rtflow bounded as
``unbounded``. Walk backwards from that line: the value came from
``len(...)``/``.shape`` of request data — often through a helper
return or a dataclass field, which is why no ``len()`` appears at the
flagged site. Fix it the way the engine does: re-bound the value
through the bucket discipline (``next(b for b in self.prompt_buckets
if b >= n)``) before it touches a shape or a factory argument; the
bound then shows up as ``len(prompt_buckets)`` in the computed budget
instead of ``unbounded``. See ``README.md`` ("Static analysis") for a
worked example.
"""
from .annotations import (FuncAnn, load_annotations,  # noqa: F401
                          parse_directives)
from .callgraph import CallGraph
from .core import (Finding, Module, ProjectRule, Report, Rule,
                   load_baseline, run, write_baseline)
from .flow import Card, FlowAnalysis, declared_budgets, parse_budget
from .metrics_names import lint_metric_name
from .rules import ALL_RULES, RULE_TABLE

DEFAULT_BASELINE = "tools/rtlint/baseline.json"


def run_paths(paths, baseline_path=None, rule_filter=None) -> Report:
    """Analyze ``paths`` with every rule; the library entry point the
    CLI and the tests share."""
    return run(paths, ALL_RULES, baseline_path=baseline_path,
               rule_filter=rule_filter)


__all__ = ["ALL_RULES", "CallGraph", "Card", "DEFAULT_BASELINE",
           "Finding", "FlowAnalysis", "FuncAnn", "Module",
           "ProjectRule", "Report", "Rule", "RULE_TABLE",
           "declared_budgets", "lint_metric_name", "load_annotations",
           "load_baseline", "parse_budget", "parse_directives", "run",
           "run_paths", "write_baseline"]
