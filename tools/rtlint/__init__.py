"""rtlint: repo-native static analysis for ray_tpu's concurrency,
jit-recompile, and wire-protocol invariants.

Usage::

    python -m tools.rtlint ray_tpu/              # human report
    python -m tools.rtlint ray_tpu/ --json       # machine report
    python -m tools.rtlint ray_tpu/ --check      # CI gate (quiet)

Rules (see ``tools/rtlint/rules.py`` for the conventions each leans on):

========  ============================================================
RT101     attribute written both with and without its guarding lock
RT102     device dispatch outside a driver-annotated engine method
RT103     unhashable / unbounded-cardinality args into jit factories
RT104     blocking calls (time.sleep, .get(), .result()) in async defs
RT105     retryable pushback classes out of sync with _PUSHBACK_CAUSES
RT106     metric names violating prometheus conventions (shared with
          the runtime MetricsRegistry.register lint)
RT107     bare / silently-swallowed except in serve control loops
RT108     owner=/holds= annotations naming a lock / driver
          registration that does not exist (the same contracts the
          runtime sanitizer tools/rtsan enforces dynamically)
========  ============================================================

Suppression: ``# rtlint: disable=RT101[,RT104]`` on the offending line
(or the line above, or the enclosing ``def`` line) — add a justification
after the directive. Grandfathered findings live in
``tools/rtlint/baseline.json``; ``--update-baseline`` regenerates it.
"""
from .annotations import (FuncAnn, load_annotations,  # noqa: F401
                          parse_directives)
from .core import (Finding, Module, ProjectRule, Report, Rule,
                   load_baseline, run, write_baseline)
from .metrics_names import lint_metric_name
from .rules import ALL_RULES, RULE_TABLE

DEFAULT_BASELINE = "tools/rtlint/baseline.json"


def run_paths(paths, baseline_path=None, rule_filter=None) -> Report:
    """Analyze ``paths`` with every rule; the library entry point the
    CLI and the tests share."""
    return run(paths, ALL_RULES, baseline_path=baseline_path,
               rule_filter=rule_filter)


__all__ = ["Finding", "FuncAnn", "Module", "ProjectRule", "Report",
           "Rule", "ALL_RULES", "RULE_TABLE", "DEFAULT_BASELINE",
           "lint_metric_name", "load_annotations", "load_baseline",
           "parse_directives", "run", "run_paths", "write_baseline"]
