"""rtlint rules RT101-RT107: the invariants this repo's serve/engine
stack keeps breaking in review (see ISSUE 8).

Every rule is lexical AST analysis — no type inference — so each one
documents the convention it leans on and the annotation that satisfies
it. False positives are handled with ``# rtlint: disable=RTxxx`` plus a
justification, or grandfathered in the checked-in baseline.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, Module, ProjectRule, Rule
from .metrics_names import lint_metric_name

#: Attribute names that count as locks for RT101 guard inference
#: (shared definition — see annotations.LOCKISH_RE).
from .annotations import LOCKISH_RE
#: Receiver names that look like queues for RT104's timeout-less .get().
QUEUEISH_RE = re.compile(r"(^|_)(q|queue)$|queue", re.I)


def _self_attr(node) -> Optional[str]:
    """``self.X`` -> ``'X'`` (else None)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _terminal_name(func) -> Optional[str]:
    """Rightmost name of a call target: ``a.b.c(...)`` -> ``'c'``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _write_target_attr(node) -> Optional[str]:
    """Attr written by an assignment target: ``self.X`` or
    ``self.X[...]`` -> ``'X'``."""
    a = _self_attr(node)
    if a is not None:
        return a
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return None


# ------------------------------------------------------------------ RT101
class LockGuardRule(Rule):
    """RT101: a ``self._x`` attribute written both inside and outside
    ``with self.<lock>`` blocks across a class's methods.

    Convention knobs (all lexical):

    - lock attrs are ``self.*`` names matching ``lock|cond|mutex`` used
      as ``with`` contexts anywhere in the class;
    - ``__init__``/``__del__`` writes are construction/teardown, never
      counted as unguarded;
    - methods named ``*_locked``, annotated ``# rtlint: holds=<lock>``,
      or containing a manual ``self.<lock>.acquire(...)`` call are
      treated as guarded (callers hold the lock / hand-rolled locking);
    - methods annotated ``# rtlint: owner=driver`` are single-thread
      owned: their writes need no lock by design (see RT102).
    """

    id = "RT101"
    summary = "attribute written both with and without its guarding lock"

    def check(self, mod: Module) -> Iterable[Finding]:
        for cls in [n for n in ast.walk(mod.tree)
                    if isinstance(n, ast.ClassDef)]:
            yield from self._check_class(mod, cls)

    def _check_class(self, mod: Module, cls: ast.ClassDef):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        locks: Set[str] = set()
        for m in methods:
            for w in ast.walk(m):
                if isinstance(w, ast.With):
                    for item in w.items:
                        a = _self_attr(item.context_expr)
                        if a and LOCKISH_RE.search(a):
                            locks.add(a)
        if not locks:
            return
        # attr -> [(method, line, guards frozenset, assumed_guarded)]
        writes: Dict[str, List[Tuple[str, int, frozenset, bool]]] = {}
        for m in methods:
            d = mod.func_directives(m)
            if d.get("owner") == "driver":
                continue           # single-thread owned: no lock needed
            held = {h.strip() for h in d.get("holds", "").split(",")
                    if h.strip()}
            assumed = (m.name.endswith("_locked") or bool(held)
                       or self._acquires_manually(m, locks))
            self._collect_writes(m, locks, held, assumed, writes)
        for attr, ws in sorted(writes.items()):
            guarded = [w for w in ws if w[2] or w[3]]
            unguarded = [w for w in ws if not (w[2] or w[3])
                         and w[0] not in ("__init__", "__del__")]
            if not guarded or not unguarded:
                continue
            lock_names = sorted({l for w in guarded for l in w[2]}) \
                or sorted(locks)
            g = guarded[0]
            for (mn, ln, _gs, _a) in unguarded:
                yield Finding(
                    mod.relpath, ln, self.id,
                    f"self.{attr} is written in {cls.name}.{mn} without "
                    f"{'/'.join('self.' + l for l in lock_names)} held, "
                    f"but under it in {cls.name}.{g[0]} (line {g[1]}); "
                    f"guard the write, annotate the method with "
                    f"'# rtlint: holds=<lock>' or "
                    f"'# rtlint: owner=driver', or suppress with a "
                    f"justification",
                    f"{cls.name}.{mn}.{attr}")

    @staticmethod
    def _acquires_manually(m, locks: Set[str]) -> bool:
        for w in ast.walk(m):
            if isinstance(w, ast.Call) and \
                    isinstance(w.func, ast.Attribute) and \
                    w.func.attr == "acquire" and \
                    _self_attr(w.func.value) in locks:
                return True
        return False

    @staticmethod
    def _collect_writes(m, locks, held, assumed, writes):
        def rec(node, guards):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not m:
                return             # nested def: different execution ctx
            if isinstance(node, ast.With):
                g2 = set(guards)
                for item in node.items:
                    a = _self_attr(item.context_expr)
                    if a in locks:
                        g2.add(a)
                for c in node.body:
                    rec(c, g2)
                return
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                a = _write_target_attr(t)
                if a and a not in locks:
                    writes.setdefault(a, []).append(
                        (m.name, node.lineno,
                         frozenset(guards | held), assumed))
            for c in ast.iter_child_nodes(node):
                rec(c, guards)
        rec(m, set())


# ------------------------------------------------------------------ RT102
class DriverOwnershipRule(Rule):
    """RT102: device-dispatch calls in the decode engine (its drafters
    — ISSUE 9 — the offline batch-inference pipeline driver,
    ``data/llm.py`` — ISSUE 11 — the disaggregation handoff plane,
    ``serve/handoff.py`` — ISSUE 14 — and the autoscaling control
    loop, ``serve/autoscaler.py`` — ISSUE 17) must run on the driver
    thread (the reconcile thread, for the autoscaler).
    Lexically: calls to the bound jit wrappers (``self._prefill`` /
    ``self._step`` / ``self._verify`` / ``self._ingest`` /
    ``self._export`` / ``self._import``) or an immediately-invoked
    ``jit_*`` factory (``jit_x(...)(...)``) are only allowed inside
    methods annotated ``# rtlint: owner=driver``. Binding a factory
    (``self._prefill = jit_prefill(...)``) is construction, not a
    dispatch, and is not flagged."""

    id = "RT102"
    summary = "device dispatch outside a driver-annotated method"

    DISPATCH_ATTRS = ("_prefill", "_step", "_verify", "_ingest",
                      "_export", "_import")

    def applies(self, mod: Module) -> bool:
        return mod.relpath.endswith(("serve/engine.py",
                                     "serve/draft.py",
                                     "serve/handoff.py",
                                     "serve/autoscaler.py",
                                     "data/llm.py"))

    def check(self, mod: Module) -> Iterable[Finding]:
        yield from self._walk(mod, mod.tree, scope="<module>",
                              owned=False)

    def _walk(self, mod, node, scope, owned):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d = mod.func_directives(child)
                yield from self._walk(
                    mod, child, f"{scope}.{child.name}"
                    if scope != "<module>" else child.name,
                    d.get("owner") == "driver")
                continue
            if isinstance(child, ast.ClassDef):
                yield from self._walk(mod, child, child.name, False)
                continue
            if isinstance(child, ast.Call) and not owned:
                what = self._dispatch_callee(child)
                if what:
                    yield Finding(
                        mod.relpath, child.lineno, self.id,
                        f"device dispatch {what} in {scope}, which is "
                        f"not annotated '# rtlint: owner=driver' — only "
                        f"the engine driver thread may touch the "
                        f"device (TPU dispatch discipline)",
                        f"{scope}.{what}")
            yield from self._walk(mod, child, scope, owned)

    def _dispatch_callee(self, call: ast.Call) -> Optional[str]:
        a = _self_attr(call.func)
        if a in self.DISPATCH_ATTRS:
            return f"self.{a}(...)"
        if isinstance(call.func, ast.Call):
            inner = _terminal_name(call.func.func)
            if inner and inner.startswith("jit_"):
                return f"{inner}(...)(...)"
        return None


# ------------------------------------------------------------------ RT103
class RecompileHazardRule(Rule):
    """RT103: arguments flowing into ``lru_cache``'d jit factories
    (``jit_*`` call sites) or recorded ``static_argnums`` positions
    must be hashable and of bounded cardinality. Flags:

    - unhashable literals (list/set/dict displays, comprehensions) —
      ``lru_cache`` raises ``TypeError`` at runtime;
    - values derived from ``len(...)`` or ``.shape``/``.size`` —
      unbounded cardinality: every distinct value compiles (and caches)
      a fresh program, the silent-recompile failure mode the engine's
      bucket discipline exists to prevent.

    ``static_argnums`` tracking is module-local: an assignment
    ``x = jax.jit(f, static_argnums=(2,))`` makes position 2 of later
    ``x(...)`` calls subject to the same classifiers."""

    id = "RT103"
    summary = "recompile / lru_cache hazard at a jit factory call site"

    def check(self, mod: Module) -> Iterable[Finding]:
        static_map = self._collect_static_argnums(mod)
        for node, scope in _calls_with_scope(mod.tree):
            name = _terminal_name(node.func)
            args = []
            if name and name.startswith("jit_"):
                args = [(i, a) for i, a in enumerate(node.args)]
                args += [(k.arg, k.value) for k in node.keywords]
            else:
                key = _self_attr(node.func) or (
                    node.func.id if isinstance(node.func, ast.Name)
                    else None)
                positions = static_map.get(key or "")
                if positions:
                    args = [(i, a) for i, a in enumerate(node.args)
                            if i in positions]
                    name = key
            for pos, arg in args:
                bad = self._classify(arg)
                if bad:
                    yield Finding(
                        mod.relpath, arg.lineno, self.id,
                        f"argument {ast.unparse(arg)!r} (position "
                        f"{pos}) of {name}(...) is {bad}; static knobs "
                        f"must be hashable, bounded-cardinality values "
                        f"(config attrs, constants, bucket sizes)",
                        f"{scope}.{name}.arg{pos}")

    @staticmethod
    def _classify(arg) -> Optional[str]:
        if isinstance(arg, (ast.List, ast.Set, ast.Dict, ast.ListComp,
                            ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return ("an unhashable literal (lru_cache raises TypeError; "
                    "pass a tuple)")
        for w in ast.walk(arg):
            if isinstance(w, ast.Call) and \
                    isinstance(w.func, ast.Name) and w.func.id == "len":
                return ("derived from len(...) — unbounded cardinality, "
                        "one compiled program per distinct value")
            if isinstance(w, ast.Attribute) and w.attr in ("shape",
                                                           "size"):
                return (f"derived from .{w.attr} — unbounded "
                        f"cardinality, one compiled program per "
                        f"distinct value")
        return None

    @staticmethod
    def _collect_static_argnums(mod: Module) -> Dict[str, Set[int]]:
        out: Dict[str, Set[int]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if _terminal_name(call.func) != "jit":
                continue
            positions: Set[int] = set()
            for kw in call.keywords:
                if kw.arg != "static_argnums":
                    continue
                vals = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                for v in vals:
                    if isinstance(v, ast.Constant) and \
                            isinstance(v.value, int):
                        positions.add(v.value)
            if not positions:
                continue
            for t in node.targets:
                key = _self_attr(t) or (
                    t.id if isinstance(t, ast.Name) else None)
                if key:
                    out[key] = positions
        return out


# ------------------------------------------------------------------ RT104
class AsyncBlockingRule(Rule):
    """RT104: blocking calls inside ``async def`` bodies stall the
    whole event loop (every connection, every health probe). Flags
    ``time.sleep``, timeout-less ``.get()`` on queue-looking receivers,
    and timeout-less ``.result()``. Calls under an ``await`` expression
    are exempt (async protocols: ``await q.get()``,
    ``await asyncio.wait_for(q.get(), t)``), as are nested sync ``def``
    bodies (they run on executor threads)."""

    id = "RT104"
    summary = "blocking call inside an async def body"

    def check(self, mod: Module) -> Iterable[Finding]:
        sleep_names = self._time_sleep_names(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield from self._scan(mod, node, sleep_names)

    @staticmethod
    def _time_sleep_names(mod: Module) -> Set[str]:
        names = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "sleep":
                        names.add(a.asname or a.name)
        return names

    def _scan(self, mod: Module, fn: ast.AsyncFunctionDef, sleep_names):
        def rec(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return             # nested defs have their own context
            if isinstance(node, ast.Await):
                return             # awaited subtree: async protocol
            if isinstance(node, ast.Call):
                bad = self._blocking(node, sleep_names)
                if bad:
                    yield Finding(
                        mod.relpath, node.lineno, self.id,
                        f"{bad} inside 'async def {fn.name}' blocks the "
                        f"event loop; await an async equivalent, add a "
                        f"timeout, or move the call to an executor "
                        f"thread",
                        f"{fn.name}.{bad.split('(')[0]}")
            for c in ast.iter_child_nodes(node):
                yield from rec(c)
        for stmt in fn.body:
            yield from rec(stmt)

    @staticmethod
    def _blocking(call: ast.Call, sleep_names) -> Optional[str]:
        f = call.func
        kws = {k.arg for k in call.keywords}
        if isinstance(f, ast.Attribute) and f.attr == "sleep" and \
                isinstance(f.value, ast.Name) and f.value.id == "time":
            return "time.sleep(...)"
        if isinstance(f, ast.Name) and f.id in sleep_names:
            return f"{f.id}(...) [time.sleep]"
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "result" and not call.args and "timeout" not in kws:
            return "timeout-less .result()"
        if f.attr == "get" and "timeout" not in kws:
            if len(call.args) >= 2:
                # Queue.get(block, timeout) positional timeout — or a
                # dict.get(key, default); bounded either way.
                return None
            nonblocking = any(
                isinstance(a, ast.Constant) and a.value is False
                for a in call.args[:1]) or any(
                k.arg == "block" and isinstance(k.value, ast.Constant)
                and k.value.value is False for k in call.keywords)
            if call.args and not all(
                    isinstance(a, ast.Constant) and a.value is True
                    for a in call.args[:1]):
                return None        # dict.get(key) shape
            recv = f.value
            rn = recv.attr if isinstance(recv, ast.Attribute) else (
                recv.id if isinstance(recv, ast.Name) else "")
            if not nonblocking and QUEUEISH_RE.search(rn or ""):
                return f"timeout-less {rn}.get()"
        return None


# ------------------------------------------------------------------ RT105
class RetryableWireRule(ProjectRule):
    """RT105: the router re-picks on typed pushback two ways — the
    ``retryable = True`` class attribute (local raises) and the
    ``_PUSHBACK_CAUSES`` name tuple (errors that crossed the wire as
    ``TaskError``, where only ``cause_type`` survives). Both must agree:

    - a name listed in ``_PUSHBACK_CAUSES`` whose class does not set
      ``retryable = True`` breaks the local-raise path;
    - an exception class setting ``retryable = True`` that is missing
      from ``_PUSHBACK_CAUSES`` breaks the cross-wire path.

    Inheritance is resolved within the analyzed file set."""

    id = "RT105"
    summary = "retryable pushback class out of sync with _PUSHBACK_CAUSES"

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        causes: Set[str] = set()
        cause_sites: List[Tuple[Module, int]] = []
        classes: Dict[str, Tuple[Module, ast.ClassDef]] = {}
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and \
                                t.id == "_PUSHBACK_CAUSES" and \
                                isinstance(node.value,
                                           (ast.Tuple, ast.List)):
                            for e in node.value.elts:
                                if isinstance(e, ast.Constant) and \
                                        isinstance(e.value, str):
                                    causes.add(e.value)
                            cause_sites.append((mod, node.lineno))
                elif isinstance(node, ast.ClassDef):
                    classes.setdefault(node.name, (mod, node))
        if not cause_sites:
            return                 # nothing to check against
        for name in sorted(causes):
            ent = classes.get(name)
            if ent is None:
                continue           # defined outside the analyzed set
            mod, node = ent
            if self._retryable(name, classes) is not True:
                yield Finding(
                    mod.relpath, node.lineno, self.id,
                    f"{name} is listed in _PUSHBACK_CAUSES but does not "
                    f"set 'retryable = True' — a LOCAL raise of it "
                    f"would not be re-picked (only the wire-crossed "
                    f"TaskError would)", name)
        for name, (mod, node) in sorted(classes.items()):
            if name in causes:
                continue
            if self._retryable(name, classes) is not True:
                continue
            if not self._looks_like_exception(name, classes):
                continue
            yield Finding(
                mod.relpath, node.lineno, self.id,
                f"{name} sets 'retryable = True' but is not listed in "
                f"_PUSHBACK_CAUSES — after crossing the replica wire as "
                f"a TaskError only its cause_type name survives, so the "
                f"router would bury the replica instead of re-picking",
                name)

    @classmethod
    def _retryable(cls, name, classes, seen=None) -> Optional[bool]:
        seen = seen or set()
        if name in seen or name not in classes:
            return None
        seen.add(name)
        _mod, node = classes[name]
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == "retryable" \
                            and isinstance(stmt.value, ast.Constant):
                        return bool(stmt.value.value)
        for base in node.bases:
            bn = _terminal_name(base)
            got = cls._retryable(bn, classes, seen) if bn else None
            if got is not None:
                return got
        return None

    @classmethod
    def _looks_like_exception(cls, name, classes, seen=None) -> bool:
        seen = seen or set()
        if name in seen:
            return False
        seen.add(name)
        if name.endswith(("Error", "Exception")):
            return True
        if name not in classes:
            return False
        _mod, node = classes[name]
        return any(
            (bn := _terminal_name(base)) and (
                bn.endswith(("Error", "Exception"))
                or cls._looks_like_exception(bn, classes, seen))
            for base in node.bases)


# ------------------------------------------------------------------ RT106
class MetricNameRule(Rule):
    """RT106: the prometheus naming conventions, applied statically at
    every ``Counter(...)`` / ``Gauge(...)`` / ``Histogram(...)``
    construction site with a literal name. Shares ONE implementation
    (:func:`tools.rtlint.metrics_names.lint_metric_name`) with the
    runtime ``MetricsRegistry.register`` lint, so the static and
    runtime checks cannot drift. ``collections.Counter`` is excluded
    via the module's imports."""

    id = "RT106"
    summary = "metric name violates prometheus conventions"

    KINDS = {"Counter": "counter", "Gauge": "gauge",
             "Histogram": "histogram"}

    def check(self, mod: Module) -> Iterable[Finding]:
        collections_names = self._collections_imports(mod)
        for node, scope in _calls_with_scope(mod.tree):
            f = node.func
            name = _terminal_name(f)
            if name not in self.KINDS:
                continue
            if isinstance(f, ast.Name) and f.id in collections_names:
                continue
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "collections":
                continue
            metric = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                metric = node.args[0].value
            else:
                for k in node.keywords:
                    if k.arg == "name" and \
                            isinstance(k.value, ast.Constant) and \
                            isinstance(k.value.value, str):
                        metric = k.value.value
            if metric is None:
                continue
            for problem in lint_metric_name(metric, self.KINDS[name]):
                yield Finding(mod.relpath, node.lineno, self.id,
                              problem, metric)

    @staticmethod
    def _collections_imports(mod: Module) -> Set[str]:
        out = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "collections":
                for a in node.names:
                    out.add(a.asname or a.name)
        return out


# ------------------------------------------------------------------ RT107
class SwallowedExceptRule(Rule):
    """RT107: exception hygiene in the serve control loops. Flags

    - bare ``except:`` that does not re-raise (it catches
      ``SystemExit``/``KeyboardInterrupt`` and can wedge a teardown);
    - broad handlers (``Exception``/``BaseException``) whose body only
      ``pass``/``continue``s, with NO justification comment — a control
      loop that silently eats its own failures is how a dead driver
      looks healthy.

    A comment on the ``except`` line (or the first body line) counts as
    the justification; the repo convention is
    ``except Exception:  # noqa: BLE001 - <why swallowing is safe>``.
    Scoped to ``ray_tpu/serve/`` — the driver/controller/replica
    control loops this rule exists for — plus ``data/llm.py``, the
    offline batch-inference pipeline driver (ISSUE 11), which runs the
    same submit/collect/commit control loop against the engines."""

    id = "RT107"
    summary = "bare or silently-swallowed except in a serve control loop"

    def applies(self, mod: Module) -> bool:
        return "serve/" in mod.relpath \
            or mod.relpath.endswith("data/llm.py")

    def check(self, mod: Module) -> Iterable[Finding]:
        for node, scope in _nodes_with_scope(mod.tree, ast.ExceptHandler):
            bare = node.type is None
            broad = bare or (
                _terminal_name(node.type) in ("Exception", "BaseException")
                if not isinstance(node.type, ast.Tuple) else False)
            if not broad:
                continue
            reraises = any(isinstance(s, ast.Raise) and s.exc is None
                           for s in ast.walk(node))
            if bare and not reraises:
                yield Finding(
                    mod.relpath, node.lineno, self.id,
                    f"bare 'except:' in {scope} (catches SystemExit/"
                    f"KeyboardInterrupt); name the exception type",
                    f"{scope}.bare_except")
                continue
            swallow = all(isinstance(s, (ast.Pass, ast.Continue))
                          for s in node.body)
            if not swallow or bare:
                continue
            justified = node.lineno in mod.comments or \
                (node.body and node.body[0].lineno in mod.comments)
            if not justified:
                yield Finding(
                    mod.relpath, node.lineno, self.id,
                    f"broad except in {scope} swallows the error with "
                    f"no justification comment; handle it, narrow the "
                    f"type, or comment why dropping it is safe",
                    f"{scope}.swallowed_except")


# ------------------------------------------------------------------ RT108
class AnnotationDriftRule(Rule):
    """RT108: annotation drift — an ``owner=``/``holds=`` contract
    whose named lock or driver registration does not exist. The
    annotations are enforced BOTH statically (RT101/RT102 trust them)
    and dynamically (tools/rtsan asserts them at runtime), so a
    dangling name is a contract nobody can check: it was true the day
    it was written and rotted as the class grew. Flags:

    - ``holds=<name>`` on a method where no method of the enclosing
      class ever assigns ``self.<name>`` — the promised lock attribute
      does not exist (rtsan escalates this to a hard error at runtime);
    - in the driver-owned files (RT102's path scope, where rtsan binds
      thread ownership) a class with ``owner=driver`` methods but no
      method annotated ``# rtlint: entry=driver`` — nothing registers
      WHICH thread is the driver, so the ownership contract is
      unanchored both for the reader and for the runtime check.
    """

    id = "RT108"
    summary = "owner=/holds= annotation names a lock/registration that does not exist"

    ENTRY_SCOPE = ("serve/engine.py", "serve/draft.py",
                   "serve/autoscaler.py", "data/llm.py")

    def check(self, mod: Module) -> Iterable[Finding]:
        in_entry_scope = mod.relpath.endswith(self.ENTRY_SCOPE)
        for cls in [n for n in ast.walk(mod.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            assigned = self._assigned_attrs(methods) \
                | self._class_body_attrs(cls)
            owners, entries = [], []
            for m in methods:
                d = mod.func_directives(m)
                if d.get("owner") == "driver":
                    owners.append(m)
                if d.get("entry") == "driver":
                    entries.append(m)
                for name in (h.strip() for h in
                             d.get("holds", "").split(",") if h.strip()):
                    if name not in assigned:
                        yield Finding(
                            mod.relpath, m.lineno, self.id,
                            f"{cls.name}.{m.name} is annotated "
                            f"'holds={name}' but no method of "
                            f"{cls.name} assigns self.{name} — the "
                            f"contract names a lock that does not "
                            f"exist; fix the name or drop the "
                            f"annotation",
                            f"{cls.name}.{m.name}.holds.{name}")
            if in_entry_scope and owners and not entries:
                m0 = owners[0]
                yield Finding(
                    mod.relpath, m0.lineno, self.id,
                    f"{cls.name} has owner=driver methods (first: "
                    f"{m0.name}) but no method annotated "
                    f"'# rtlint: entry=driver' — nothing registers the "
                    f"driver thread, so neither reviewers nor the "
                    f"runtime sanitizer can tell who the owner is; "
                    f"annotate the method whose caller becomes the "
                    f"driver (the thread target / the consume loop)",
                    f"{cls.name}.driver_entry")

    @staticmethod
    def _class_body_attrs(cls: ast.ClassDef) -> Set[str]:
        """Class-level attribute assignments (``class X: _lock = ...``)
        — reachable as ``self.<name>`` and therefore valid ``holds=``
        targets."""
        out: Set[str] = set()
        for node in cls.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    @staticmethod
    def _assigned_attrs(methods) -> Set[str]:
        """Every ``self.X`` assigned anywhere in the class's methods —
        including tuple/list unpacking targets. Lexical only: an
        attribute assigned by a BASE class is invisible here (suppress
        with a justification in that rare case)."""
        out: Set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                while targets:
                    t = targets.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        targets.extend(t.elts)
                        continue
                    if isinstance(t, ast.Starred):
                        targets.append(t.value)
                        continue
                    a = _self_attr(t)
                    if a:
                        out.add(a)
        return out


# ------------------------------------------------------------------ RT112
class DriverEmitRule(Rule):
    """RT112: flight-recorder emission inside ``owner=driver`` hot
    loops must go through the rate-capped driver helper.

    The driver loop dispatches per token; a plain ``events.emit`` there
    is a ring-storm hazard — one busy stream floods the ring and the
    post-mortem loses the interesting tail. The events module ships a
    dedicated helper, ``driver_emit`` (``ray_tpu._private.events``),
    with a tighter per-kind rate cap sized for dispatch-frequency call
    sites; driver-annotated methods must use it.

    Lexically: any call whose terminal name is ``emit`` (``emit(...)``,
    ``_events.emit(...)``, ``events.emit(...)``) inside a function
    annotated ``# rtlint: owner=driver`` is flagged; ``driver_emit``
    (under any import alias ending in ``driver_emit``) is the
    compliant spelling. Code outside driver-owned functions emits at
    control-plane frequency and keeps the plain helper."""

    id = "RT112"
    summary = "plain events.emit inside an owner=driver hot loop"

    def check(self, mod: Module) -> Iterable[Finding]:
        yield from self._walk(mod, mod.tree, scope="<module>",
                              owned=False)

    def _walk(self, mod, node, scope, owned):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d = mod.func_directives(child)
                yield from self._walk(
                    mod, child, f"{scope}.{child.name}"
                    if scope != "<module>" else child.name,
                    d.get("owner") == "driver")
                continue
            if isinstance(child, ast.ClassDef):
                yield from self._walk(mod, child, child.name, False)
                continue
            if isinstance(child, ast.Call) and owned \
                    and _terminal_name(child.func) == "emit":
                yield Finding(
                    mod.relpath, child.lineno, self.id,
                    f"plain events.emit in {scope}, which is annotated "
                    f"'# rtlint: owner=driver' — the driver loop runs "
                    f"per dispatch, so emission there must use the "
                    f"rate-capped driver_emit helper "
                    f"(ray_tpu._private.events) or a storm floods the "
                    f"ring and the crash tail is lost",
                    f"{scope}.emit")
            yield from self._walk(mod, child, scope, owned)


# ----------------------------------------------------------------- shared
def _nodes_with_scope(tree, node_type):
    """Yield (node, qualified_scope) for every ``node_type`` in the
    tree, tracking enclosing class/function names."""
    def rec(node, scope):
        for child in ast.iter_child_nodes(node):
            s = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                s = f"{scope}.{child.name}" if scope != "<module>" \
                    else child.name
            if isinstance(child, node_type):
                yield child, scope
            yield from rec(child, s)
    yield from rec(tree, "<module>")


def _calls_with_scope(tree):
    yield from _nodes_with_scope(tree, ast.Call)


from .flow import (InterprocContractRule, ProgramBudgetRule,  # noqa: E402
                   SyncPointRule)

ALL_RULES: Tuple[Rule, ...] = (
    LockGuardRule(), DriverOwnershipRule(), RecompileHazardRule(),
    AsyncBlockingRule(), RetryableWireRule(), MetricNameRule(),
    SwallowedExceptRule(), AnnotationDriftRule(), ProgramBudgetRule(),
    InterprocContractRule(), SyncPointRule(), DriverEmitRule())

RULE_TABLE = {r.id: r for r in ALL_RULES}
