"""THE shared annotation loader: one parse consumed by both the static
analyzer (rtlint) and the runtime sanitizer (tools/rtsan).

rtlint's directives are *contracts*, not comments: ``owner=driver``
promises a method only ever runs on its object's driver thread,
``holds=<lock>`` promises every caller enters with ``self.<lock>``
held, and ``entry=driver`` marks the method whose CALLER registers as
the driver thread (rtsan binds ownership there; RT108 requires one per
driver-owned class). A contract checked by two tools must be parsed by
ONE loader — if the static and dynamic sides ever read the same
comment differently, an annotation could pass review while enforcing
nothing — so this module owns the grammar and both
``tools/rtlint/core.py`` and ``tools/rtsan/core.py`` import it
(identity pinned by ``tests/test_rtsan.py``).

Grammar (one comment, any number of ``key=value`` tokens separated by
whitespace; prose after the tokens is ignored so directives can carry a
justification)::

    # rtlint: disable=RT101,RT104   <why this is safe>
    # rtlint: owner=driver entry=driver
    # rtlint: holds=_lock           <every caller holds self._lock>
    # rtlint: sync-ok=ttft          <why this host sync is deliberate>
    # rtsan: disable=RS104          <why this blocking call is safe>

One directive key escapes the ``k=v`` token grammar:
``program-budget:`` (rtflow's RT109 compiled-program-budget audit)
takes the REST of the comment as a symbolic expression, because budget
expressions contain spaces::

    # rtlint: program-budget: len(prompt_buckets) + 3

The expression grammar is integer literals, ``len(<name>)`` atoms, and
``+`` / ``*`` (see :func:`tools.rtlint.flow.parse_budget`); a budget
comment carries no other directives and no prose.

Placement: a directive on a line (or the line directly above, for
wrapped statements) attaches to that line; a directive anywhere on a
(possibly multi-line) ``def`` signature — INCLUDING its decorator
lines — or on the line directly above the first decorator, applies to
the whole function.
"""
from __future__ import annotations

import ast
import functools
import io
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


#: THE ``self.<attr>`` naming convention for locks, shared by RT101
#: guard inference, rtflow's call-graph lock context (RT110), and
#: rtsan's annotation-coverage summary — one definition so the static
#: and runtime tools can never disagree about what counts as a lock.
LOCKISH_RE = re.compile(r"lock|cond|mutex", re.I)


@functools.lru_cache(maxsize=8)
def _tag_re(tag: str) -> "re.Pattern":
    return re.compile(re.escape(tag) + r":\s*(.*)")


#: The one directive whose value is the whole comment remainder (a
#: symbolic expression with spaces), not a whitespace-split token.
BUDGET_KEY = "program-budget"
_BUDGET_RE = re.compile(re.escape(BUDGET_KEY) + r":\s*(.+?)\s*$")


def parse_directives(comment: str, tag: str = "rtlint") -> Dict[str, str]:
    """``# <tag>: k=v[,v2] [k=v ...] prose`` -> ``{k: v[,v2]}``. Tokens
    split on whitespace ONLY, so comma-joined values
    (``disable=RT101,RT104``) stay intact; the first non ``k=v`` token
    starts the prose. ``# <tag>: program-budget: <expr>`` is special:
    the whole remainder is the (space-containing) budget expression.
    Non-directive comments return ``{}``."""
    m = _tag_re(tag).search(comment)
    if not m:
        return {}
    b = _BUDGET_RE.match(m.group(1))
    if b:
        return {BUDGET_KEY: b.group(1)}
    out: Dict[str, str] = {}
    for tok in m.group(1).split():
        if "=" not in tok:
            break      # first non k=v token starts the prose
        k, _, v = tok.partition("=")
        if not k or not v:
            break
        out[k] = out[k] + "," + v if k in out else v
    return out


def comment_map(source: str) -> Dict[int, str]:
    """line -> full comment text (without the leading ``#``), built
    with ``tokenize`` so comments survive into analysis — ``ast`` alone
    drops them. Partial on TokenError (the caller already parsed)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string.lstrip("#")
    except (tokenize.TokenError, IndentationError):
        pass
    return out


def directive_map(source: str, tag: str = "rtlint"
                  ) -> Dict[int, Dict[str, str]]:
    """line -> parsed directives on that line (empty lines omitted)."""
    return {ln: d for ln, c in comment_map(source).items()
            if (d := parse_directives(c, tag))}


def line_directives(directives: Dict[int, Dict[str, str]],
                    line: int) -> Dict[str, str]:
    """Directives attached to ``line``: on the line itself or the line
    directly above (wrapped statements)."""
    out = dict(directives.get(line - 1, ()))
    out.update(directives.get(line, ()))
    return out


def func_directives(directives: Dict[int, Dict[str, str]],
                    funcdef) -> Dict[str, str]:
    """Directives anywhere on the (possibly multi-line) ``def``
    signature — including its DECORATOR lines, so ``# rtlint:
    disable=..`` next to ``@decorator`` covers the decorated ``def`` —
    or on the line directly above the first decorator.

    ``funcdef.lineno`` is the ``def`` line (decorators carry their own
    linenos), so the scan starts at the first decorator when one
    exists; without the decorator span a directive on a decorator line
    only covered the def when it HAPPENED to be the line directly
    above it (single, single-line decorator)."""
    deco = getattr(funcdef, "decorator_list", None) or ()
    start = min([funcdef.lineno] + [d.lineno for d in deco])
    out = dict(directives.get(start - 1, ()))
    sig_end = (funcdef.body[0].lineno - 1 if funcdef.body
               else funcdef.lineno)
    for ln in range(start, sig_end + 1):
        out.update(directives.get(ln, ()))
    return out


@dataclass(frozen=True)
class FuncAnn:
    """One annotated function: the contract rtsan enforces at runtime
    and RT108 checks statically."""
    cls: Optional[str]     # dotted enclosing-class path; None = module
    name: str
    lineno: int
    end_lineno: int
    owner: Optional[str]   # owner=<who> (``driver``)
    holds: Tuple[str, ...]  # holds=<lock[,lock2]> attribute names
    entry: Optional[str]   # entry=<who>: caller registers as the owner
    directives: Dict[str, str] = None  # the full directive dict

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


def load_annotations(source: str, tag: str = "rtlint") -> List[FuncAnn]:
    """Parse ``source`` and return every function carrying an
    ``owner=`` / ``holds=`` / ``entry=`` contract. Raises SyntaxError
    on unparseable source (callers gate)."""
    tree = ast.parse(source)
    directives = directive_map(source, tag)
    out: List[FuncAnn] = []

    def rec(node, cls_path: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                nested = (f"{cls_path}.{child.name}" if cls_path
                          else child.name)
                rec(child, nested)
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                d = func_directives(directives, child)
                holds = tuple(h.strip() for h in
                              d.get("holds", "").split(",") if h.strip())
                owner = d.get("owner")
                entry = d.get("entry")
                if owner or holds or entry:
                    out.append(FuncAnn(
                        cls=cls_path, name=child.name,
                        lineno=child.lineno,
                        end_lineno=child.end_lineno or child.lineno,
                        owner=owner, holds=holds, entry=entry,
                        directives=d))
                rec(child, cls_path)  # nested defs share the class path
                continue
            rec(child, cls_path)

    rec(tree, None)
    return out
