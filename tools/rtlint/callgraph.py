"""rtflow call graph: a project-wide, AST-derived call graph over the
analyzed file set (ISSUE 15).

rtlint's per-module rules stop at function boundaries — a ``holds=``
contract, a driver-ownership annotation, or a config-derived value
evaporates the moment it crosses a call. This module builds the graph
those checks propagate over. Resolution is *lexical*, like every other
rtlint analysis, and resolves exactly the idioms this repo uses:

- **module functions**: bare-name calls to defs in the same module, and
  through ``from x import f`` / ``import x as m`` → ``m.f(...)``
  (relative imports resolved against the module's own dotted path; only
  modules inside the analyzed set resolve);
- **methods through self**: ``self.m(...)`` against the enclosing class
  and its bases (bases matched by terminal name across the analyzed
  set, first definition wins — the same convention RT105 uses);
- **module aliases on self**: ``self._gd.f(...)`` where some method
  assigned ``self._gd = <imported module>`` (the engine's
  ``self._gd = gpt_decode`` idiom);
- **constructors**: ``Cls(...)`` → ``Cls.__init__``;
- **driver registration**: ``threading.Thread(target=self._run)`` (and
  any ``*Thread(target=...)``) becomes an edge of ``kind="thread"`` —
  the repo's driver-thread registration idiom, which RT110 treats as
  the legitimate entry into ``owner=driver`` code.

Every edge records the **lock context** at the call site: the
``self.<lock>`` attributes (names matching ``lock|cond|mutex``) whose
``with`` blocks lexically enclose the call, plus the caller's own
``holds=`` contract and any lock it manually ``.acquire()``s — the
exact leniencies RT101 already grants, made transitive.

What does NOT resolve (and is deliberately skipped, never guessed):
calls through arbitrary objects (``self._drafter.propose(...)`` where
``_drafter``'s type is a runtime choice), calls through containers, and
anything behind ``getattr``. Unresolved calls produce no edges; rules
built on this graph check only what resolved, so precision errs toward
false negatives, not noise.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: LOCKISH_RE is the shared lock-naming convention (RT101's) — one
#: definition in annotations so rtflow and rtsan can never disagree.
from .annotations import LOCKISH_RE
from .core import Module


def self_attr(node) -> Optional[str]:
    """``self.X`` -> ``'X'`` (else None)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def terminal_name(func) -> Optional[str]:
    """Rightmost name of a call target: ``a.b.c(...)`` -> ``'c'``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


@dataclass
class FuncNode:
    """One function/method in the analyzed set."""

    key: str                      # "<relpath>::<Qual.name>"
    mod: Module
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    cls: Optional[str]            # enclosing class qualname, or None
    name: str
    directives: Dict[str, str] = field(default_factory=dict)

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class ClassNode:
    key: str                      # "<relpath>::<Qual>"
    mod: Module
    node: ast.ClassDef
    bases: Tuple[str, ...]        # terminal base names
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fkey
    #: self.<attr> = <value> assignment sites: attr -> [(fkey, value)]
    attr_assigns: Dict[str, List[Tuple[str, ast.AST]]] = \
        field(default_factory=dict)
    #: self.<attr> = <imported module> aliases: attr -> module relpath
    module_aliases: Dict[str, str] = field(default_factory=dict)


@dataclass
class CallEdge:
    """One resolved call site. ``locks`` is the caller-side lock
    context: lexical ``with self.<lock>`` blocks enclosing the site,
    the caller's own ``holds=``, and locks the caller manually
    acquires anywhere in its body (RT101's leniency, transitive)."""

    caller: Optional[str]         # FuncNode key; None = module level
    callee: str                   # FuncNode key
    mod: Module                   # the CALLER's module (finding anchor)
    line: int
    call: ast.Call
    locks: frozenset = frozenset()
    kind: str = "call"            # "call" | "thread"


def _dotted(relpath: str) -> str:
    """``ray_tpu/serve/engine.py`` -> ``ray_tpu.serve.engine`` (and
    ``pkg/__init__.py`` -> ``pkg``)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class CallGraph:
    """Build with :meth:`build`; query via the indexes below."""

    def __init__(self):
        self.funcs: Dict[str, FuncNode] = {}
        self.classes: Dict[str, ClassNode] = {}     # by key
        self.class_by_name: Dict[str, ClassNode] = {}  # terminal, 1st wins
        self.edges: List[CallEdge] = []
        self.edges_to: Dict[str, List[CallEdge]] = {}
        self.edges_from: Dict[str, List[CallEdge]] = {}
        #: module relpath -> {local name -> ("mod", relpath) |
        #:                    ("obj", relpath, objname)}
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        self._by_dotted: Dict[str, str] = {}        # dotted -> relpath

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, mods: Sequence[Module]) -> "CallGraph":
        g = cls()
        for m in mods:
            g._by_dotted[_dotted(m.relpath)] = m.relpath
        for m in mods:
            g._index_module(m)
        for m in mods:
            g._collect_imports(m)
        for m in mods:
            g._collect_aliases(m)
        for m in mods:
            g._collect_edges(m)
        for e in g.edges:
            g.edges_to.setdefault(e.callee, []).append(e)
            if e.caller:
                g.edges_from.setdefault(e.caller, []).append(e)
        return g

    def _index_module(self, mod: Module):
        def rec(node, cls_path: Optional[str], cnode: Optional[ClassNode]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = (f"{cls_path}.{child.name}" if cls_path
                            else child.name)
                    ck = f"{mod.relpath}::{qual}"
                    cn = ClassNode(
                        key=ck, mod=mod, node=child,
                        bases=tuple(b for b in
                                    (terminal_name(x) for x in child.bases)
                                    if b))
                    self.classes[ck] = cn
                    self.class_by_name.setdefault(child.name, cn)
                    rec(child, qual, cn)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = (f"{cls_path}.{child.name}" if cls_path
                            else child.name)
                    fk = f"{mod.relpath}::{qual}"
                    fn = FuncNode(key=fk, mod=mod, node=child,
                                  cls=cls_path, name=child.name,
                                  directives=mod.func_directives(child))
                    # A nested def shadowing its enclosing method's
                    # name keeps the method (indexed first) as the key.
                    self.funcs.setdefault(fk, fn)
                    if cnode is not None:
                        cnode.methods.setdefault(child.name, fk)
                        self._collect_attr_assigns(cnode, fk, child)
                    # Nested defs keep the class path (same convention
                    # as the annotations loader).
                    rec(child, cls_path, cnode)
                    continue
                rec(child, cls_path, cnode)

        rec(mod.tree, None, None)

    @staticmethod
    def _collect_attr_assigns(cnode: ClassNode, fkey: str, method):
        for node in ast.walk(method):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None:
                continue
            for t in targets:
                a = self_attr(t)
                if a:
                    cnode.attr_assigns.setdefault(a, []).append(
                        (fkey, value))

    def _collect_imports(self, mod: Module):
        table: Dict[str, Tuple] = {}
        own_pkg = _dotted(mod.relpath).rsplit(".", 1)[0] \
            if "." in _dotted(mod.relpath) else ""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    rel = self._by_dotted.get(a.name)
                    if rel and (a.asname or "." not in a.name):
                        # Without an alias, "import a.b" binds "a", not
                        # "a.b" — only top-level imports resolve bare.
                        table[a.asname or a.name] = ("mod", rel)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = own_pkg.split(".") if own_pkg else []
                    parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    # "from m import x": x is a submodule OR an object.
                    sub = self._by_dotted.get(f"{base}.{a.name}"
                                              if base else a.name)
                    if sub:
                        table[a.asname or a.name] = ("mod", sub)
                        continue
                    rel = self._by_dotted.get(base)
                    if rel:
                        table[a.asname or a.name] = ("obj", rel, a.name)
        self.imports[mod.relpath] = table

    def _collect_aliases(self, mod: Module):
        """``self.X = <imported module>`` assignments (the engine's
        ``self._gd = gpt_decode``): X becomes a module alias for
        ``self.X.f(...)`` resolution."""
        table = self.imports.get(mod.relpath, {})
        for cn in self.classes.values():
            if cn.mod is not mod:
                continue
            for attr, sites in cn.attr_assigns.items():
                for _fk, value in sites:
                    if isinstance(value, ast.Name):
                        ent = table.get(value.id)
                        if ent and ent[0] == "mod":
                            cn.module_aliases[attr] = ent[1]

    # --------------------------------------------------------- resolution
    def _module_func(self, relpath: str, name: str) -> Optional[str]:
        key = f"{relpath}::{name}"
        if key in self.funcs:
            return key
        ck = f"{relpath}::{name}"
        cn = self.classes.get(ck)
        if cn is not None:
            return cn.methods.get("__init__")
        return None

    def method_of(self, cnode: Optional[ClassNode],
                  name: str, _seen=None) -> Optional[str]:
        """Method lookup through the class and its bases (terminal-name
        matched across the analyzed set)."""
        if cnode is None:
            return None
        _seen = _seen or set()
        if cnode.key in _seen:
            return None
        _seen.add(cnode.key)
        got = cnode.methods.get(name)
        if got:
            return got
        for b in cnode.bases:
            got = self.method_of(self.class_by_name.get(b), name, _seen)
            if got:
                return got
        return None

    def resolve_call(self, mod: Module, cnode: Optional[ClassNode],
                     call: ast.Call) -> Optional[str]:
        f = call.func
        table = self.imports.get(mod.relpath, {})
        if isinstance(f, ast.Name):
            got = self._module_func(mod.relpath, f.id)
            if got:
                return got
            ent = table.get(f.id)
            if ent and ent[0] == "obj":
                return self._module_func(ent[1], ent[2])
            if ent and ent[0] == "mod":
                return None
            cn = self.class_by_name.get(f.id)
            if cn is not None and f.id[:1].isupper():
                return cn.methods.get("__init__")
            return None
        if isinstance(f, ast.Attribute):
            a = self_attr(f.value)
            if a is not None and cnode is not None:
                alias = cnode.module_aliases.get(a)
                if alias:
                    return self._module_func(alias, f.attr)
                return None
            a = self_attr(f)
            if a is not None:
                return self.method_of(cnode, a)
            if isinstance(f.value, ast.Name):
                ent = table.get(f.value.id)
                if ent and ent[0] == "mod":
                    return self._module_func(ent[1], f.attr)
        return None

    # ------------------------------------------------------ edge collection
    @staticmethod
    def _acquired_locks(fn) -> frozenset:
        out = set()
        for w in ast.walk(fn):
            if isinstance(w, ast.Call) and \
                    isinstance(w.func, ast.Attribute) and \
                    w.func.attr == "acquire":
                a = self_attr(w.func.value)
                if a and LOCKISH_RE.search(a):
                    out.add(a)
        return frozenset(out)

    def _collect_edges(self, mod: Module):
        def visit(node, caller: Optional[FuncNode],
                  cnode: Optional[ClassNode], cls_qual: Optional[str],
                  locks: frozenset):
            if isinstance(node, ast.ClassDef):
                qual = f"{cls_qual}.{node.name}" if cls_qual \
                    else node.name
                cn = self.classes.get(f"{mod.relpath}::{qual}")
                for c in ast.iter_child_nodes(node):
                    visit(c, None, cn, qual, frozenset())
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{cls_qual}.{node.name}" if cls_qual
                        else node.name)
                fn = self.funcs.get(f"{mod.relpath}::{qual}")
                base = frozenset(
                    h.strip() for h in
                    (fn.directives.get("holds", "") if fn else ""
                     ).split(",") if h.strip()) \
                    | self._acquired_locks(node)
                for c in ast.iter_child_nodes(node):
                    visit(c, fn or caller, cnode, cls_qual,
                          frozenset(base))
                return
            if isinstance(node, ast.With):
                held = set(locks)
                for item in node.items:
                    a = self_attr(item.context_expr)
                    if a and LOCKISH_RE.search(a):
                        held.add(a)
                    visit(item.context_expr, caller, cnode, cls_qual,
                          locks)
                for c in node.body:
                    visit(c, caller, cnode, cls_qual, frozenset(held))
                return
            if isinstance(node, ast.Call):
                self._edge_for_call(mod, caller, cnode, node, locks)
            for c in ast.iter_child_nodes(node):
                visit(c, caller, cnode, cls_qual, locks)

        for top in ast.iter_child_nodes(mod.tree):
            visit(top, None, None, None, frozenset())

    def _edge_for_call(self, mod: Module, caller: Optional[FuncNode],
                       cnode: Optional[ClassNode], call: ast.Call,
                       locks: frozenset):
        tname = terminal_name(call.func)
        if tname and tname.endswith("Thread"):
            for kw in call.keywords:
                if kw.arg != "target":
                    continue
                target = None
                a = self_attr(kw.value)
                if a is not None:
                    target = self.method_of(cnode, a)
                elif isinstance(kw.value, ast.Name):
                    target = self._module_func(mod.relpath, kw.value.id)
                if target:
                    self.edges.append(CallEdge(
                        caller=caller.key if caller else None,
                        callee=target, mod=mod, line=call.lineno,
                        call=call, locks=locks, kind="thread"))
            return
        callee = self.resolve_call(mod, cnode, call)
        if callee:
            self.edges.append(CallEdge(
                caller=caller.key if caller else None, callee=callee,
                mod=mod, line=call.lineno, call=call, locks=locks))
