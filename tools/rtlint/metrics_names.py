"""Prometheus metric-name lint — THE shared implementation.

This is the single source of truth for the metric naming conventions
enforced in two places that must never drift:

- at runtime, ``ray_tpu._private.metrics.MetricsRegistry.register``
  lints every instrument as it is registered (warn by default, raise
  under ``RT_METRICS_STRICT``);
- statically, rtlint rule **RT106** applies the same function to every
  ``Counter(...)`` / ``Gauge(...)`` / ``Histogram(...)`` construction
  site it can see, so a bad name fails CI before the instrument ever
  registers.

Deliberately dependency-free (stdlib ``re`` only): the runtime imports
this module from inside ``ray_tpu`` and must not pull the rest of the
analyzer in with it.
"""
from __future__ import annotations

import re
from typing import List

#: Prometheus metric-name grammar (data model spec).
METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: A histogram whose name suggests it measures time must carry the
#: canonical ``_seconds`` unit suffix.
DURATION_HINTS = ("duration", "latency", "wait", "elapsed", "_time",
                  "ttft", "tpot")


def lint_metric_name(name: str, kind: str) -> List[str]:
    """Prometheus naming-convention problems for an instrument, or []."""
    problems = []
    if not METRIC_NAME_RE.match(name):
        problems.append(
            f"metric name {name!r} does not match the prometheus naming "
            f"regex {METRIC_NAME_RE.pattern}")
    if kind == "counter" and not name.endswith("_total"):
        problems.append(
            f"counter {name!r} must end in '_total' (prometheus counter "
            f"convention)")
    if kind == "histogram" and not name.endswith("_seconds") and \
            any(h in name for h in DURATION_HINTS):
        problems.append(
            f"duration histogram {name!r} must end in '_seconds' "
            f"(prometheus base-unit convention)")
    return problems
