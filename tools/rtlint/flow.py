"""rtflow: interprocedural dataflow over the rtlint call graph, and the
three rules built on it (ISSUE 15 tentpole).

Every engine PR since the continuous-batching engine has hand-audited
one invariant — the compiled-program set stays bounded
(``len(prompt_buckets) + 1`` base, ``+1`` spec-decode verify, ``+2``
KV-handoff export/import) — because one stray request-varying Python
value reaching a jit trace key silently multiplies XLA compiles. RT103
checks the hazard intra-procedurally; the contracts evaporate at the
first helper boundary. rtflow makes three of them machine-checked
project-wide:

RT109  **static compiled-program-budget audit.** Factory entrypoints
       declare ``# rtlint: program-budget: <expr>``; rtflow computes an
       upper bound on the distinct trace keys reachable from all call
       sites and fails when the bound exceeds the declaration or is
       unbounded (a request-varying value reaches a static factory
       argument or a dispatch-time array shape).
RT110  **interprocedural lock/driver contracts.** ``holds=`` /
       ``owner=driver`` annotations are checked at every resolved call
       EDGE: a ``holds=L`` method entered on an edge that does not hold
       ``L``, a ``*_locked`` method entered with no lock at all, or an
       ``owner=driver`` method called from non-driver code (thread
       registration and ``entry=driver`` excepted) — the static twin of
       rtsan's RS102/RS103, one hop earlier.
RT111  **host-device sync points.** In the driver-dispatch files, every
       synchronizing use of a dispatch result (``np.asarray`` /
       ``np.array`` / ``.item()`` / implicit ``bool()`` on a value that
       came out of a bound jit program — tracked through helper calls —
       plus ``jax.device_get`` / ``.block_until_ready()`` anywhere)
       must carry a ``# rtlint: sync-ok=<tag> <why>`` justification, so
       the complete sync-point inventory of the dispatch loop is
       explicit and a stray ``.item()`` fails the gate.

The cardinality lattice
-----------------------

Values are classified by how many DISTINCT runtime values they can
take, as a symbolic linear expression over ``len(<collection>)`` atoms:

- config default — ``1``: literals, function parameters with no
  analyzed caller (a deployment fixes them once), ``self.<attr>``
  unless some assignment taints it. The budget is per engine INSTANCE,
  so per-instance-fixed values cost one trace key.
- bounded — ``len(X)``: an element of a collection whose terminal name
  matches ``buckets`` (``self.prompt_buckets``, the repo's compile-
  shape discipline) or the mesh-shape discipline (``tps``/``meshes``,
  ISSUE 20), extracted via ``for``/``next(...)``/subscript. ``len(X)``
  of such a collection is itself a config scalar (``1``).
- unbounded: ``len(...)``, ``.shape``, ``.size`` of anything else —
  one compiled program per distinct value — and anything arithmetic
  derives from one.

Cardinalities propagate through assignments, arithmetic (``|A·B|``
bounds; a product of two symbolic factors distributes into product
atoms — ``len(buckets)·len(tps)`` keys, the mesh-keyed factory-table
bound — never collapsing to unbounded), returned values,
and function parameters (a small fixpoint over the call graph), so
``len(prompt)`` laundered through a helper still arrives unbounded at
the trace key — the blind spot RT103 cannot see. Array SHAPES propagate
separately: ``np.zeros((1, bucket))`` is an array whose trace-key
multiplicity is ``card(bucket)``; dispatching it through a bound
program multiplies that binding's program count.

Deliberate approximations (all err toward the config default, so
precision failures are false NEGATIVES — rtflow never guesses a value
is request-varying): attribute reads off unknown objects use a
project-wide per-field-name summary (every ``x.f = v`` and
``Ctor(f=v)`` joined); branch-exclusive rebinds of one ``self.<attr>``
join by max (one engine takes one config branch); arrays not built by a
recognized constructor (``zeros``/``ones``/``full``/``empty``/
``reshape``) have shape multiplicity 1.

Budget grammar: integers, ``len(<name>)`` atoms, ``+``, and products
of the above — ``int * len(<name>)`` or ``len(<a>) * len(<b>)`` (a
per-mesh-shape budget: ``len(prompt_buckets) * len(tps)``) — e.g.
``len(prompt_buckets) + 3``. For a
BINDING method (one that assigns ``self.X = <factory>(...)``) the
declaration bounds the method's total across everything it binds; for
a factory DEF it bounds the programs any single call site can create.
Comparisons assume every atom is >= 1 (an engine has at least one
prompt bucket).
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .callgraph import (CallGraph, ClassNode, FuncNode, self_attr,
                        terminal_name)
from .core import Finding, Module, ProjectRule

#: Collections whose elements are compile-shape knobs: the repo's
#: bucket discipline (prompt_buckets, default_buckets, ...) plus the
#: mesh-shape discipline (ISSUE 20: ``tps`` / ``meshes`` collections —
#: a sharded factory keyed by (bucket, tp) compiles one program per
#: element of each, never per request).
BUCKETS_RE = re.compile(r"(buckets|tps|meshes)$")

#: Files under the compiled-program-budget discipline: factory defs and
#: binding methods here MUST declare budgets (RT109), and dispatch
#: results here are sync-audited (RT111, minus gpt_decode whose host
#: loops are the library surface, not the engine driver).
BUDGET_SCOPE = ("models/gpt_decode.py", "serve/engine.py",
                "serve/draft.py", "serve/handoff.py", "data/llm.py")
SYNC_SCOPE = ("serve/engine.py", "serve/draft.py", "serve/handoff.py",
              "data/llm.py")

#: Array constructors whose first argument is the shape.
_SHAPE_CTORS = ("zeros", "ones", "full", "empty")
#: Host-converting calls that synchronize on a device value.
_SYNC_CONVERTERS = ("asarray", "array")
#: Pure-ish passthroughs: card of result = product of arg cards.
_PASSTHROUGH = ("int", "float", "bool", "abs", "round", "min", "max",
                "sorted", "tuple", "list", "set", "frozenset", "str",
                "int32", "int64", "float32", "uint32", "asarray",
                "array")

_FIXPOINT_ROUNDS = 4


# ------------------------------------------------------------------ Card
def _compose_atoms(a: str, b: str) -> str:
    """Product-atom name: the sorted ``*``-join of both factor lists
    (``"" `` is the constant term and contributes no factor), so
    ``len(x)*len(y)`` names one atom regardless of operand order or
    association."""
    if not a:
        return b
    if not b:
        return a
    return "*".join(sorted(a.split("*") + b.split("*")))


class Card:
    """A symbolic upper bound on distinct values: ``terms`` maps atom
    name -> coefficient, with the constant under ``""``; ``terms is
    None`` means unbounded. Immutable."""

    __slots__ = ("terms",)

    def __init__(self, terms: Optional[Dict[str, int]]):
        self.terms = None if terms is None else dict(terms)

    @staticmethod
    def const(n: int = 1) -> "Card":
        return Card({"": int(n)})

    @staticmethod
    def atom(name: str) -> "Card":
        return Card({name: 1})

    @staticmethod
    def unbounded() -> "Card":
        return Card(None)

    @property
    def is_unbounded(self) -> bool:
        return self.terms is None

    def _const_only(self) -> Optional[int]:
        if self.terms is None:
            return None
        if all(k == "" for k in self.terms):
            return self.terms.get("", 0)
        return None

    def add(self, other: "Card") -> "Card":
        if self.is_unbounded or other.is_unbounded:
            return Card.unbounded()
        out = dict(self.terms)
        for k, v in other.terms.items():
            out[k] = out.get(k, 0) + v
        return Card(out)

    def mul(self, other: "Card") -> "Card":
        if self.is_unbounded or other.is_unbounded:
            return Card.unbounded()
        a, b = self._const_only(), other._const_only()
        if a is not None:
            return Card({k: v * max(a, 1) for k, v in other.terms.items()})
        if b is not None:
            return Card({k: v * max(b, 1) for k, v in self.terms.items()})
        # Two symbolic factors: distribute into product atoms (ISSUE 20
        # — a mesh-keyed factory table is len(buckets)*len(tps) programs,
        # a REAL bound, not "give up"). Atom names compose as the sorted
        # "*"-join of their factors so `a*b` and `b*a` meet in leq/join.
        out: Dict[str, int] = {}
        for ka, va in self.terms.items():
            for kb, vb in other.terms.items():
                k = _compose_atoms(ka, kb)
                out[k] = out.get(k, 0) + va * vb
        return Card(out)

    def join(self, other: "Card") -> "Card":
        """Branch join: per-atom max (branch-exclusive configs — one
        instance takes one branch). A unit constant (the ubiquitous
        config default, e.g. a ``next(gen, <default>)`` fallback) is
        absorbed into an atom-bearing side: the default is assumed to
        coincide with one of the bounded values, keeping budgets tight
        (``len(prompt_buckets)``, not ``len(prompt_buckets) + 1``)."""
        if self.is_unbounded or other.is_unbounded:
            return Card.unbounded()
        a, b = self._const_only(), other._const_only()
        if a is not None and a <= 1 and b is None:
            return Card(other.terms)
        if b is not None and b <= 1 and a is None:
            return Card(self.terms)
        out = dict(self.terms)
        for k, v in other.terms.items():
            out[k] = max(out.get(k, 0), v)
        return Card(out)

    def leq(self, declared: "Card") -> bool:
        """``self <= declared`` assuming every atom >= 1."""
        if declared.is_unbounded:
            return True
        if self.is_unbounded:
            return False
        slack = 0
        for k in set(self.terms) | set(declared.terms):
            if k == "":
                continue
            d = declared.terms.get(k, 0) - self.terms.get(k, 0)
            if d < 0:
                return False
            slack += d               # each atom is worth >= 1
        return self.terms.get("", 0) <= declared.terms.get("", 0) + slack

    def render(self) -> str:
        if self.is_unbounded:
            return "unbounded"
        parts = []
        for k in sorted(t for t in self.terms if t and self.terms[t]):
            c = self.terms[k]
            parts.append(k if c == 1 else f"{c}*{k}")
        c0 = self.terms.get("", 0)
        if c0 or not parts:
            parts.append(str(c0))
        return " + ".join(parts)

    def evaluate(self, atoms: Dict[str, int]) -> int:
        """Numeric value given concrete atom sizes (raises KeyError on
        a missing atom; ValueError when unbounded). Product atoms
        (``len(x)*len(y)``) evaluate as the product of their factors."""
        if self.is_unbounded:
            raise ValueError("unbounded budget has no numeric value")

        def val(k: str) -> int:
            out = 1
            for f in k.split("*"):
                out *= atoms[f]
            return out

        return sum(v * (1 if k == "" else val(k))
                   for k, v in self.terms.items())

    def __eq__(self, other):
        return isinstance(other, Card) and self.terms == other.terms

    def __repr__(self):
        return f"Card<{self.render()}>"


def parse_budget(expr: str) -> Card:
    """``len(prompt_buckets) + 3`` -> :class:`Card`. Grammar: integer
    literals, ``len(<name>)`` / ``len(<obj>.<name>)`` atoms, ``+``, and
    products — with an integer, or of two atoms (a mesh-keyed budget:
    ``len(prompt_buckets) * len(tps)``). Raises ValueError on anything
    else."""
    try:
        tree = ast.parse(expr.strip(), mode="eval").body
    except SyntaxError as e:
        raise ValueError(f"unparseable budget expression {expr!r}: "
                         f"{e.msg}") from None

    def ev(node) -> Card:
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return Card.const(node.value)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            return ev(node.left).add(ev(node.right))
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            return ev(node.left).mul(ev(node.right))
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "len" and len(node.args) == 1:
            t = terminal_name(node.args[0])
            if t:
                return Card.atom(f"len({t})")
        raise ValueError(
            f"budget expression {expr!r} must be built from integers, "
            f"len(<name>) atoms, '+', and products ('int * atom' or "
            f"'atom * atom')")

    return ev(tree)


def declared_budgets(mod: Module) -> Dict[str, Tuple[int, str]]:
    """``qualname -> (def lineno, raw budget expr)`` for every function
    in ``mod`` carrying a ``program-budget:`` declaration (the helper
    the budget-vs-actual test reads the engine's contract through)."""
    out: Dict[str, Tuple[int, str]] = {}

    def rec(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                rec(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                d = mod.func_directives(child)
                if "program-budget" in d:
                    out[f"{prefix}{child.name}"] = \
                        (child.lineno, d["program-budget"])
                rec(child, prefix)

    rec(mod.tree, "")
    return out


# ------------------------------------------------------------- analysis
def _is_factory(fn: FuncNode) -> bool:
    """A jit/pjit program factory: named ``jit_*``/``pjit_*``, or a def
    that directly calls ``jax.jit`` / ``pjit``."""
    if fn.name.startswith(("jit_", "pjit_")):
        return True
    for w in ast.walk(fn.node):
        if isinstance(w, ast.Call):
            t = terminal_name(w.func)
            if t in ("jit", "pjit"):
                return True
    return False


def _rt103_visible(arg) -> bool:
    """True when RT103's intra-procedural classifier reports this
    argument (unhashable literal, or len()/.shape/.size directly in
    the expression) — rtflow then stays quiet to keep one finding per
    hazard; RT109 adds only what RT103 cannot see. Callers must ALSO
    check that RT103 covers the call site at all: its classifier is
    name-based (``jit_*`` callees), so a structurally-recognized
    factory's sites are rtflow's to report even when the len() is
    right there in the argument."""
    if isinstance(arg, (ast.List, ast.Set, ast.Dict, ast.ListComp,
                        ast.SetComp, ast.DictComp, ast.GeneratorExp)):
        return True
    for w in ast.walk(arg):
        if isinstance(w, ast.Call) and isinstance(w.func, ast.Name) \
                and w.func.id == "len":
            return True
        if isinstance(w, ast.Attribute) and w.attr in ("shape", "size"):
            return True
    return False


def _bucketish(expr) -> Optional[str]:
    """Terminal name of a bucket-convention collection expression."""
    t = self_attr(expr)
    if t is None and isinstance(expr, ast.Name):
        t = expr.id
    if t is None and isinstance(expr, ast.Attribute):
        t = expr.attr
    if t is not None and BUCKETS_RE.search(t):
        return t
    return None


@dataclass
class _FactoryCallSite:
    factory: str                  # factory FuncNode key
    caller: Optional[str]
    mod: Module
    call: ast.Call
    args_card: Card               # product over static args
    bound_attr: Optional[str]     # self.<attr> the result binds to
    bound_local: Optional[str]    # local name it binds to
    unbounded_arg: Optional[ast.AST]  # first non-RT103-visible offender


@dataclass
class _DispatchSite:
    mod: Module
    call: ast.Call
    caller: Optional[str]
    cls_key: Optional[str]
    attr: Optional[str]           # self.<attr> dispatch
    local: Optional[str]          # local-binding dispatch
    shape_card: Card


class FlowAnalysis:
    """One pass over the analyzed set: call graph + cardinality/device
    fixpoints + the per-site audit tables the rules read."""

    def __init__(self, mods: Sequence[Module]):
        self.mods = list(mods)
        self.graph = CallGraph.build(mods)
        g = self.graph
        self.factories: Dict[str, FuncNode] = {
            k: f for k, f in g.funcs.items() if _is_factory(f)}
        #: class key -> {attr: True} attrs ever bound from a factory
        self.bound_attrs: Dict[str, Set[str]] = {}
        for ck, cn in g.classes.items():
            for attr, sites in cn.attr_assigns.items():
                for _fk, value in sites:
                    if isinstance(value, ast.Call) and \
                            self._factory_of(cn.mod, cn, value):
                        self.bound_attrs.setdefault(ck, set()).add(attr)
        # Fixpoint state.
        self.param_cards: Dict[Tuple[str, str], Card] = {}
        self.ret_cards: Dict[str, Card] = {}
        #: Element-wise cards for functions whose every return is a
        #: tuple literal of one length — tuple-unpacking call sites
        #: read these instead of the (product) whole-value card, which
        #: would compound through fixpoint feedback loops. None marks
        #: incompatible return shapes.
        self.ret_tuple_cards: Dict[str, Optional[List[Card]]] = {}
        self.attr_cards: Dict[Tuple[str, str], Card] = {}
        self.field_cards: Dict[str, Card] = {}
        self.param_taint: Set[Tuple[str, str]] = set()
        self.ret_taint: Set[str] = set()
        # Audit tables (rebuilt on the final round).
        self.factory_sites: List[_FactoryCallSite] = []
        self.dispatch_sites: List[_DispatchSite] = []
        self.sync_sites: List[Tuple[Module, int, str, Optional[str]]] = []
        self._run_fixpoint()

    # ------------------------------------------------------------ plumbing
    def _factory_of(self, mod: Module, cnode: Optional[ClassNode],
                    call: ast.Call) -> Optional[str]:
        key = self.graph.resolve_call(mod, cnode, call)
        if key and key in self.factories:
            return key
        return None

    def _class_of(self, fn: FuncNode) -> Optional[ClassNode]:
        if fn.cls is None:
            return None
        return self.graph.classes.get(f"{fn.mod.relpath}::{fn.cls}")

    def _run_fixpoint(self):
        self._seed_field_cards()
        for rnd in range(_FIXPOINT_ROUNDS):
            final = rnd == _FIXPOINT_ROUNDS - 1
            if final:
                self.factory_sites = []
                self.dispatch_sites = []
                self.sync_sites = []
            changed = False
            for key in sorted(self.graph.funcs):
                fn = self.graph.funcs[key]
                flow = _FuncFlow(self, fn, record=final)
                flow.run()
                changed |= flow.changed
            if not changed and not final:
                # Converged early: one more pass with recording on.
                for key in sorted(self.graph.funcs):
                    _FuncFlow(self, self.graph.funcs[key],
                              record=True).run()
                break

    def _seed_field_cards(self):
        """Project-wide per-field-name summaries from constructor
        keywords (``_EngineRequest(bucket=...)``): the data-carrier
        idiom request state flows through. Non-constructor keyword args
        are excluded (a ``Capitalized`` callee is the convention)."""
        for mod in self.mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                t = terminal_name(node.func)
                if not t or not t.lstrip("_")[:1].isupper():
                    continue
                for kw in node.keywords:
                    if kw.arg:
                        self.field_cards[kw.arg] = Card.const(1)
        # Values are joined in during the fixpoint (via _FuncFlow).

    # Fixpoint update helpers (monotone joins; report change).
    def _join_into(self, table, key, card: Card) -> bool:
        cur = table.get(key)
        new = card if cur is None else cur.join(card)
        if cur is None or new.terms != cur.terms:
            table[key] = new
            return True
        return False


class _FuncFlow:
    """One function's forward pass: evaluates local cardinalities and
    shapes, propagates summaries outward, and (on the recording round)
    emits the audit sites."""

    def __init__(self, an: FlowAnalysis, fn: FuncNode, record: bool):
        self.an = an
        self.fn = fn
        self.record = record
        self.changed = False
        self.cnode = an._class_of(fn)
        self.cls_key = self.cnode.key if self.cnode else None
        self.env: Dict[str, Card] = {}
        self.shapes: Dict[str, Card] = {}
        self.taint: Set[str] = set()
        self.local_factories: Set[str] = set()
        self._recording = False
        args = fn.node.args
        all_args = list(getattr(args, "posonlyargs", [])) + args.args + \
            ([args.vararg] if args.vararg else []) + args.kwonlyargs + \
            ([args.kwarg] if args.kwarg else [])
        for a in all_args:
            if a.arg in ("self", "cls"):
                continue
            self.env[a.arg] = an.param_cards.get((fn.key, a.arg),
                                                 Card.const(1))
            if (fn.key, a.arg) in an.param_taint:
                self.taint.add(a.arg)

    # ------------------------------------------------------------- driving
    def run(self):
        # Two passes over the body approximate loop-carried joins (the
        # lattice is shallow; cards only grow); audit sites are emitted
        # on the SECOND pass only, with the env fully converged.
        self._recording = False
        self._walk_body(self.fn.node.body)
        self._recording = self.record
        self._walk_body(self.fn.node.body)

    def _walk_body(self, stmts):
        for s in stmts:
            self._stmt(s)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                          # separate flow unit
        if isinstance(node, ast.Assign):
            self._visit_expr(node.value)
            card = self._eval(node.value)
            tainted = self._is_device(node.value)
            shape = self._shape_of(node.value)
            for t in node.targets:
                self._assign(t, node.value, card, tainted, shape)
            self._note_summaries(node.targets, node.value, card)
            return
        if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._visit_expr(node.value)
                card = self._eval(node.value)
                if isinstance(node, ast.AugAssign) and \
                        isinstance(node.target, ast.Name):
                    card = card.mul(self.env.get(node.target.id,
                                                 Card.const(1)))
                self._assign(node.target, node.value, card,
                             self._is_device(node.value), None)
                self._note_summaries([node.target], node.value, card)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                self._visit_expr(node.value)
                self.changed |= self.an._join_into(
                    self.an.ret_cards, self.fn.key,
                    self._eval(node.value))
                self._note_ret_tuple(node.value)
                if self._is_device(node.value):
                    if self.fn.key not in self.an.ret_taint:
                        self.an.ret_taint.add(self.fn.key)
                        self.changed = True
            return
        if isinstance(node, ast.For):
            self._visit_expr(node.iter)
            card = self._element_card(node.iter)
            self._assign(node.target, None, card, False, None)
            self._walk_body(node.body)
            self._walk_body(node.orelse)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._visit_expr(node.test)
            self._check_bool_sync(node.test)
            self._walk_body(node.body)
            self._walk_body(node.orelse)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self._visit_expr(item.context_expr)
            self._walk_body(node.body)
            return
        if isinstance(node, ast.Try):
            self._walk_body(node.body)
            for h in node.handlers:
                self._walk_body(h.body)
            self._walk_body(node.orelse)
            self._walk_body(node.finalbody)
            return
        if isinstance(node, ast.Expr):
            self._visit_expr(node.value)
            return
        # Everything else: visit any embedded expressions generically.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _note_ret_tuple(self, value):
        tbl = self.an.ret_tuple_cards
        if not isinstance(value, ast.Tuple):
            if self.fn.key in tbl and tbl[self.fn.key] is not None:
                tbl[self.fn.key] = None
                self.changed = True
            elif self.fn.key not in tbl:
                tbl[self.fn.key] = None
            return
        cards = [self._eval(e) for e in value.elts]
        cur = tbl.get(self.fn.key)
        if self.fn.key in tbl and (cur is None or len(cur) != len(cards)):
            if cur is not None:
                tbl[self.fn.key] = None
                self.changed = True
            return
        if cur is None:
            tbl[self.fn.key] = cards
            self.changed = True
            return
        out = [a.join(b) for a, b in zip(cur, cards)]
        if any(a.terms != b.terms for a, b in zip(out, cur)):
            tbl[self.fn.key] = out
            self.changed = True

    def _assign(self, target, value, card: Card, tainted: bool,
                shape: Optional[Card]):
        if isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and \
                    len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._assign(t, v, self._eval(v),
                                 self._is_device(v), self._shape_of(v))
                return
            if isinstance(value, ast.Call):
                callee = self.an.graph.resolve_call(
                    self.fn.mod, self.cnode, value)
                elems = self.an.ret_tuple_cards.get(callee) \
                    if callee else None
                if elems is not None and len(elems) == len(target.elts):
                    for t, c in zip(target.elts, elems):
                        self._assign(t, None, c, tainted, None)
                    return
            for t in target.elts:
                self._assign(t, None, card, tainted, None)
            return
        if isinstance(target, ast.Starred):
            self._assign(target.value, None, card, tainted, None)
            return
        if isinstance(target, ast.Name):
            old = self.env.get(target.id)
            self.env[target.id] = card if old is None else old.join(card)
            if tainted:
                self.taint.add(target.id)
            if shape is not None:
                self.shapes[target.id] = shape
            if isinstance(value, ast.Call) and \
                    self.an._factory_of(self.fn.mod, self.cnode, value):
                self.local_factories.add(target.id)
            elif isinstance(value, ast.Name) and \
                    value.id in self.local_factories:
                self.local_factories.add(target.id)

    def _note_summaries(self, targets, value, card: Card):
        """Feed self-attr and field-name summaries."""
        for t in targets:
            a = self_attr(t)
            if a is not None and self.cls_key:
                self.changed |= self.an._join_into(
                    self.an.attr_cards, (self.cls_key, a), card)
                continue
            if isinstance(t, ast.Attribute):    # x.f = v (field summary)
                self.changed |= self.an._join_into(
                    self.an.field_cards, t.attr, card)

    # --------------------------------------------------------- expressions
    def _visit_expr(self, expr):
        """Walk an expression, producing param-summary updates for
        resolved calls and (on the recording round) audit sites."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._visit_call(node)
            elif isinstance(node, (ast.GeneratorExp, ast.ListComp,
                                   ast.SetComp, ast.DictComp)):
                for gen in node.generators:
                    card = self._element_card(gen.iter)
                    self._assign(gen.target, None, card, False, None)

    def _visit_call(self, call: ast.Call):
        callee = self.an.graph.resolve_call(self.fn.mod, self.cnode, call)
        if callee is not None:
            self._propagate_params(callee, call)
        fkey = callee if callee in self.an.factories else None
        if fkey is not None and self._recording:
            self._note_factory_call(fkey, call)
        if self._recording:
            self._note_dispatch(call)
            self._note_sync(call)
        # Constructor keywords feed the field summaries.
        t = terminal_name(call.func)
        if t and t.lstrip("_")[:1].isupper():
            for kw in call.keywords:
                if kw.arg:
                    self.changed |= self.an._join_into(
                        self.an.field_cards, kw.arg, self._eval(kw.value))

    def _propagate_params(self, callee: str, call: ast.Call):
        cf = self.an.graph.funcs.get(callee)
        if cf is None:
            return
        args = cf.node.args
        names = [a.arg for a in
                 list(getattr(args, "posonlyargs", [])) + args.args]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred) or i >= len(names):
                break
            self._feed_param(callee, names[i], a)
        for kw in call.keywords:
            if kw.arg:
                self._feed_param(callee, kw.arg, kw.value)

    def _feed_param(self, callee: str, name: str, value):
        self.changed |= self.an._join_into(
            self.an.param_cards, (callee, name), self._eval(value))
        if self._is_device(value) and (callee, name) not in \
                self.an.param_taint:
            self.an.param_taint.add((callee, name))
            self.changed = True

    # ---------------------------------------------------------- audit sites
    def _binding_of(self, call: ast.Call) -> Tuple[Optional[str],
                                                   Optional[str]]:
        """(self_attr, local_name) this call's result is assigned to,
        found via the enclosing statement (best-effort: direct assign)."""
        parent = getattr(call, "_rtflow_parent", None)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                a = self_attr(t)
                if a:
                    return a, None
                if isinstance(t, ast.Name):
                    return None, t.id
        return None, None

    def _note_factory_call(self, fkey: str, call: ast.Call):
        cards = []
        offender = None
        # RT103 only classifies jit_*-named call sites; a factory
        # recognized structurally (jax.jit in its body) is invisible
        # to it, so rtflow owns even the argument-local hazards there.
        callee = terminal_name(call.func) or ""
        rt103_site = callee.startswith(("jit_", "pjit_"))
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            c = self._eval(a)
            if c.is_unbounded:
                if offender is None and not (rt103_site
                                             and _rt103_visible(a)):
                    offender = a
                continue             # reported (here or by RT103)
            cards.append(c)
        total = Card.const(1)
        for c in cards:
            total = total.mul(c)
        attr, local = self._binding_of(call)
        self.an.factory_sites.append(_FactoryCallSite(
            factory=fkey, caller=self.fn.key, mod=self.fn.mod, call=call,
            args_card=total, bound_attr=attr, bound_local=local,
            unbounded_arg=offender))

    def _dispatch_target(self, call: ast.Call) -> Tuple[Optional[str],
                                                        Optional[str]]:
        """(attr, local) when this call dispatches a bound program."""
        a = self_attr(call.func)
        if a is not None and self.cls_key and \
                a in self.an.bound_attrs.get(self.cls_key, ()):
            return a, None
        if isinstance(call.func, ast.Name):
            # Local binding: f = jit_x(...); f(...)
            if call.func.id in self.local_factories:
                return None, call.func.id
        if isinstance(call.func, ast.Call):
            inner = self.an._factory_of(self.fn.mod, self.cnode,
                                        call.func)
            if inner:
                return None, "<immediate>"
        return None, None

    def _note_dispatch(self, call: ast.Call):
        attr, local = self._dispatch_target(call)
        if attr is None and local is None:
            return
        mult = Card.const(1)
        for a in call.args:
            mult = mult.mul(self._shape_card(a))
        self.an.dispatch_sites.append(_DispatchSite(
            mod=self.fn.mod, call=call, caller=self.fn.key,
            cls_key=self.cls_key, attr=attr, local=local,
            shape_card=mult))

    def _note_sync(self, call: ast.Call):
        if not self.fn.mod.relpath.endswith(SYNC_SCOPE):
            return
        t = terminal_name(call.func)
        line = call.lineno
        if t in ("device_get", "block_until_ready"):
            self.an.sync_sites.append(
                (self.fn.mod, line, f"{t}(...)", self.fn.qualname))
            return
        if t == "item" and isinstance(call.func, ast.Attribute) and \
                self._is_device(call.func.value):
            self.an.sync_sites.append(
                (self.fn.mod, line, ".item() on a dispatch result",
                 self.fn.qualname))
            return
        if t in _SYNC_CONVERTERS and call.args and \
                self._is_device(call.args[0]):
            self.an.sync_sites.append(
                (self.fn.mod, line,
                 f"np.{t}(...) on a dispatch result", self.fn.qualname))

    def _check_bool_sync(self, test):
        if not (getattr(self, "_recording", False) and
                self.fn.mod.relpath.endswith(SYNC_SCOPE)):
            return
        expr = test
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            expr = expr.operand
        if isinstance(expr, ast.Name) and expr.id in self.taint:
            self.an.sync_sites.append(
                (self.fn.mod, test.lineno,
                 f"implicit bool() on dispatch result {expr.id!r}",
                 self.fn.qualname))

    # ------------------------------------------------------------- taint
    def _is_device(self, expr) -> bool:
        """Did this value come out of a bound jit program? Tracked
        through locals, tuple unpacking, params, and returns; a host
        conversion (np.asarray/.item()) strips the taint."""
        if isinstance(expr, ast.Name):
            return expr.id in self.taint
        if isinstance(expr, ast.Call):
            attr, local = self._dispatch_target(expr)
            if attr is not None or local is not None:
                return True
            callee = self.an.graph.resolve_call(self.fn.mod, self.cnode,
                                                expr)
            return callee in self.an.ret_taint
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._is_device(e) for e in expr.elts)
        if isinstance(expr, ast.Subscript):
            return self._is_device(expr.value)
        return False

    # -------------------------------------------------------------- shapes
    def _shape_of(self, expr) -> Optional[Card]:
        """Shape multiplicity of a recognized array construction."""
        if not isinstance(expr, ast.Call):
            return None
        t = terminal_name(expr.func)
        if t in _SHAPE_CTORS and expr.args:
            return self._dims_card(expr.args[0])
        if t == "reshape" and expr.args:
            dims = expr.args[0] if len(expr.args) == 1 else None
            if dims is not None:
                return self._dims_card(dims)
            out = Card.const(1)
            for a in expr.args:
                out = out.mul(self._eval(a))
            return out
        return None

    def _dims_card(self, dims) -> Card:
        if isinstance(dims, (ast.Tuple, ast.List)):
            out = Card.const(1)
            for d in dims.elts:
                out = out.mul(self._eval(d))
            return out
        return self._eval(dims)

    def _shape_card(self, arg) -> Card:
        if isinstance(arg, ast.Name):
            return self.shapes.get(arg.id, Card.const(1))
        got = self._shape_of(arg)
        return got if got is not None else Card.const(1)

    # --------------------------------------------------------------- cards
    def _eval(self, expr) -> Card:
        if expr is None:
            return Card.const(1)
        if isinstance(expr, ast.Constant):
            return Card.const(1)
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id, Card.const(1))
        if isinstance(expr, ast.Attribute):
            if expr.attr in ("shape", "size"):
                return Card.unbounded()
            a = self_attr(expr)
            if a is not None:
                if self.cls_key:
                    got = self.an.attr_cards.get((self.cls_key, a))
                    if got is not None:
                        return got
                return Card.const(1)
            return self.an.field_cards.get(expr.attr, Card.const(1))
        if isinstance(expr, ast.Subscript):
            b = _bucketish(expr.value)
            if b:
                return Card.atom(f"len({b})")
            return self._eval(expr.value)
        if isinstance(expr, ast.Call):
            return self._eval_call(expr)
        if isinstance(expr, ast.BinOp):
            return self._eval(expr.left).mul(self._eval(expr.right))
        if isinstance(expr, ast.BoolOp):
            out = Card.const(1)
            for v in expr.values:
                out = out.mul(self._eval(v))
            return out
        if isinstance(expr, ast.UnaryOp):
            return self._eval(expr.operand)
        if isinstance(expr, ast.Compare):
            return Card.const(2)
        if isinstance(expr, ast.IfExp):
            return self._eval(expr.body).join(self._eval(expr.orelse))
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = Card.const(1)
            for e in expr.elts:
                out = out.mul(self._eval(e))
            return out
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value)
        return Card.const(1)

    def _eval_call(self, call: ast.Call) -> Card:
        t = terminal_name(call.func)
        if t == "len" and len(call.args) == 1:
            b = _bucketish(call.args[0])
            if b:
                return Card.const(1)     # len of a config tuple: fixed
            return Card.unbounded()
        if t == "next" and call.args:
            card = self._element_card_of_gen(call.args[0])
            if len(call.args) > 1:
                card = card.join(self._eval(call.args[1]))
            return card
        if t == "range":
            out = Card.const(1)
            for a in call.args:
                out = out.mul(self._eval(a))
            return out
        callee = self.an.graph.resolve_call(self.fn.mod, self.cnode, call)
        if callee is not None:
            got = self.an.ret_cards.get(callee)
            if got is not None:
                return got
            return Card.const(1)
        if t in _PASSTHROUGH:
            out = Card.const(1)
            for a in call.args:
                out = out.mul(self._eval(a))
            return out
        return Card.const(1)

    def _element_card_of_gen(self, expr) -> Card:
        if isinstance(expr, ast.GeneratorExp) and expr.generators:
            return self._element_card(expr.generators[0].iter)
        return self._element_card(expr)

    def _element_card(self, it) -> Card:
        b = _bucketish(it)
        if b:
            return Card.atom(f"len({b})")
        if isinstance(it, ast.Call) and terminal_name(it.func) == "range":
            out = Card.const(1)
            for a in it.args:
                out = out.mul(self._eval(a))
            return out
        card = self._eval(it)
        if card.is_unbounded:
            return Card.unbounded()
        return Card.const(1)


# Parent links for _binding_of: set once per module tree.
def _link_parents(mods: Sequence[Module]):
    for mod in mods:
        if getattr(mod, "_rtflow_linked", False):
            continue
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                child._rtflow_parent = node
        mod._rtflow_linked = True


_ANALYSIS_CACHE: Dict[tuple, FlowAnalysis] = {}


def get_analysis(mods: Sequence[Module]) -> FlowAnalysis:
    key = tuple(id(m) for m in mods)
    got = _ANALYSIS_CACHE.get(key)
    if got is None:
        _ANALYSIS_CACHE.clear()          # one live analysis at a time
        _link_parents(mods)
        got = FlowAnalysis(mods)
        _ANALYSIS_CACHE[key] = got
    return got


# ----------------------------------------------------------------- RT109
class ProgramBudgetRule(ProjectRule):
    """RT109: static compiled-program-budget audit (see the module
    docstring for the lattice and the grammar). Three checks:

    - a factory def (``jit_*``/``pjit_*`` or direct ``jax.jit``) or a
      method binding one to ``self`` in the budget-scope files without
      a ``# rtlint: program-budget:`` declaration;
    - an UNBOUNDED value reaching a trace key: a request-varying factory
      argument RT103 cannot see at the site (it arrived through a
      helper/variable), or a dispatch of an array whose shape derives
      from one — each compiled program's cache grows per distinct value;
    - a declared budget the computed bound exceeds (binding methods:
      total over everything the method binds, each binding multiplied
      by the worst dispatch-shape multiplicity of its attribute;
      factory defs: the worst single call site).
    """

    id = "RT109"
    summary = "compiled-program budget missing, exceeded, or unbounded"

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        an = get_analysis(mods)
        g = an.graph
        budgets: Dict[str, Tuple[FuncNode, Optional[Card], str]] = {}
        for key, fn in sorted(g.funcs.items()):
            raw = fn.directives.get("program-budget")
            if raw is None:
                continue
            try:
                budgets[key] = (fn, parse_budget(raw), raw)
            except ValueError as e:
                budgets[key] = (fn, None, raw)
                yield Finding(
                    fn.mod.relpath, fn.node.lineno, self.id,
                    f"{fn.qualname}: {e}", f"{fn.qualname}.budget_syntax")

        # Binding methods: which functions assign self.<attr> from a
        # factory call (collected from the recorded factory sites).
        binds_by_fn: Dict[str, List[_FactoryCallSite]] = {}
        sites_by_factory: Dict[str, List[_FactoryCallSite]] = {}
        for s in an.factory_sites:
            sites_by_factory.setdefault(s.factory, []).append(s)
            if s.caller:
                binds_by_fn.setdefault(s.caller, []).append(s)

        # Check 1: missing declarations in the budget-scope files.
        for key, fn in sorted(g.funcs.items()):
            if not fn.mod.relpath.endswith(BUDGET_SCOPE):
                continue
            if key in budgets:
                continue
            if key in an.factories:
                yield Finding(
                    fn.mod.relpath, fn.node.lineno, self.id,
                    f"jit factory {fn.qualname} has no "
                    f"'# rtlint: program-budget: <expr>' declaration — "
                    f"every factory entrypoint must state how many "
                    f"compiled programs it can create per call site",
                    f"{fn.qualname}.budget_missing")
                continue
            if any(s.bound_attr for s in binds_by_fn.get(key, ())):
                yield Finding(
                    fn.mod.relpath, fn.node.lineno, self.id,
                    f"{fn.qualname} binds jit programs to self but has "
                    f"no '# rtlint: program-budget: <expr>' declaration "
                    f"— the engine's compiled-program set must be a "
                    f"declared, machine-checked budget",
                    f"{fn.qualname}.budget_missing")

        # Check 2a: unbounded factory arguments (RT103-invisible).
        for s in an.factory_sites:
            if s.unbounded_arg is None:
                continue
            fac = g.funcs[s.factory]
            yield Finding(
                s.mod.relpath, s.unbounded_arg.lineno, self.id,
                f"argument {ast.unparse(s.unbounded_arg)!r} of "
                f"{fac.name}(...) is request-varying (unbounded "
                f"cardinality, established interprocedurally) — every "
                f"distinct value compiles and caches a fresh XLA "
                f"program; thread a bucketed config value instead",
                f"{_caller_qual(g, s.caller)}.{fac.name}.unbounded")

        # Check 2b: unbounded dispatch shapes.
        attr_mult: Dict[Tuple[Optional[str], str], Card] = {}
        local_mult: Dict[Tuple[Optional[str], str], Card] = {}
        for d in an.dispatch_sites:
            if d.shape_card.is_unbounded:
                what = f"self.{d.attr}" if d.attr else "the bound program"
                yield Finding(
                    d.mod.relpath, d.call.lineno, self.id,
                    f"dispatch of {what} with an array whose shape "
                    f"derives from a request-varying value — every "
                    f"distinct shape is a fresh trace key (one compiled "
                    f"program per value); pad to a prompt bucket first",
                    f"{_caller_qual(g, d.caller)}.{what}.unbounded_shape")
                continue
            if d.attr is not None:
                k = (d.cls_key, d.attr)
                attr_mult[k] = attr_mult.get(k, Card.const(1)).join(
                    d.shape_card)
            elif d.local not in (None, "<immediate>"):
                k = (d.caller, d.local)
                local_mult[k] = local_mult.get(k, Card.const(1)).join(
                    d.shape_card)

        # Check 3: computed bound vs declaration.
        for key in sorted(budgets):
            fn, declared, raw = budgets[key]
            if declared is None:
                continue
            if key in an.factories:
                computed = Card.const(0)
                for s in sites_by_factory.get(key, ()):
                    computed = computed.join(
                        self._site_card(s, attr_mult, local_mult, g))
                kind = "worst call site"
            else:
                computed = Card.const(0)
                per_attr: Dict[str, Card] = {}
                for s in binds_by_fn.get(key, ()):
                    c = self._site_card(s, attr_mult, local_mult, g)
                    if s.bound_attr:
                        per_attr[s.bound_attr] = per_attr.get(
                            s.bound_attr, Card.const(0)).join(c)
                    else:
                        computed = computed.add(c)
                for a in sorted(per_attr):
                    computed = computed.add(per_attr[a])
                kind = "total bound programs"
            if not computed.leq(declared):
                yield Finding(
                    fn.mod.relpath, fn.node.lineno, self.id,
                    f"{fn.qualname} declares 'program-budget: {raw}' "
                    f"but rtflow bounds its {kind} at "
                    f"{computed.render()} — raise the declaration only "
                    f"if the extra programs are intended, otherwise "
                    f"find the knob that multiplied the trace keys",
                    f"{fn.qualname}.budget_exceeded")

    @staticmethod
    def _site_card(s: _FactoryCallSite, attr_mult, local_mult,
                   g: CallGraph) -> Card:
        mult = Card.const(1)
        caller = g.funcs.get(s.caller) if s.caller else None
        if s.bound_attr and caller is not None and caller.cls:
            k = (f"{caller.mod.relpath}::{caller.cls}", s.bound_attr)
            mult = attr_mult.get(k, Card.const(1))
        elif s.bound_local:
            mult = local_mult.get((s.caller, s.bound_local),
                                  Card.const(1))
        return s.args_card.mul(mult)


def _caller_qual(g: CallGraph, caller: Optional[str]) -> str:
    fn = g.funcs.get(caller) if caller else None
    return fn.qualname if fn else "<module>"


# ----------------------------------------------------------------- RT110
class InterprocContractRule(ProjectRule):
    """RT110: lock/driver contracts checked at call EDGES — the
    interprocedural completion of RT101/RT102/RT108 and the static twin
    of rtsan's RS102/RS103. For every resolved call:

    - callee annotated ``holds=L``: the edge must hold ``L`` (lexical
      ``with self.L``, caller's own ``holds=``, a manual ``acquire()``
      in the caller, or a ``*_locked`` caller — RT101's leniencies,
      made transitive);
    - callee named ``*_locked``: the edge must hold at least one lock;
    - callee annotated ``owner=driver``: the caller must be driver code
      (``owner=`` / ``entry=driver``), the edge a thread registration
      (``Thread(target=...)``), or the callee itself an ``entry=driver``
      rebinding point. Anything else runs device-owning code off the
      driver thread; suppress with a justification only where ownership
      is deliberately transferred (e.g. failing a confirmed-dead
      driver's lanes)."""

    id = "RT110"
    summary = "holds=/owner= contract broken at a resolved call edge"

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        an = get_analysis(mods)
        g = an.graph
        for e in an.graph.edges:
            callee = g.funcs.get(e.callee)
            if callee is None:
                continue
            caller = g.funcs.get(e.caller) if e.caller else None
            cd = caller.directives if caller else {}
            caller_qual = caller.qualname if caller else "<module>"
            caller_locked = bool(caller and
                                 caller.name.endswith("_locked"))
            holds = tuple(h.strip() for h in
                          callee.directives.get("holds", "").split(",")
                          if h.strip())
            for lock in holds:
                if lock in e.locks or caller_locked:
                    continue
                yield Finding(
                    e.mod.relpath, e.line, self.id,
                    f"{caller_qual} calls {callee.qualname} without "
                    f"self.{lock} held — the callee's 'holds={lock}' "
                    f"contract promises every caller locks first "
                    f"(rtsan raises RS102 for this at runtime)",
                    f"{caller_qual}->{callee.qualname}.holds.{lock}")
            if callee.cls and callee.name.endswith("_locked") \
                    and not holds and e.kind == "call":
                if not e.locks and not caller_locked:
                    yield Finding(
                        e.mod.relpath, e.line, self.id,
                        f"{caller_qual} calls {callee.qualname} with no "
                        f"lock held — the *_locked naming convention "
                        f"promises callers hold the guarding lock",
                        f"{caller_qual}->{callee.qualname}.locked")
            if callee.directives.get("owner") == "driver":
                if e.kind == "thread":
                    continue
                if callee.directives.get("entry") == "driver":
                    continue         # the call itself (re)binds the owner
                if cd.get("owner") == "driver" or \
                        cd.get("entry") == "driver":
                    continue
                yield Finding(
                    e.mod.relpath, e.line, self.id,
                    f"{caller_qual} calls {callee.qualname}, which is "
                    f"'owner=driver', from non-driver code — only the "
                    f"driver thread may run it (rtsan raises RS103 at "
                    f"runtime); annotate the caller, register a thread "
                    f"entry, or suppress with the ownership-transfer "
                    f"justification",
                    f"{caller_qual}->{callee.qualname}.owner")


# ----------------------------------------------------------------- RT111
class SyncPointRule(ProjectRule):
    """RT111: every host-device sync point reachable in the driver
    dispatch path must be JUSTIFIED — ``# rtlint: sync-ok=<tag> <why>``
    on the line (or the line above), or a ``disable=RT111`` suppression.
    Dispatch results are tracked through locals, tuple unpacking,
    helper parameters, and returns (the interprocedural part RT102's
    lexical scope cannot see), so the justified sites ARE the complete
    sync inventory of the dispatch loop: a new stray ``.item()`` or
    ``np.asarray`` on a device value — each one a device-queue stall —
    fails the gate instead of quietly riding a PR. ``jax.device_get``
    and ``.block_until_ready()`` are flagged unconditionally."""

    id = "RT111"
    summary = "unjustified host-device sync point in the dispatch path"

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        an = get_analysis(mods)
        seen = set()
        for mod, line, what, qual in an.sync_sites:
            key = (mod.relpath, line, what)
            if key in seen:
                continue
            seen.add(key)
            if "sync-ok" in mod.line_directives(line):
                continue
            yield Finding(
                mod.relpath, line, self.id,
                f"{what} in {qual} synchronizes the host with the "
                f"device inside the driver dispatch path; if the sync "
                f"is deliberate (chunk-boundary transfer, TTFT token), "
                f"annotate it '# rtlint: sync-ok=<tag> <why>' — "
                f"otherwise hoist it out of the loop",
                f"{qual}.sync.{what.split('(')[0].strip('.')}")
