"""CLI: ``python -m tools.rtsan --report [artifacts...]``.

Renders a run artifact (written by the conftest gate at session end,
or by any process via ``tools.rtsan.dump``): findings, the accumulated
lock-acquisition-order graph, and the per-site hold-time table.
Multiple artifacts (e.g. one per worker process from ``RT_SAN_DIR``)
are merged. With no paths, reads ``$RT_SAN_DIR`` or the newest
``/tmp/rtsan-*.json``. Exit code 1 when any merged finding is missing
from the baseline (the same --check semantics as rtlint), else 0.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from ..rtlint.core import load_baseline
from .core import DEFAULT_BASELINE, HOLD_BUCKETS, coverage_totals


def _default_paths():
    d = os.environ.get("RT_SAN_DIR")
    if d and os.path.isdir(d):
        return sorted(glob.glob(os.path.join(d, "*.json")))
    cands = glob.glob("/tmp/rtsan-*.json")
    return [max(cands, key=os.path.getmtime)] if cands else []


def _merge(paths):
    findings, edges, holds = {}, {}, {}
    coverage = {"modules": {}, "totals": {}}
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        cov = data.get("coverage") or {}
        for m, c in cov.get("modules", {}).items():
            coverage["modules"].setdefault(m, c)
        for fd in data.get("findings", ()):
            findings.setdefault(fd["key"], fd)
        for e in data.get("edges", ()):
            key = (e["from"], e["to"])
            cur = edges.get(key)
            if cur is None:
                edges[key] = dict(e)
            else:
                cur["count"] += e.get("count", 0)
        for h in data.get("holds", ()):
            cur = holds.get(h["site"])
            if cur is None:
                holds[h["site"]] = dict(h)
            else:
                cur["count"] += h["count"]
                cur["total_s"] += h["total_s"]
                cur["max_s"] = max(cur["max_s"], h["max_s"])
                cur["buckets"] = [x + y for x, y in
                                  zip(cur["buckets"], h["buckets"])]
                cur["name"] = cur["name"] or h.get("name")
    if coverage["modules"]:
        # Recompute the totals from the merged per-module rows: with
        # one artifact per process the processes may have sanitized
        # different module sets, so no single artifact's totals line
        # describes the union printed above it.
        coverage["totals"] = coverage_totals(coverage["modules"].values())
    return findings, edges, holds, coverage


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.rtsan",
        description="runtime sanitizer report (rules RS101-RS105)")
    ap.add_argument("paths", nargs="*",
                    help="run artifact json files (default: $RT_SAN_DIR "
                         "or the newest /tmp/rtsan-*.json)")
    ap.add_argument("--report", action="store_true",
                    help="print findings + lock-order graph + hold-time "
                         "table (the default action)")
    ap.add_argument("--json", action="store_true",
                    help="merged machine-readable report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    args = ap.parse_args(argv)

    paths = args.paths or _default_paths()
    if not paths:
        print("rtsan: no run artifact found (run the suite first, or "
              "pass artifact paths)", file=sys.stderr)
        return 2
    findings, edges, holds, coverage = _merge(paths)
    baseline = load_baseline(args.baseline)
    new = sorted(k for k in findings if k not in baseline)

    if args.json:
        print(json.dumps({
            "version": 1,
            "artifacts": [os.path.abspath(p) for p in sorted(paths)],
            "coverage": coverage,
            "findings": [findings[k] for k in sorted(findings)],
            "new": new,
            "edges": [edges[k] for k in sorted(edges)],
            "holds": [holds[k] for k in sorted(holds)],
        }, indent=2, sort_keys=True))
        return 1 if new else 0

    print(f"rtsan report ({len(paths)} artifact"
          f"{'s' if len(paths) != 1 else ''})")

    tot = coverage.get("totals") or {}
    if tot:
        print(f"\n== annotation coverage (the contract set rtlint "
              f"checks statically and rtsan enforces) ==")
        for m in sorted(coverage.get("modules", {})):
            c = coverage["modules"][m]
            print(f"  {m}: {c['annotated']}/{c['methods']} driver "
                  f"methods annotated, {c['locks_with_holds']}/"
                  f"{c['locks']} locks named by holds=")
        print(f"  TOTAL: methods {tot['annotated']}/{tot['methods']} "
              f"({tot['method_fraction']:.0%}), locks "
              f"{tot['locks_with_holds']}/{tot['locks']} "
              f"({tot['lock_fraction']:.0%})")
    print(f"\n== findings: {len(findings)} ({len(new)} new) ==")
    for k in sorted(findings):
        fd = findings[k]
        mark = "" if k in baseline else " [NEW]"
        first = fd["message"].splitlines()[0]
        print(f"  {fd['path']}:{fd['line']}: {fd['rule']} {first}{mark}")

    print(f"\n== lock-order graph: {len(edges)} edges ==")
    for (a, b) in sorted(edges):
        e = edges[(a, b)]
        print(f"  {a} -> {b}  (x{e['count']}, first at "
              f"{e.get('acquire_site', '?')})")

    print(f"\n== hold times: {len(holds)} lock sites ==")
    labels = [f"<{ub * 1000:g}ms" for ub in HOLD_BUCKETS] + [
        f">={HOLD_BUCKETS[-1]:g}s"]
    for site in sorted(holds):
        h = holds[site]
        mean = h["total_s"] / max(h["count"], 1)
        hist = " ".join(f"{lb}:{n}" for lb, n in
                        zip(labels, h["buckets"]))
        name = f" ({h['name']})" if h.get("name") else ""
        print(f"  {site}{name}  n={h['count']} mean={mean * 1000:.3f}ms "
              f"max={h['max_s'] * 1000:.3f}ms  [{hist}]")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
