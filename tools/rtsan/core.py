"""rtsan core: the runtime sanitizer.

One :class:`Sanitizer` per process. :func:`enable` monkeypatches the
``threading.Lock`` / ``RLock`` / ``Condition`` factories (repo-created
locks become :class:`SanLock` wrappers; stdlib-internal locks — Events,
queues, futures — are left raw, decided by the factory caller's file),
patches ``time.sleep`` and ``threading.Thread.start``, wraps the
``jit_*`` program factories in ``ray_tpu.models.gpt_decode``, and
instruments every method carrying an rtlint ``owner=`` / ``holds=`` /
``entry=`` annotation (read through THE same loader rtlint uses,
:mod:`tools.rtlint.annotations`). ``enabled`` is the patch state;
``active`` gates all recording and enforcement, so a dormant sanitizer
costs one flag check per operation and :func:`disable` restores every
identity (pinned by the no-op test).

Checks:

=======  ===========================================================
RS101    lock-order cycle: the global acquisition-order graph gained
         an edge closing a cycle — a potential ABBA deadlock,
         reported with both acquisition stacks even if the deadlock
         never fires in this run
RS102    a ``holds=<lock>`` method entered without ``self.<lock>``
         held (raises), or naming an attribute that does not exist
         (hard error — the contract is unverifiable)
RS103    an ``owner=driver`` method called from a thread that is not
         the registered driver (raises); ``entry=driver`` methods
         (re)register their caller, and a dead owner is rebound
RS104    blocking while holding a repo lock: ``time.sleep`` under a
         lock, ``Condition.wait`` with no timeout (or while holding
         OTHER locks — only the condition's own lock is released),
         and device dispatch (a ``jit_*`` program invocation) under a
         lock; per-site hold times are histogrammed either way
RS105    a thread started inside a :func:`Sanitizer.thread_watch`
         window (engine/drafter/pipeline start sites) still alive at
         its end — a leaked driver
=======  ===========================================================

Findings ride rtlint's machinery: the same :class:`Finding` model and
line-number-free baseline keys (``tools/rtsan/baseline.json``, shipped
EMPTY), with inline suppressions spelled ``# rtsan: disable=RSxxx
<why>`` at the reported line (or the line above / the enclosing def),
resolved through :class:`tools.rtlint.core.Module` with
``tag="rtsan"``. RS102/RS103 raise :class:`RTSanViolation` at the
violation site (a broken contract is a bug NOW); RS101/RS104/RS105 are
recorded and fail the suite at the conftest gate.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..rtlint.annotations import load_annotations, parse_directives
from ..rtlint.core import Finding, Module, load_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")

#: Modules whose annotated methods are instrumented by default — the
#: engine/controller/pipeline surfaces whose contracts rtlint checks
#: statically. Import failures are gated (a stripped environment just
#: sanitizes less).
DEFAULT_MODULES = (
    "ray_tpu.serve.engine",
    "ray_tpu.serve.draft",
    "ray_tpu.serve.handoff",
    "ray_tpu.serve.autoscaler",
    "ray_tpu.serve._replica",
    "ray_tpu.serve._controller",
    "ray_tpu.data.llm",
    "ray_tpu.data.executor",
    "ray_tpu._private.object_store",
)

#: Thread start-sites the leak watch flags by default: the driver
#: threads of the sanitized subsystems. Infra threads (head, core
#: worker, reaper) are long-lived by design and out of scope.
DEFAULT_THREAD_TARGETS = (
    "ray_tpu/serve/engine.py",
    "ray_tpu/serve/draft.py",
    "ray_tpu/data/llm.py",
)

# Originals captured at import time, BEFORE any patching.
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition
_ORIG_SLEEP = time.sleep
_ORIG_THREAD_START = threading.Thread.start

_THIS_FILE = os.path.abspath(__file__)
_STDLIB_SUFFIXES = (os.sep + "threading.py", os.sep + "queue.py")


def annotation_coverage(modules=DEFAULT_MODULES) -> Dict[str, dict]:
    """Static annotation-coverage summary (ISSUE 15 satellite): how
    much of the sanitized driver surface actually carries the
    ``owner=`` / ``holds=`` / ``entry=`` contracts that rtlint
    (RT101/RT102/RT108/RT110) checks statically and this sanitizer
    enforces at runtime. An unannotated driver method or an unnamed
    lock is a gap BOTH tools are blind to, so the fraction is the
    visible size of the shared contract set.

    Per module: ``methods`` / ``annotated`` count the methods of
    driver-owned classes (>= 1 ``owner=``/``entry=`` method) and how
    many of them carry any contract; ``locks`` / ``locks_with_holds``
    count the lock-ish ``self.<attr>`` assignments (``lock|cond|
    mutex``) and how many are named by at least one ``holds=``.
    ``totals`` aggregates with the two fractions. Purely source-based
    (``find_spec``, no import), so it works without :func:`enable`."""
    import ast as _ast
    import importlib.util

    from ..rtlint.annotations import LOCKISH_RE as lockish
    out: Dict[str, dict] = {"modules": {}, "totals": {}}
    for modname in modules:
        try:
            spec = importlib.util.find_spec(modname)
            path = getattr(spec, "origin", None)
            if not path or not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                src = f.read()
            anns = load_annotations(src)
            tree = _ast.parse(src)
        except Exception:  # noqa: BLE001 - coverage is best-effort
            continue
        contracts = {(a.cls, a.name) for a in anns}
        driver_classes = {a.cls for a in anns
                          if a.owner or a.entry}
        holds_named = {h for a in anns for h in a.holds}
        methods = annotated = 0
        locks = set()

        def classes(node, prefix=""):
            for child in _ast.iter_child_nodes(node):
                if isinstance(child, _ast.ClassDef):
                    yield f"{prefix}{child.name}", child
                    yield from classes(child,
                                       f"{prefix}{child.name}.")
                elif isinstance(child, (_ast.FunctionDef,
                                        _ast.AsyncFunctionDef)):
                    yield from classes(child, prefix)

        for qual, cls in classes(tree):
            names = [n.name for n in cls.body
                     if isinstance(n, (_ast.FunctionDef,
                                       _ast.AsyncFunctionDef))]
            if qual in driver_classes:
                methods += len(names)
                annotated += sum((qual, n) in contracts for n in names)
        for node in _ast.walk(tree):
            targets = []
            if isinstance(node, _ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, _ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                while isinstance(t, (_ast.Tuple, _ast.List)) and t.elts:
                    t = t.elts[0]
                if isinstance(t, _ast.Attribute) and \
                        isinstance(t.value, _ast.Name) and \
                        t.value.id == "self" and lockish.search(t.attr):
                    locks.add(t.attr)
        covered = len(locks & holds_named)
        out["modules"][modname] = {
            "methods": methods, "annotated": annotated,
            "locks": len(locks), "locks_with_holds": covered,
        }
    out["totals"] = coverage_totals(out["modules"].values())
    return out


def coverage_totals(rows) -> dict:
    """Aggregate per-module coverage rows into the ``totals`` block —
    THE one implementation, shared by single-process snapshots and the
    CLI's multi-artifact merge so they can never disagree."""
    rows = list(rows)
    methods = sum(r["methods"] for r in rows)
    annotated = sum(r["annotated"] for r in rows)
    locks = sum(r["locks"] for r in rows)
    covered = sum(r["locks_with_holds"] for r in rows)
    return {
        "methods": methods, "annotated": annotated,
        "locks": locks, "locks_with_holds": covered,
        "method_fraction": round(annotated / methods, 3)
        if methods else 1.0,
        "lock_fraction": round(covered / locks, 3) if locks else 1.0,
    }


class RTSanViolation(RuntimeError):
    """A broken owner=/holds= contract, raised at the violation site."""


_MISSING = object()


def _caller_site() -> Optional[Tuple[str, int]]:
    """(abspath, lineno) of the nearest frame outside rtsan itself and
    the threading machinery."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _THIS_FILE and not fn.endswith(_STDLIB_SUFFIXES):
            return os.path.abspath(fn), f.f_lineno
        f = f.f_back
    return None


class _TLS(threading.local):
    def __init__(self):
        self.held: list = []     # [_Held] in acquisition order


class _Held:
    __slots__ = ("lock", "t0", "site")

    def __init__(self, lock, t0, site):
        self.lock = lock
        self.t0 = t0
        self.site = site         # "path:line" of the acquire call


#: Hold-time histogram bucket upper bounds (seconds); the last bucket
#: is unbounded.
HOLD_BUCKETS = (0.001, 0.01, 0.1, 1.0)


class SanLock:
    """Instrumented lock: forwards to a real ``threading.Lock`` /
    ``RLock`` while feeding the sanitizer's acquisition-order graph,
    per-thread held stack, and hold-time histogram. Implements the
    ``_is_owned`` / ``_release_save`` / ``_acquire_restore`` protocol
    so ``threading.Condition`` composes (and tracking follows the wait
    through the release/reacquire)."""

    def __init__(self, inner, site: str, san: "Sanitizer",
                 reentrant: bool):
        self._inner = inner
        self._reentrant = reentrant
        self._san = san
        self._owner: Optional[int] = None   # thread ident
        self._count = 0
        self.site = site       # creation site "relpath:line"
        self.name: Optional[str] = None     # set by holds= resolution

    # ------------------------------------------------------------- protocol
    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._count += 1
            return got
        if blocking:
            # Only BLOCKING acquires feed the lock-order graph: a
            # trylock-and-bail (blocking=False) cannot participate in a
            # deadlock by construction, and recording it would turn the
            # repo's drain patterns into false RS101 cycles.
            self._san.note_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count = 1
            self._san.note_acquired(self)
        return got

    def release(self):
        if self._reentrant and self._count > 1:
            self._count -= 1
            self._inner.release()
            return
        self._owner = None
        self._count = 0
        self._san.note_released(self)
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:   # RLock has no locked() on this python
            return self._owner is not None

    def held_by_current(self) -> bool:
        return self._owner == threading.get_ident()

    # Condition integration: release/reacquire fully (RLock recursion
    # included) while keeping the sanitizer's held stack truthful.
    def _is_owned(self) -> bool:
        return self.held_by_current()

    def _release_save(self):
        state = (self._count, self._owner)
        self._owner = None
        self._count = 0
        self._san.note_released(self)
        if self._reentrant:
            inner_state = self._inner._release_save()
        else:
            self._inner.release()
            inner_state = None
        return state + (inner_state,)

    def _acquire_restore(self, saved):
        count, owner, inner_state = saved
        if self._reentrant:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._count = count
        self._owner = owner
        self._san.note_acquired(self)

    def __repr__(self):
        return (f"<SanLock {'R' if self._reentrant else ''}"
                f"{self.name or self.site} inner={self._inner!r}>")


class SanCondition(_ORIG_CONDITION):
    """Instrumented condition: its lock is (or wraps into) a SanLock,
    so acquisition tracking rides the normal lock protocol; ``wait``
    additionally flags timeout-less waits and waits that still hold
    OTHER locks (RS104) — only the condition's own lock is released
    while parked."""

    def __init__(self, lock, site: str, san: "Sanitizer"):
        super().__init__(lock)
        self._san_site = site
        self._san = san

    def wait(self, timeout=None):
        san = self._san
        if san.active:
            site = _caller_site()
            if timeout is None:
                san.record(
                    "RS104", site,
                    f"timeout-less Condition.wait on the condition "
                    f"created at {self._san_site} — an un-notified (or "
                    f"lost-wakeup) wait parks this thread forever; "
                    f"bound it with a timeout and re-check the "
                    f"predicate in a loop",
                    symbol=f"cond_wait_timeoutless.{self._san_site}")
            others = [h for h in san.tls.held if h.lock is not self._lock]
            if others:
                held = ", ".join(h.lock.name or h.lock.site
                                 for h in others)
                san.record(
                    "RS104", site,
                    f"Condition.wait while still holding [{held}] — "
                    f"wait releases ONLY the condition's own lock "
                    f"({self._san_site}); everything else stays held "
                    f"for the full wait",
                    symbol=f"cond_wait_holding.{self._san_site}")
        return super().wait(timeout)


class _DispatchFn:
    """Wrapper for one compiled jit program: flags invocation while a
    repo lock is held (RS104 — device dispatch under an engine or
    controller lock serializes everyone behind a device-speed wait).
    Attribute access (``_cache_size`` etc.) delegates to the program."""

    def __init__(self, fn, factory_name: str, san: "Sanitizer"):
        self._fn = fn
        self._factory_name = factory_name
        self._san = san

    def __call__(self, *args, **kwargs):
        san = self._san
        if san.active and san.tls.held:
            held = ", ".join(h.lock.name or h.lock.site
                             for h in san.tls.held)
            site = _caller_site()
            san.record(
                "RS104", site,
                f"device dispatch ({self._factory_name} program) while "
                f"holding [{held}] — a dispatch can block for a full "
                f"device step (or a first-call compile); never hold an "
                f"engine/controller lock across it",
                symbol=f"dispatch_under_lock.{self._factory_name}")
        return self._fn(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._fn, item)


class _DispatchFactory:
    """Wrapper for an ``lru_cache``'d ``jit_*`` factory: returns the
    SAME :class:`_DispatchFn` per underlying program, so identity-based
    program counting (``factory(...).cache_info()``,
    ``fn._cache_size()``) keeps working."""

    __rtsan__ = True

    def __init__(self, orig, name: str, san: "Sanitizer"):
        self._orig = orig
        self._name = name
        self._san = san
        # id(fn) -> (fn, wrapper); holding fn keeps the id stable.
        self._wrappers: Dict[int, tuple] = {}

    def __call__(self, *args, **kwargs):
        fn = self._orig(*args, **kwargs)
        ent = self._wrappers.get(id(fn))
        if ent is None or ent[0] is not fn:
            if len(self._wrappers) >= 256:
                # The strong refs here would otherwise pin every
                # lru-evicted program alive forever; identity only
                # matters between consecutive factory calls, so a rare
                # wholesale reset is safe (wrappers rebuild on demand
                # and delegate to the same underlying programs).
                self._wrappers.clear()
            ent = (fn, _DispatchFn(fn, self._name, self._san))
            self._wrappers[id(fn)] = ent
        return ent[1]

    def __getattr__(self, item):
        return getattr(self._orig, item)


class Sanitizer:
    """Per-process sanitizer state. Use the module-level singleton via
    :func:`tools.rtsan.enable`."""

    def __init__(self):
        self.enabled = False
        self.active = False
        self.tls = _TLS()
        self._mu = _ORIG_RLOCK()          # raw: never self-instrumented
        self.roots = [REPO_ROOT] + [
            r for r in os.environ.get("RT_SAN_ROOTS", "").split(":") if r]
        self.findings: List[Finding] = []
        self._finding_keys: set = set()
        self.suppressed: List[dict] = []
        # (site_a, site_b) -> {count, acquire_stack, acquire_site}
        self.edges: Dict[Tuple[str, str], dict] = {}
        self._succ: Dict[str, set] = {}
        self._cycles_seen: set = set()
        # lock site -> {name, count, total_s, max_s, buckets[...]}
        self.holds: Dict[str, dict] = {}
        self.thread_targets = tuple(DEFAULT_THREAD_TARGETS)
        self.thread_allow: list = []
        self._modules_cache: Dict[str, Optional[Module]] = {}
        self._seen_modules: set = set()
        self._instrumented: list = []     # (cls, attr, orig_fn)
        self._factory_patches: list = []  # (module, attr, orig)
        self._atexit_armed = False

    # -------------------------------------------------------------- plumbing
    def _in_roots(self, path: str) -> bool:
        return any(path.startswith(r + os.sep) or path == r
                   for r in self.roots)

    def _rel(self, path: str) -> str:
        for r in self.roots:
            if path.startswith(r + os.sep):
                return os.path.relpath(path, r).replace(os.sep, "/")
        return path.replace(os.sep, "/")

    def _suppressed_at(self, abspath: str, line: int, rule: str) -> bool:
        mod = self._modules_cache.get(abspath, False)
        if mod is False:
            mod = None
            try:
                with open(abspath, encoding="utf-8") as f:
                    src = f.read()
                mod = Module(abspath, self._rel(abspath), src,
                             tag="rtsan")
            except (OSError, SyntaxError, UnicodeDecodeError):
                pass
            self._modules_cache[abspath] = mod
        return mod is not None and mod.suppresses(line, rule)

    def record(self, rule: str, site: Optional[Tuple[str, int]],
               message: str, symbol: str,
               raise_violation: bool = False) -> Optional[Finding]:
        """Register one finding (suppression- and dedup-checked); with
        ``raise_violation`` also raises :class:`RTSanViolation` —
        contract breaks (RS102/RS103) are bugs at the call site, not
        just report lines."""
        path, line = site if site else ("<unknown>", 0)
        if path != "<unknown>" and self._suppressed_at(path, line, rule):
            with self._mu:
                self.suppressed.append({
                    "rule": rule, "path": self._rel(path), "line": line,
                    "symbol": symbol})
            return None
        f = Finding(self._rel(path), line, rule, message, symbol)
        fresh = False
        with self._mu:
            if f.key not in self._finding_keys:
                self._finding_keys.add(f.key)
                self.findings.append(f)
                fresh = True
        if fresh and os.environ.get("RT_SAN_VERBOSE"):
            print(f"rtsan: {f.render()}", file=sys.stderr)
        if raise_violation:
            raise RTSanViolation(f.render())
        return f if fresh else None

    # ------------------------------------------------------------- lock hooks
    def note_acquire(self, lock: SanLock):
        """Pre-acquire: record acquisition-order edges from every held
        lock to this one; a NEW edge gets a stack and a cycle check."""
        if not self.active:
            return
        held = self.tls.held
        if not held:
            return
        b = lock.site
        cycle_msgs = []
        with self._mu:
            for h in held:
                a = h.lock.site
                if a == b or h.lock is lock:
                    continue
                e = self.edges.get((a, b))
                if e is not None:
                    e["count"] += 1
                    continue
                site = _caller_site()
                self.edges[(a, b)] = {
                    "count": 1,
                    "acquire_site": f"{self._rel(site[0])}:{site[1]}"
                    if site else "<unknown>",
                    "acquire_stack": "".join(
                        traceback.format_stack(sys._getframe(2),
                                               limit=16)),
                }
                self._succ.setdefault(a, set()).add(b)
                path = self._find_path(b, a)
                if path is not None:
                    cyc = tuple(sorted(set(path + [b])))
                    if cyc not in self._cycles_seen:
                        self._cycles_seen.add(cyc)
                        cycle_msgs.append((a, b, path, site))
        for a, b, path, site in cycle_msgs:
            chain = " -> ".join(path + [b])
            back_edge = self.edges.get((path[0], path[1])) if \
                len(path) > 1 else self.edges.get((b, a))
            back_stack = (back_edge or {}).get("acquire_stack", "")
            this_stack = self.edges[(a, b)]["acquire_stack"]
            self.record(
                "RS101", site,
                f"lock-order cycle: acquiring {b} while holding {a} "
                f"closes the cycle [{chain}] — two threads taking "
                f"these locks in opposite orders can deadlock even if "
                f"this run never does. Acquiring stack:\n{this_stack}"
                f"Opposite-order stack (first seen):\n{back_stack}",
                symbol=f"cycle.{'->'.join(sorted(set(path + [b])))}")

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """BFS over the order graph; returns the site path src..dst."""
        prev = {src: None}
        frontier = [src]
        while frontier:
            nxt = []
            for n in frontier:
                for m in self._succ.get(n, ()):
                    if m in prev:
                        continue
                    prev[m] = n
                    if m == dst:
                        out = [m]
                        while prev[out[-1]] is not None:
                            out.append(prev[out[-1]])
                        return out[::-1]
                    nxt.append(m)
            frontier = nxt
        return None

    def note_acquired(self, lock: SanLock):
        if not self.active:
            return
        site = _caller_site()
        self.tls.held.append(_Held(
            lock, time.perf_counter(),
            f"{self._rel(site[0])}:{site[1]}" if site else "<unknown>"))

    def note_released(self, lock: SanLock):
        held = self.tls.held
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                h = held.pop(i)
                if not self.active:
                    return
                dt = time.perf_counter() - h.t0
                with self._mu:
                    st = self.holds.get(lock.site)
                    if st is None:
                        st = self.holds[lock.site] = {
                            "name": lock.name, "count": 0,
                            "total_s": 0.0, "max_s": 0.0,
                            "buckets": [0] * (len(HOLD_BUCKETS) + 1)}
                    if lock.name and not st["name"]:
                        st["name"] = lock.name
                    st["count"] += 1
                    st["total_s"] += dt
                    st["max_s"] = max(st["max_s"], dt)
                    for j, ub in enumerate(HOLD_BUCKETS):
                        if dt < ub:
                            st["buckets"][j] += 1
                            break
                    else:
                        st["buckets"][-1] += 1
                return

    # ------------------------------------------------------------- factories
    def _lock_factory(self, orig, reentrant: bool):
        san = self

        def factory():
            inner = orig()
            f = sys._getframe(1)
            path = f.f_code.co_filename
            if not san._in_roots(os.path.abspath(path)):
                return inner
            site = f"{san._rel(os.path.abspath(path))}:{f.f_lineno}"
            return SanLock(inner, site, san, reentrant)

        factory.__rtsan__ = True
        factory.__orig__ = orig
        return factory

    def _condition_factory(self, orig_cond):
        san = self

        def factory(lock=None):
            f = sys._getframe(1)
            path = os.path.abspath(f.f_code.co_filename)
            if not san._in_roots(path):
                return orig_cond(lock)
            site = f"{san._rel(path)}:{f.f_lineno}"
            if lock is None:
                lock = SanLock(_ORIG_RLOCK(), site, san, True)
            return SanCondition(lock, site, san)

        factory.__rtsan__ = True
        factory.__orig__ = orig_cond
        return factory

    def _san_sleep(self, secs):
        if self.active and self.tls.held:
            held = ", ".join(h.lock.name or h.lock.site
                             for h in self.tls.held)
            self.record(
                "RS104", _caller_site(),
                f"time.sleep({secs!r}) while holding [{held}] — every "
                f"thread queued on those locks stalls for the whole "
                f"sleep; release first, or wait on a condition",
                symbol="sleep_under_lock")
        return _ORIG_SLEEP(secs)

    def _san_thread_start(self):
        san = self

        def start(t):
            if san.enabled:
                site = _caller_site()
                if site is not None:
                    try:
                        t._rtsan_start_site = \
                            f"{san._rel(site[0])}:{site[1]}"
                        t._rtsan_start_abs = site
                    except Exception:  # noqa: BLE001 - slots-only Thread
                        pass
            return _ORIG_THREAD_START(t)

        start.__rtsan__ = True
        return start

    # -------------------------------------------------------- instrumentation
    def _instrument_module(self, modname: str):
        """Wrap every annotated method of ``modname`` with the
        owner/holds contract check. Import failures are gated — an
        environment missing the module just sanitizes less."""
        import importlib

        try:
            mod = importlib.import_module(modname)
            path = getattr(mod, "__file__", None)
            if not path:
                return
            with open(path, encoding="utf-8") as f:
                src = f.read()
            anns = load_annotations(src)
        except Exception:  # noqa: BLE001 - gated: sanitize what imports
            return
        abspath = os.path.abspath(path)
        for ann in anns:
            if ann.cls is None:
                continue
            obj = mod
            for part in ann.cls.split("."):
                obj = getattr(obj, part, None)
                if obj is None:
                    break
            if not isinstance(obj, type):
                continue
            fn = obj.__dict__.get(ann.name)
            if not callable(fn) or getattr(fn, "__rtsan_contract__", None):
                continue
            setattr(obj, ann.name,
                    self._wrap_contract(fn, ann, abspath, obj.__name__))
            self._instrumented.append((obj, ann.name, fn))

    def _wrap_contract(self, fn, ann, abspath: str, clsname: str):
        import functools

        san = self
        holds = ann.holds
        is_owner = ann.owner == "driver"
        is_entry = ann.entry == "driver"
        site = (abspath, ann.lineno)

        @functools.wraps(fn)
        def wrapper(self_obj, *args, **kwargs):
            if san.active:
                san.check_contract(self_obj, holds, is_owner, is_entry,
                                   site, clsname, ann.name)
            return fn(self_obj, *args, **kwargs)

        wrapper.__rtsan_contract__ = ann
        return wrapper

    def check_contract(self, obj, holds, is_owner: bool, is_entry: bool,
                       site, clsname: str, method: str):
        for name in holds:
            lk = getattr(obj, name, _MISSING)
            if lk is _MISSING:
                self.record(
                    "RS102", site,
                    f"{clsname}.{method} is annotated 'holds={name}' "
                    f"but self.{name} does not exist on this instance "
                    f"— the contract is unverifiable (hard error; fix "
                    f"the annotation or the attribute)",
                    symbol=f"{clsname}.{method}.holds_missing.{name}",
                    raise_violation=True)
                continue
            if isinstance(lk, SanLock):
                if lk.name is None:
                    lk.name = f"{clsname}.{name}"
                held = lk.held_by_current()
            elif hasattr(lk, "_is_owned"):     # raw RLock / Condition
                held = lk._is_owned()
            elif hasattr(lk, "locked"):        # raw Lock: best-effort
                held = lk.locked()
            else:
                held = False
            if not held:
                self.record(
                    "RS102", site,
                    f"{clsname}.{method} entered without self.{name} "
                    f"held — the 'holds={name}' contract promises "
                    f"every caller locks first",
                    symbol=f"{clsname}.{method}.holds.{name}",
                    raise_violation=True)
        if is_owner or is_entry:
            cur = threading.current_thread()
            prev = getattr(obj, "_rtsan_owner", None)
            if is_entry or prev is None or not prev.is_alive():
                # entry=driver (re)binds: the caller IS the driver by
                # definition (engine restart, pipeline reuse); a dead
                # owner also rebinds (ownership transfers to the
                # failing thread once the driver is confirmed dead).
                try:
                    obj._rtsan_owner = cur
                except Exception:  # noqa: BLE001 - slots-only instance
                    pass
            elif prev is not cur:
                self.record(
                    "RS103", site,
                    f"{clsname}.{method} (owner=driver) called from "
                    f"thread {cur.name!r} but the registered driver is "
                    f"{prev.name!r} (alive) — only the driver thread "
                    f"may run this",
                    symbol=f"{clsname}.{method}.owner",
                    raise_violation=True)

    def _wrap_jit_factories(self):
        try:
            from ray_tpu.models import gpt_decode
        except Exception:  # noqa: BLE001 - gated: no device surface here
            return
        for name in dir(gpt_decode):
            if not name.startswith("jit_"):
                continue
            orig = getattr(gpt_decode, name)
            if not callable(orig) or getattr(orig, "__rtsan__", False):
                continue
            setattr(gpt_decode, name, _DispatchFactory(orig, name, self))
            self._factory_patches.append((gpt_decode, name, orig))

    # -------------------------------------------------------------- lifecycle
    def enable(self, modules=DEFAULT_MODULES, active: bool = True,
               wrap_dispatch: bool = True) -> "Sanitizer":
        """Patch everything. Idempotent; repeat calls can only widen
        ``active`` and instrument not-yet-seen modules."""
        fresh = not self.enabled
        self.enabled = True
        self.active = self.active or active
        if fresh:
            threading.Lock = self._lock_factory(_ORIG_LOCK, False)
            threading.RLock = self._lock_factory(_ORIG_RLOCK, True)
            threading.Condition = self._condition_factory(_ORIG_CONDITION)
            time.sleep = self._san_sleep
            threading.Thread.start = self._san_thread_start()
        for m in modules:
            if m not in self._seen_modules:
                self._seen_modules.add(m)
                self._instrument_module(m)
        if wrap_dispatch and modules:
            self._wrap_jit_factories()
        out_dir = os.environ.get("RT_SAN_DIR")
        if out_dir and not self._atexit_armed:
            self._atexit_armed = True
            atexit.register(self._atexit_dump, out_dir)
        return self

    def disable(self) -> "Sanitizer":
        """Restore every patched identity (the zero-overhead path)."""
        if not self.enabled:
            return self
        self.active = False
        self.enabled = False
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK
        threading.Condition = _ORIG_CONDITION
        time.sleep = _ORIG_SLEEP
        threading.Thread.start = _ORIG_THREAD_START
        for cls, attr, orig in reversed(self._instrumented):
            setattr(cls, attr, orig)
        self._instrumented.clear()
        self._seen_modules.clear()
        for mod, attr, orig in reversed(self._factory_patches):
            setattr(mod, attr, orig)
        self._factory_patches.clear()
        return self

    @contextmanager
    def activated(self):
        """Temporarily turn recording/enforcement on (the per-test
        opt-in window used by conftest)."""
        prev = self.active
        self.active = True
        try:
            yield self
        finally:
            self.active = prev

    # ------------------------------------------------------------- thread watch
    @contextmanager
    def thread_watch(self, targets=None, allow=(), grace_s: float = 0.2):
        """Leak detector: threads STARTED inside this window (from a
        target start-site) still alive at its end are RS105 findings.
        ``targets`` filters by start-site suffix (default: the
        engine/drafter/pipeline files); ``allow`` adds name substrings
        to ignore on top of :attr:`thread_allow`."""
        targets = tuple(targets) if targets is not None \
            else self.thread_targets
        before = set(threading.enumerate())
        try:
            yield
        finally:
            if self.active:
                leaked = []
                for t in threading.enumerate():
                    if t in before or not t.is_alive():
                        continue
                    site = getattr(t, "_rtsan_start_site", None)
                    abs_site = getattr(t, "_rtsan_start_abs", None)
                    if site is None or abs_site is None:
                        continue
                    path = site.rsplit(":", 1)[0]
                    if targets and not any(
                            path.endswith(x) or abs_site[0].endswith(x)
                            for x in targets):
                        continue
                    if any(p in t.name
                           for p in list(allow) + self.thread_allow):
                        continue
                    leaked.append((t, site, abs_site))
                for t, site, abs_site in leaked:
                    t.join(grace_s)   # a thread mid-exit is not a leak
                    if not t.is_alive():
                        continue
                    path = site.rsplit(":", 1)[0]
                    self.record(
                        "RS105", abs_site,
                        f"thread {t.name!r} started at {site} is still "
                        f"alive at watch teardown — a leaked driver "
                        f"keeps its pool (and a device queue slot) "
                        f"pinned forever; shut the owner down",
                        symbol=f"leaked_thread.{path}")

    # --------------------------------------------------------------- reports
    def snapshot(self) -> dict:
        """JSON-ready state: the run artifact ``python -m tools.rtsan
        --report`` renders."""
        coverage = annotation_coverage(
            tuple(sorted(self._seen_modules)) or DEFAULT_MODULES)
        with self._mu:
            return {
                "version": 1,
                "pid": os.getpid(),
                "coverage": coverage,
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": list(self.suppressed),
                "edges": [
                    {"from": a, "to": b,
                     "count": e["count"],
                     "acquire_site": e.get("acquire_site", ""),
                     "acquire_stack": e.get("acquire_stack", "")}
                    for (a, b), e in sorted(self.edges.items())],
                "holds": [
                    {"site": s, **st}
                    for s, st in sorted(self.holds.items())],
            }

    def dump(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def _atexit_dump(self, out_dir: str):
        try:
            os.makedirs(out_dir, exist_ok=True)
            self.dump(os.path.join(out_dir, f"rtsan-{os.getpid()}.json"))
        except Exception:  # noqa: BLE001 - best-effort on teardown
            pass

    def gate(self, extra: Optional[List[dict]] = None,
             baseline_path: str = DEFAULT_BASELINE) -> dict:
        """The --check-style verdict: findings (plus ``extra`` finding
        dicts merged from worker artifacts) not in the baseline are
        NEW and must fail the suite."""
        baseline = load_baseline(baseline_path)
        merged: Dict[str, Finding] = {}
        with self._mu:
            for f in self.findings:
                merged[f.key] = f
        for d in extra or ():
            f = Finding(d["path"], d["line"], d["rule"], d["message"],
                        d["symbol"])
            merged.setdefault(f.key, f)
        new = sorted(f for f in merged.values() if f.key not in baseline)
        old = sorted(f for f in merged.values() if f.key in baseline)
        return {"new": new, "baselined": old,
                "suppressed": len(self.suppressed)}

    def stats_block(self, path_filter: str = "serve/") -> dict:
        """The ``engine.stats()`` sanitizer block: process findings
        count plus max hold time per named lock whose site matches
        ``path_filter`` (chaos benchmarks assert zero findings)."""
        with self._mu:
            return {
                "findings": len(self.findings),
                "max_hold_s": {
                    (st["name"] or s): round(st["max_s"], 6)
                    for s, st in sorted(self.holds.items())
                    if path_filter in s},
            }


#: THE per-process sanitizer.
SANITIZER = Sanitizer()
