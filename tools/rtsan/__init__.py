"""rtsan: runtime enforcement of rtlint's concurrency contracts.

rtlint (tools/rtlint) checks the annotations *statically*; rtsan checks
that execution actually honors them — the same ``# rtlint:
owner=driver`` / ``holds=<lock>`` / ``entry=driver`` comments, read
through the same loader (:mod:`tools.rtlint.annotations`), become
runtime assertions, and a global lock-acquisition-order graph catches
ABBA deadlocks that never fire in the run that reveals them.

Usage::

    RT_SAN=1 pytest tests/            # sanitize the whole suite
    pytest tests/                     # engine/chaos/data-llm modules
                                      # sanitized via the conftest
                                      # opt-in list (tier-1 default)
    RT_SAN=0 pytest tests/            # fully off (no patching at all)
    python -m tools.rtsan --report    # lock-order graph + hold times
                                      # from the last run's artifact

Checks RS101 (lock-order cycle), RS102 (holds= violated / dangling —
raises), RS103 (owner=driver violated — raises), RS104 (blocking under
a lock: time.sleep, timeout-less Condition.wait, device dispatch),
RS105 (leaked thread at watch teardown). Suppress with ``# rtsan:
disable=RSxxx <why>`` on the reported line (or the line above / the
enclosing ``def``); grandfathered keys live in
``tools/rtsan/baseline.json`` — shipped EMPTY and expected to stay so.
"""
from .core import (DEFAULT_BASELINE, DEFAULT_MODULES, REPO_ROOT,
                   SANITIZER, RTSanViolation, SanCondition, Sanitizer,
                   SanLock, annotation_coverage)

RULES = {
    "RS101": "lock-order cycle (potential ABBA deadlock)",
    "RS102": "holds=<lock> contract violated or names a missing attr",
    "RS103": "owner=driver method ran off the registered driver thread",
    "RS104": "blocking under a lock (sleep / timeout-less wait / "
             "device dispatch)",
    "RS105": "thread leaked past its watch scope",
}


def enable(modules=DEFAULT_MODULES, active: bool = True,
           wrap_dispatch: bool = True) -> Sanitizer:
    return SANITIZER.enable(modules=modules, active=active,
                            wrap_dispatch=wrap_dispatch)


def disable() -> Sanitizer:
    return SANITIZER.disable()


def is_enabled() -> bool:
    return SANITIZER.enabled


def is_active() -> bool:
    return SANITIZER.enabled and SANITIZER.active


def activated():
    return SANITIZER.activated()


def thread_watch(targets=None, allow=(), grace_s: float = 0.2):
    return SANITIZER.thread_watch(targets=targets, allow=allow,
                                  grace_s=grace_s)


def findings():
    return list(SANITIZER.findings)


def gate(extra=None, baseline_path: str = DEFAULT_BASELINE) -> dict:
    return SANITIZER.gate(extra=extra, baseline_path=baseline_path)


def snapshot() -> dict:
    return SANITIZER.snapshot()


def dump(path: str) -> str:
    return SANITIZER.dump(path)


def stats_block(path_filter: str = "serve/") -> dict:
    return SANITIZER.stats_block(path_filter)


__all__ = ["DEFAULT_BASELINE", "DEFAULT_MODULES", "REPO_ROOT", "RULES",
           "RTSanViolation", "SANITIZER", "SanCondition", "Sanitizer",
           "SanLock", "activated", "annotation_coverage", "disable",
           "dump", "enable", "findings", "gate", "is_active",
           "is_enabled", "snapshot", "stats_block", "thread_watch"]
