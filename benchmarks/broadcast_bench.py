"""Object broadcast benchmark (VERDICT round 2, item 3): one large
object fanned out to N workers across 2 shm domains — the weight-sync
shape. Reference scale point: 1GiB to 50 nodes in 15.86s
(BASELINE.md:32).

One ``rt.put`` → N consumers passing the ref; same-domain consumers
attach the single shm segment, cross-domain consumers chunk-pull and
register as copies (later pullers stripe across them).

Run: ``python benchmarks/broadcast_bench.py [--mb 1024] [--workers 8]``
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=1024)
    parser.add_argument("--workers", type=int, default=8)
    args = parser.parse_args()

    import numpy as np

    import ray_tpu as rt
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    n1 = cluster.add_node(num_cpus=args.workers // 2)
    n2 = cluster.add_node(num_cpus=args.workers // 2)
    rt = cluster.connect()
    strat = rt.NodeAffinitySchedulingStrategy

    payload = np.random.randint(0, 255, args.mb * (1 << 20),
                                dtype=np.uint8)

    @rt.remote
    def consume(x):
        return int(x[0]) + int(x[-1])

    # Warm the worker pools so spawn time stays out of the measurement.
    rt.get([consume.options(
        scheduling_strategy=strat(n.node_id)).remote(
            np.zeros(4, np.uint8))
        for n in (n1, n2) for _ in range(args.workers // 2)], timeout=120)

    t0 = time.perf_counter()
    ref = rt.put(payload)
    want = int(payload[0]) + int(payload[-1])
    refs = [consume.options(
        scheduling_strategy=strat((n1, n2)[i % 2].node_id)).remote(ref)
        for i in range(args.workers)]
    out = rt.get(refs, timeout=600)
    wall = time.perf_counter() - t0
    assert out == [want] * args.workers

    gib = args.mb / 1024
    print(json.dumps({
        "metric": "broadcast_to_workers",
        "value": round(wall, 2), "unit": "s",
        "size_gib": round(gib, 3), "workers": args.workers,
        "domains": 2,
        "effective_gbps": round(gib * args.workers / wall, 2),
        "reference_point": "1GiB to 50 nodes in 15.86s "
                           "(BASELINE.md:32, multi-host cluster)"}))
    rt.shutdown()
    cluster.shutdown()


if __name__ == "__main__":
    main()
