"""Million-user cluster-serving harness: the standing A/B for every
scaling PR (ISSUE 17).

Drives the SLO-driven autoscaler end to end with a synthetic workload
shaped like real multi-tenant traffic:

- **diurnal arrival curve**: open-loop Poisson arrivals whose rate
  sweeps a raised-cosine between ``--rate-lo`` and ``--rate-hi`` over
  ``--period`` seconds (a compressed day);
- **heavy-tailed lengths**: per-tenant lognormal output lengths (the
  p99 stream is ~an order of magnitude longer than the median);
- **multi-tenant mix**: tenants with zipf-ish weights and distinct
  length profiles, users drawn from a million-id space so cache-key
  cardinality looks like production, not like a loop variable;
- **chaos**: a replica kill AND a controller kill mid-ramp. Replicas
  are detached named actors and the desired state is journaled, so the
  revived controller must adopt the fleet (zero orphans) and every
  client stream must survive (resumable replay; routers degrade to
  cached membership while the controller is down).

Two standing comparisons:

- the **chaos row** (``serve_cluster_autoscale_chaos``): zero broken
  streams, zero orphan replicas after convergence, and the convergence
  time after each fault;
- the **A/B row** (``serve_cluster_goodput_ab``, full mode): goodput
  per chip-second — completed in-SLO tokens divided by the integral of
  live replica count — autoscaled vs a static fleet pinned at
  ``max_replicas``, same workload seed. Idle accelerator time is the
  dominant serving cost on TPUs; the autoscaled run must win this at
  equal SLO.

``--smoke`` is the tier-1 CI hook: a short curve, both chaos kills,
asserts convergence + zero broken streams + zero orphans — and (via
the implied ``--blackbox``) that the flight recorder reconstructs the
killed-replica request's full story from the dead process's ring.

``--blackbox`` (ISSUE 19) arms the cluster flight recorder: every
process (harness, router, replicas, controller) appends to a crash-
durable ring under a shared events directory; after the chaos run the
harness merges the rings — including the SIGKILLed replica's — into
one timeline and reconstructs the resumed request's cross-process
story (admission → dispatches → kill → router resume → token-identity
verdict).

JSON lines on stdout, one row per metric (serve_gpt.py idiom).
"""
import argparse
import json
import math
import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu._private import events as _events  # noqa: E402

VOCAB = 50257

#: tenant -> (arrival weight, lognormal mu, sigma) for output lengths.
TENANTS = {
    "chat": (0.6, 2.2, 0.6),
    "code": (0.3, 2.8, 0.8),
    "batch": (0.1, 3.2, 1.0),
}
USER_SPACE = 1_000_000


def token_at(seed: int, i: int) -> int:
    """The deterministic stream: token i of the stream seeded ``seed``.
    Shared by replica and client, so a resumed stream is verifiable
    token by token."""
    return (seed * 1_000_003 + i * 7_919) % VOCAB


def sample_request(rng: random.Random, max_out: int) -> dict:
    tenant = rng.choices(list(TENANTS), weights=[w for w, _, _ in
                                                TENANTS.values()])[0]
    _, mu, sigma = TENANTS[tenant]
    out = max(2, min(max_out, int(rng.lognormvariate(mu, sigma))))
    user = rng.randrange(USER_SPACE)
    return {"tenant": tenant, "user": user, "out": out,
            "seed": (user * 2_654_435_761 + out) % (1 << 31)}


def diurnal_rate(t: float, period: float, lo: float, hi: float) -> float:
    """Raised-cosine arrival rate: trough at t=0, peak at period/2."""
    return lo + (hi - lo) * 0.5 * (1 - math.cos(2 * math.pi * t / period))


def make_deployment(serve, *, autoscaled: bool, max_replicas: int,
                    tok_s: float):
    ac = None
    num = max_replicas
    if autoscaled:
        ac = serve.AutoscalingConfig(
            min_replicas=1, max_replicas=max_replicas,
            target_ongoing_requests=1.5, upscale_delay_s=0.2,
            downscale_delay_s=1.0, metrics_interval_s=0.1,
            ema_tau_s=0.5, hysteresis=0.1, upscale_step=2,
            downscale_step=1)
        num = 1

    @serve.deployment(num_replicas=num, max_ongoing_requests=4,
                      autoscaling_config=ac, health_check_period_s=0.3,
                      graceful_shutdown_timeout_s=15.0)
    class SynthLLM:
        """Deterministic synthetic decode: one token per ``tok_s`` of
        driver sleep. A resumed stream replays identically (the prefix
        is suppressed replica-side), so chaos correctness is checkable
        token by token."""

        def __call__(self, request):
            seed, out = int(request["seed"]), int(request["out"])
            for i in range(out):
                time.sleep(tok_s)
                yield token_at(seed, i)

    return SynthLLM


class FleetSampler(threading.Thread):
    """Polls serve.status() to integrate replica count over time —
    the chip-seconds denominator of the goodput metric — and records
    the replica timeline for convergence analysis."""

    def __init__(self, serve, app: str, dname: str, poll_s: float = 0.2):
        super().__init__(daemon=True, name="fleet-sampler")
        self.serve, self.app, self.dname = serve, app, dname
        self.poll_s = poll_s
        self.chip_seconds = 0.0
        self.timeline = []          # (t, replicas, target)
        self.peak = 0
        self._halt = threading.Event()

    def run(self):
        last = time.monotonic()
        while not self._halt.is_set():
            time.sleep(self.poll_s)
            now = time.monotonic()
            try:
                st = self.serve.status()["applications"][self.app][
                    "deployments"][self.dname]
                n, tgt = int(st["replicas"]), int(st["target"])
            except Exception:  # noqa: BLE001 - controller down mid-chaos
                continue
            self.chip_seconds += n * (now - last)
            last = now
            self.peak = max(self.peak, n)
            self.timeline.append((now, n, tgt))

    def stop(self):
        self._halt.set()


def live_replica_names(app: str) -> set:
    from ray_tpu.util.state import list_actors

    prefix = f"SERVE_REPLICA:{app}:"
    return {a["name"] for a in list_actors()
            if a["state"] == "ALIVE"
            and (a.get("name") or "").startswith(prefix)}


def membership_names(app: str, dname: str) -> set:
    import ray_tpu as rt
    from ray_tpu.serve.autoscaler import replica_actor_name
    from ray_tpu.serve.config import SERVE_CONTROLLER_NAME

    ctrl = rt.get_actor(SERVE_CONTROLLER_NAME, timeout=10)
    info = rt.get(ctrl.get_replicas.remote(app, dname), timeout=15)
    return {replica_actor_name(app, rid)
            for rid in (info or {"replicas": {}})["replicas"]}


def wait_converged(app: str, dname: str, timeout_s: float = 45.0):
    """Seconds until the live named-actor census exactly matches the
    controller membership (no orphans, no ghosts); None on timeout."""
    t0 = time.monotonic()
    deadline = t0 + timeout_s
    while time.monotonic() < deadline:
        try:
            members = membership_names(app, dname)
            if members and live_replica_names(app) == members:
                return time.monotonic() - t0
        except Exception:  # noqa: BLE001 - controller mid-revival
            pass
        time.sleep(0.3)
    return None


def revive_controller(timeout_s: float = 45.0):
    import ray_tpu as rt
    from ray_tpu.serve import api as sapi

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            ctrl = sapi._get_or_create_controller()
            rt.get(ctrl.status.remote(), timeout=5)
            with sapi._client_lock:
                sapi._client["controller"] = ctrl
            return ctrl
        except Exception:  # noqa: BLE001 - dead name not reaped yet
            time.sleep(0.3)
    raise TimeoutError("controller did not revive")


def run_cell(args, *, autoscaled: bool, chaos: bool) -> dict:
    """One A/B cell: the full diurnal curve against one fleet config.
    Returns the stats row; callers own the asserts."""
    import ray_tpu as rt
    from ray_tpu import serve

    app = "cluster_auto" if autoscaled else "cluster_static"
    dname = "SynthLLM"
    serve.start(proxy=False)
    SynthLLM = make_deployment(serve, autoscaled=autoscaled,
                               max_replicas=args.max_replicas,
                               tok_s=args.tok_s)
    handle = serve.run(SynthLLM.bind(), name=app, route_prefix=None)
    # Warm: one full stream before the clock starts.
    warm = {"seed": 1, "out": 4, "tenant": "chat", "user": 0}
    assert [int(x) for x in handle.options(stream=True).remote(warm)] == \
        [token_at(1, i) for i in range(4)]

    sampler = FleetSampler(serve, app, dname)
    sampler.start()

    rng = random.Random(args.seed)
    lock = threading.Lock()
    stats = {"requests": 0, "completed": 0, "good": 0, "good_tokens": 0,
             "tokens": 0, "broken": [], "max_stall_ms": 0.0,
             "resumed": []}

    threads = []

    def client(req: dict):
        t0 = time.monotonic()
        slo_s = req["out"] * args.tok_s * 6 + 3.0
        toks, last, stall = [], time.monotonic(), 0.0
        it = None
        try:
            it = handle.options(stream=True, resumable=True,
                                timeout_s=slo_s + 60.0).remote(req)
            for item in it:
                now = time.monotonic()
                stall = max(stall, now - last)
                last = now
                toks.append(int(item))
            expect = [token_at(req["seed"], i) for i in range(req["out"])]
            identical = toks == expect
            # The client-side close of the correlation loop: the
            # flight recorder's reconstruction ends on this verdict.
            _events.emit("client.verdict", request=it.request_id,
                         ok=identical, identical=identical,
                         tokens=len(toks), resumes=it.resumes)
            if not identical:
                raise AssertionError(
                    f"stream corrupted: {toks[:4]}... != {expect[:4]}...")
            wall = time.monotonic() - t0
            with lock:
                stats["completed"] += 1
                stats["tokens"] += len(toks)
                stats["max_stall_ms"] = max(stats["max_stall_ms"],
                                            stall * 1000)
                if it.resumes:
                    stats["resumed"].append((it.request_id, it.resumes))
                if wall <= slo_s:
                    stats["good"] += 1
                    stats["good_tokens"] += len(toks)
        except Exception as e:  # noqa: BLE001 - every failure is a
            # broken client stream, the thing this harness exists to
            # count; asserted zero by the caller
            if it is not None:
                _events.emit("client.verdict", request=it.request_id,
                             ok=False, identical=False,
                             tokens=len(toks),
                             cause=type(e).__name__)
            with lock:
                stats["broken"].append(repr(e)[:200])

    kills = 0
    convergences = []
    # The flight-recorder anchor stream (--blackbox): one long pinned
    # request launched just before the replica kill, whose SERVING
    # replica becomes the kill target — so the chaos run always
    # produces a request whose story crosses a dead process's ring.
    pinned = {"rid": None, "request": None}

    def pinned_client():
        req = {"seed": 424_242, "tenant": "chat", "user": 0,
               "out": max(16, int(4.0 / args.tok_s))}
        expect = [token_at(req["seed"], i) for i in range(req["out"])]
        toks = []
        it = None
        try:
            it = handle.options(stream=True, resumable=True,
                                timeout_s=180.0).remote(req)
            for item in it:
                toks.append(int(item))
                if pinned["rid"] is None:
                    pinned["request"] = it.request_id
                    pinned["rid"] = it._rid
            identical = toks == expect
            _events.emit("client.verdict", request=it.request_id,
                         ok=identical, identical=identical,
                         tokens=len(toks), resumes=it.resumes)
            with lock:
                if it.resumes:
                    stats["resumed"].insert(
                        0, (it.request_id, it.resumes))
                if not identical:
                    stats["broken"].append(
                        f"pinned stream corrupted: {toks[:4]}...")
        except Exception as e:  # noqa: BLE001 - a broken pinned
            # stream is a broken stream like any other
            if it is not None:
                _events.emit("client.verdict", request=it.request_id,
                             ok=False, identical=False,
                             tokens=len(toks), cause=type(e).__name__)
            with lock:
                stats["broken"].append(f"pinned: {e!r}"[:200])

    def chaos_monkey():
        """One replica kill, then one controller kill, both mid-ramp
        (the autoscaler is actively moving targets when they land)."""
        nonlocal kills
        time.sleep(args.duration * 0.3)
        try:
            from ray_tpu.serve.autoscaler import replica_actor_name

            victim = None
            if args.blackbox:
                threading.Thread(target=pinned_client, daemon=True,
                                 name="pinned-client").start()
                deadline = time.monotonic() + 10.0
                while pinned["rid"] is None and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                if pinned["rid"] is not None:
                    victim = replica_actor_name(app, pinned["rid"])
                    _events.emit("chaos.kill", target="replica",
                                 replica=pinned["rid"],
                                 request=pinned["request"])
            if victim is None:
                victims = membership_names(app, dname)
                victim = sorted(victims)[0] if victims else None
            if victim is not None:
                rt.kill(rt.get_actor(victim, timeout=5))
                kills += 1
                c = wait_converged(app, dname)
                convergences.append(("replica_kill", c))
        except Exception as e:  # noqa: BLE001 - surfaced via the row
            convergences.append(("replica_kill", f"error: {e!r}"))
        time.sleep(args.duration * 0.2)
        try:
            from ray_tpu.serve.config import SERVE_CONTROLLER_NAME

            _events.emit("chaos.kill", target="controller")
            rt.kill(rt.get_actor(SERVE_CONTROLLER_NAME, timeout=5))
            kills += 1
            revive_controller()
            c = wait_converged(app, dname)
            convergences.append(("controller_kill", c))
        except Exception as e:  # noqa: BLE001 - surfaced via the row
            convergences.append(("controller_kill", f"error: {e!r}"))

    monkey = None
    if chaos:
        monkey = threading.Thread(target=chaos_monkey, daemon=True,
                                  name="chaos-monkey")
        monkey.start()

    # Open-loop Poisson arrivals along the diurnal curve.
    t_start = time.monotonic()
    while True:
        t = time.monotonic() - t_start
        if t >= args.duration:
            break
        rate = diurnal_rate(t, args.period, args.rate_lo, args.rate_hi)
        time.sleep(rng.expovariate(rate) if rate > 0 else 0.1)
        req = sample_request(rng, args.max_out)
        stats["requests"] += 1
        th = threading.Thread(target=client, args=(req,), daemon=True)
        th.start()
        threads.append(th)

    for th in threads:
        th.join(timeout=180)
    if monkey is not None:
        monkey.join(timeout=180)
    final_conv = wait_converged(app, dname)
    sampler.stop()
    sampler.join(timeout=10)

    members = membership_names(app, dname)
    census = live_replica_names(app)
    orphans = sorted(census - members)
    wall = time.monotonic() - t_start
    chips = max(sampler.chip_seconds, 1e-9)
    row = {
        "app": app, "autoscaled": autoscaled, "chaos": chaos,
        "wall_s": round(wall, 2),
        "requests": stats["requests"], "completed": stats["completed"],
        "broken_streams": len(stats["broken"]),
        "broken_detail": stats["broken"][:4],
        "in_slo": stats["good"],
        "tokens": stats["tokens"],
        "chip_seconds": round(chips, 2),
        "goodput_tokens_per_chip_s": round(stats["good_tokens"] / chips,
                                           3),
        "peak_replicas": sampler.peak,
        "max_stall_ms": round(stats["max_stall_ms"], 1),
        "kills": kills,
        "convergence": [(k, round(c, 2) if isinstance(c, float) else c)
                        for k, c in convergences],
        "converged": final_conv is not None and all(
            isinstance(c, float) for _, c in convergences),
        "orphans": len(orphans),
        "orphan_names": orphans,
        "resumed_requests": stats["resumed"][:8],
    }
    serve.delete(app)
    serve.shutdown()
    return row


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 hook: short curve, both chaos kills, "
                        "hard asserts")
    p.add_argument("--duration", type=float, default=60.0,
                   help="seconds of arrival curve per cell")
    p.add_argument("--period", type=float, default=40.0,
                   help="diurnal period (the compressed day)")
    p.add_argument("--rate-lo", type=float, default=0.5)
    p.add_argument("--rate-hi", type=float, default=6.0)
    p.add_argument("--max-replicas", type=int, default=3)
    p.add_argument("--max-out", type=int, default=48,
                   help="output-length cap (heavy tail clamps here)")
    p.add_argument("--tok-s", type=float, default=0.02,
                   help="synthetic decode seconds per token")
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--no-ab", action="store_true",
                   help="skip the static baseline cell")
    p.add_argument("--blackbox", action="store_true",
                   help="arm the flight recorder cluster-wide; dump "
                        "the merged timeline and one request "
                        "reconstruction after the chaos cell")
    p.add_argument("--events-dir", default=None,
                   help="events directory for --blackbox (default: a "
                        "fresh temp dir)")
    args = p.parse_args()

    if args.smoke:
        args.duration = 18.0
        args.period = 12.0
        args.rate_lo, args.rate_hi = 0.5, 4.0
        args.max_out = 24
        args.tok_s = 0.01
        args.no_ab = True
        args.blackbox = True

    events_dir = None
    if args.blackbox:
        # Before rt.init: workers inherit the environment, so every
        # process in the cluster — replicas included — opens its own
        # ring under this directory from its first emit.
        events_dir = args.events_dir or tempfile.mkdtemp(
            prefix="rt-blackbox-")
        os.environ[_events.EVENTS_DIR_ENV] = events_dir

    import ray_tpu as rt

    rt.init(num_cpus=8, num_tpus=0, ignore_reinit_error=True)
    try:
        auto = run_cell(args, autoscaled=True, chaos=True)
        auto_row = dict(auto, metric="serve_cluster_autoscale_chaos",
                        value=auto["broken_streams"],
                        unit="broken_streams", smoke=bool(args.smoke))
        print(json.dumps(auto_row))

        if not args.no_ab:
            static = run_cell(args, autoscaled=False, chaos=False)
            print(json.dumps(dict(static,
                                  metric="serve_cluster_static_baseline",
                                  value=static[
                                      "goodput_tokens_per_chip_s"],
                                  unit="tokens_per_chip_s")))
            ab = {
                "metric": "serve_cluster_goodput_ab",
                "value": round(auto["goodput_tokens_per_chip_s"]
                               - static["goodput_tokens_per_chip_s"], 3),
                "unit": "tokens_per_chip_s_delta",
                "autoscaled": auto["goodput_tokens_per_chip_s"],
                "static": static["goodput_tokens_per_chip_s"],
                "autoscaled_in_slo": auto["in_slo"],
                "static_in_slo": static["in_slo"],
            }
            print(json.dumps(ab))
            assert auto["goodput_tokens_per_chip_s"] > \
                static["goodput_tokens_per_chip_s"], \
                "autoscaled fleet must beat the static fleet on " \
                "goodput per chip-second at equal SLO"

        assert auto["broken_streams"] == 0, auto["broken_detail"]
        assert auto["orphans"] == 0, auto["orphan_names"]
        assert auto["kills"] >= 1, "chaos never landed a kill"
        assert auto["converged"], auto["convergence"]

        if args.blackbox:
            blackbox_report(events_dir, auto, smoke=bool(args.smoke))
    finally:
        rt.shutdown()


def blackbox_report(events_dir: str, auto: dict, *, smoke: bool):
    """Merge every ring the run left behind — the SIGKILLed replica's
    included — and reconstruct the resumed request's story. In smoke
    mode this is the acceptance gate: the reconstruction must contain
    the kill, the resume, and the token-identity verdict, with the
    correlation id intact across processes."""
    from tools.rtblackbox import (format_timeline, load_rings,
                                  merge_timeline, reconstruct_request)

    loaded = load_rings(events_dir)
    tl = merge_timeline(loaded["rings"])
    resumed = auto.get("resumed_requests") or []
    rid = resumed[0][0] if resumed else None
    story = reconstruct_request(tl, rid) if rid else {"events": [],
                                                      "kinds": []}
    print(json.dumps({
        "metric": "serve_cluster_blackbox",
        "value": len(story["events"]), "unit": "story_events",
        "events_dir": events_dir,
        "rings": len(loaded["rings"]),
        "ring_errors": len(loaded["errors"]),
        "timeline_events": len(tl["events"]),
        "procs": len(tl["procs"]),
        "torn": tl["torn"],
        "request": rid,
        "story_kinds": story.get("kinds", []),
        "story_replicas": story.get("replicas", []),
    }))
    if story["events"]:
        print(f"--- request {rid}: cross-process story "
              f"(merged from {len(loaded['rings'])} rings) ---",
              file=sys.stderr)
        print(format_timeline(story["events"]), file=sys.stderr)
    if smoke:
        kinds = set(story.get("kinds", []))
        assert rid, "blackbox: no resumed request to reconstruct"
        assert "chaos.kill" in kinds, \
            f"blackbox: kill missing from the story: {sorted(kinds)}"
        assert "router.resume" in kinds or "engine.resume" in kinds, \
            f"blackbox: resume missing from the story: {sorted(kinds)}"
        verdicts = [e for e in story["events"]
                    if e["kind"] == "client.verdict"]
        assert verdicts and verdicts[-1]["attrs"].get("identical"), \
            "blackbox: token-identity verdict missing or failed"
        # the story must span processes — the dead replica's ring
        # contributed, not just the harness's own
        assert len({e["proc"] for e in story["events"]}) >= 2, \
            "blackbox: story never left the harness process"


if __name__ == "__main__":
    main()
