"""RL benchmark configs from BASELINE.md:63 — "PPO CartPole
(single-process)" with the reference's ≥450-solved gate
(``rllib/tuned_examples/ppo/cartpole_ppo.py``) and "IMPALA Atari Pong
(async multi-learner)" as an async-pipeline throughput config (CNN
module + aggregator actors; the Atari env itself is not bundled in this
image, so the Pong-shaped pipeline runs on synthetic 84x84 frames).

Run: ``python benchmarks/bench_rl.py [--skip-impala]``
Prints one JSON line per config.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_ppo_cartpole():
    """Train PPO until CartPole is solved (mean return >= 450, the
    reference tuned-example stopper) and report time + env steps."""
    from ray_tpu.rllib import PPOConfig

    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                        rollout_fragment_length=64)
           .training(lr=3e-4, train_batch_size=512, minibatch_size=128,
                     num_epochs=8, entropy_coeff=0.01)
           .debugging(seed=0))
    algo = cfg.build()
    t0 = time.perf_counter()
    solved_at = None
    steps = 0
    for i in range(400):
        algo.train()
        steps = algo._timesteps
        m = algo.env_runner_group.get_metrics()
        if m.get("num_episodes", 0) >= 20 and \
                m["episode_return_mean"] >= 450:
            solved_at = i + 1
            break
    dt = time.perf_counter() - t0
    algo.stop()
    print(json.dumps({
        "metric": "ppo_cartpole_solved",
        "value": round(dt, 1), "unit": "s",
        "solved": solved_at is not None,
        "iterations": solved_at, "env_steps": steps,
        "env_steps_per_sec": round(steps / dt, 1),
        "baseline_gate": ">=450 mean return "
                         "(rllib/tuned_examples/ppo/cartpole_ppo.py)",
    }))
    return solved_at is not None


def bench_impala_pong_shaped():
    """Async IMPALA pipeline at Pong dimensions: remote CNN env runners
    on a synthetic 84x84x4 env, aggregator actors, V-trace learner.
    Reports env-steps/sec through the full async pipeline."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.rllib import IMPALAConfig

    rt.init(num_cpus=8, num_tpus=0, ignore_reinit_error=True)

    def synthetic_atari():
        import gymnasium
        from gymnasium import spaces

        class SynthAtari(gymnasium.Env):
            """84x84x4 frames, 6 actions, episodic — Pong-shaped load
            without the ALE dependency."""

            observation_space = spaces.Box(0.0, 1.0, (84, 84, 4),
                                           np.float32)
            action_space = spaces.Discrete(6)

            def __init__(self):
                self._t = 0
                self._rng = np.random.default_rng(0)

            def _obs(self):
                return self._rng.random((84, 84, 4), np.float32)

            def reset(self, *, seed=None, options=None):
                self._t = 0
                return self._obs(), {}

            def step(self, action):
                self._t += 1
                done = self._t >= 200
                return self._obs(), float(action == 3), done, False, {}

        return SynthAtari()

    cfg = (IMPALAConfig()
           .environment(env_creator=synthetic_atari)
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=32)
           .rl_module(use_conv=True)
           .training(num_aggregation_workers=1, train_batch_size=256,
                     lr=3e-4)
           .debugging(seed=0))
    algo = cfg.build()
    t0 = time.perf_counter()
    for _ in range(8):
        algo.train()
    dt = time.perf_counter() - t0
    steps = algo._timesteps
    algo.stop()
    print(json.dumps({
        "metric": "impala_pong_shaped_env_steps_per_sec",
        "value": round(steps / dt, 1), "unit": "steps/s",
        "env_steps": steps,
        "config": "2 CNN env-runners x 2 envs, 1 aggregator, V-trace",
    }))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--skip-impala", action="store_true")
    args = parser.parse_args()

    from ray_tpu.testing import force_host_devices

    force_host_devices(1)
    ok = bench_ppo_cartpole()
    if not args.skip_impala:
        bench_impala_pong_shaped()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
