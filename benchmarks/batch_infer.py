"""Offline batch-inference saturation benchmark (ISSUE 11).

The complement of ``serve_gpt.py``'s Poisson-arrival serving runs: here
occupancy is driven by BACKPRESSURE, not arrivals — the pipeline keeps
every engine's admission queue topped up at ``queue_factor`` slots'
worth of backlog, so the measurement is immune to this box's run-to-run
load noise and reads the hardware's sustained ceiling (the
TPU-concurrency study's regime).

Phases (JSON line per row, like every benchmark here):

- **saturation** (in-process): N prompts with a mixed output-length
  schedule stream through ``BatchInferencer`` → total tok/s, per-fused-
  dispatch slot occupancy (the acceptance bar: >= 0.8 steady-state on
  nano CPU), bounded queue depth, dispatches/token, and cost-per-Mtok
  derived from ``--cost-per-hour`` (an input price knob, not a
  measurement).
- **resume** (subprocesses): an uninterrupted child run, a throttled
  child SIGKILLed mid-run once K blocks committed
  (``testing.sigkill_when`` + ``ProgressLog.scan``), and a resumed
  child from the same progress log → byte-identical outputs, zero
  lost / zero duplicated rows, and the resume's wall cost as a
  fraction of the uninterrupted run.

``--smoke`` shrinks both phases for the tier-1 CI hook
(``tests/test_data_llm.py``). ``--child`` is the driver subprocess
entrypoint the resume phase (and the preemption tests) spawn.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def build_engines(args):
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import DecodeEngine

    cfg = gpt.CONFIGS[args.config]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    engines = [
        DecodeEngine(params, cfg, slots=args.slots, chunk=args.chunk,
                     max_len=args.max_len, prompt_buckets=(8, 16),
                     temperature=args.temperature,
                     deployment=f"batch_infer_{i}")
        for i in range(args.engines)]
    return cfg, params, engines


def make_dataset(args, cfg):
    """Deterministic workload: mixed prompt lengths (both buckets) and
    a mixed output-length schedule — the shape continuous batching
    exists for; per-row seeds come from the pipeline's global row
    index, so every run (and every resume) regenerates identically."""
    import numpy as np

    from ray_tpu import data as rd

    rng = np.random.default_rng(123)
    mix = sorted({max(2, args.max_new // 2), args.max_new,
                  2 * args.max_new})
    rows = []
    for i in range(args.rows):
        plen = int(rng.integers(5, 17))
        rows.append({
            "rid": int(i),
            "prompt": rng.integers(0, cfg.vocab_size,
                                   (plen,)).astype(np.int32),
            "max_new": int(mix[i % len(mix)]),
        })
    mean_new = sum(r["max_new"] for r in rows) / len(rows)
    return rd.from_items(rows, block_size=args.block_size), mean_new


def run_pipeline(args, out_dir=None, progress=None):
    """Build engines + dataset, drive the pipeline to completion;
    returns (inferencer, engines, wall_s). Writes one JSON-lines file
    per output block when ``out_dir`` is set."""
    from ray_tpu.data import block as B
    from ray_tpu.data.dataset import _jsonable_row
    from ray_tpu.data.llm import BatchInferencer

    cfg, _params, engines = build_engines(args)
    ds, _mean_new = make_dataset(args, cfg)
    if args.throttle > 0:
        for eng in engines:
            eng.inject_fault("driver_slow", wedge_s=args.throttle)
    bi = BatchInferencer(
        engines, prompts_col="prompt", max_new_col="max_new",
        max_new=args.max_new, seed=args.seed,
        queue_factor=args.queue_factor, progress_path=progress)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    for idx, blk in enumerate(bi.run(ds)):
        if out_dir:
            path = os.path.join(out_dir, f"part_{idx:05d}.json")
            with open(path, "w") as f:
                for row in B.iter_rows(blk):
                    f.write(json.dumps(_jsonable_row(row)) + "\n")
    wall = time.perf_counter() - t0
    return bi, engines, wall


def child_main(args):
    """Driver subprocess for the resume phase / preemption tests: run
    the pipeline (optionally throttled), write output blocks, report
    one JSON line."""
    bi, engines, wall = run_pipeline(args, out_dir=args.out,
                                     progress=args.progress)
    for eng in engines:
        eng.shutdown()
    print(json.dumps({"child": True, "wall_s": round(wall, 3),
                      "rows": bi.stats["rows"],
                      "rows_from_log": bi.stats["rows_resumed_from_log"],
                      "tokens": bi.stats["tokens"]}))


def _child_cmd(args, *, out, progress, throttle=0.0):
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--out", out, "--progress", progress,
           "--throttle", str(throttle)]
    for flag, val in (("--config", args.config), ("--slots", args.slots),
                      ("--chunk", args.chunk), ("--max-len", args.max_len),
                      ("--engines", args.engines), ("--rows", args.rows),
                      ("--block-size", args.block_size),
                      ("--max-new", args.max_new), ("--seed", args.seed),
                      ("--temperature", args.temperature),
                      ("--queue-factor", args.queue_factor)):
        cmd += [flag, str(val)]
    return cmd


def _read_out_dir(d):
    """{filename: bytes} for the byte-identity check, plus all rids."""
    files, rids = {}, []
    for name in sorted(os.listdir(d)):
        with open(os.path.join(d, name), "rb") as f:
            files[name] = f.read()
        for line in files[name].splitlines():
            rids.append(json.loads(line)["rid"])
    return files, rids


def run_saturation(args):
    # Queue-depth sampler: proves admission stays BOUNDED while the
    # pool stays fed (the whole point of the saturation policy).
    depths, stop = [], threading.Event()
    holder = {}

    def sample():
        while not stop.is_set():
            engines = holder.get("engines")
            if engines:
                depths.append(sum(e.queue_depth() for e in engines))
            stop.wait(0.02)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()

    # Warm the compile caches outside the clock (same discipline as
    # serve_gpt): a tiny pipeline touches every program.
    warm = argparse.Namespace(**vars(args))
    warm.rows, warm.block_size, warm.throttle = 2 * args.slots, 4, 0.0
    _bi, engines, _ = run_pipeline(warm)
    for eng in engines:
        eng.shutdown()

    from ray_tpu.data.llm import BatchInferencer

    cfg, _params, engines = build_engines(args)
    holder["engines"] = engines
    ds, mean_new = make_dataset(args, cfg)
    bi = BatchInferencer(engines, prompts_col="prompt",
                         max_new_col="max_new", max_new=args.max_new,
                         seed=args.seed, queue_factor=args.queue_factor)
    t0 = time.perf_counter()
    n_blocks = sum(1 for _ in bi.run(ds))
    wall = time.perf_counter() - t0
    stop.set()
    sampler.join(timeout=2)
    stats = [e.stats() for e in engines]
    for eng in engines:
        eng.shutdown()
    disp = sum(s["dispatches"] for s in stats)
    occ = sum(s["avg_occupancy"] * s["dispatches"]
              for s in stats) / max(disp, 1)
    tok_s = bi.stats["tokens"] / wall
    cost_per_tok = (args.cost_per_hour / 3600.0) / max(tok_s, 1e-9)
    row = {
        "metric": f"batch_infer_{args.config}_saturation",
        "value": round(tok_s, 1), "unit": "tokens/s",
        "rows": bi.stats["rows"], "blocks": n_blocks,
        "tokens": bi.stats["tokens"], "wall_s": round(wall, 2),
        "mean_max_new": round(mean_new, 1),
        "avg_slot_occupancy": round(occ, 3),
        "peak_active": max(s["peak_active"] for s in stats),
        "slots": args.slots * args.engines, "engines": args.engines,
        "dispatches_per_token": round(
            (disp + sum(s["prefills"] for s in stats))
            / max(bi.stats["tokens"], 1), 4),
        "queue_depth_mean": round(sum(depths) / max(len(depths), 1), 1),
        "queue_depth_max": max(depths, default=0),
        "queue_factor": args.queue_factor,
        "cost_per_hour": args.cost_per_hour,
        "cost_per_mtok": round(cost_per_tok * 1e6, 4),
        "smoke": bool(args.smoke),
    }
    print(json.dumps(row))
    return row


def run_resume(args):
    from ray_tpu.data.llm import ProgressLog
    from ray_tpu.testing import sigkill_when

    base = tempfile.mkdtemp(prefix="batch_infer_resume_")
    out_a = os.path.join(base, "out_uninterrupted")
    out_c = os.path.join(base, "out_resumed")
    progress = os.path.join(base, "progress")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    n_blocks = -(-args.rows // args.block_size)

    # A: uninterrupted reference (its own progress log, never killed).
    pa = subprocess.run(
        _child_cmd(args, out=out_a,
                   progress=os.path.join(base, "progress_a")),
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert pa.returncode == 0, pa.stdout + "\n" + pa.stderr
    wall_a = json.loads(pa.stdout.splitlines()[-1])["wall_s"]

    # B: throttled driver, SIGKILLed once a third of the blocks are
    # durably committed — mid-run by construction.
    kill_at = max(1, n_blocks // 3)
    pb = subprocess.Popen(
        _child_cmd(args, out=os.path.join(base, "out_killed"),
                   progress=progress, throttle=args.throttle or 0.03),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        cwd=ROOT)
    killed = sigkill_when(
        pb, lambda: len(ProgressLog.scan(progress)) >= kill_at,
        timeout_s=300)
    committed_at_kill = len(ProgressLog.scan(progress))

    # C: resume from the progress log, full speed.
    pc = subprocess.run(
        _child_cmd(args, out=out_c, progress=progress),
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert pc.returncode == 0, pc.stdout + "\n" + pc.stderr
    crow = json.loads(pc.stdout.splitlines()[-1])

    files_a, rids_a = _read_out_dir(out_a)
    files_c, rids_c = _read_out_dir(out_c)
    lost = len(set(rids_a) - set(rids_c))
    dup = len(rids_c) - len(set(rids_c))
    row = {
        "metric": f"batch_infer_{args.config}_resume",
        "value": round(crow["wall_s"] / max(wall_a, 1e-9), 3),
        "unit": "resume_wall_frac_of_uninterrupted",
        "killed": bool(killed),
        "blocks": n_blocks, "blocks_committed_at_kill": committed_at_kill,
        "skipped_frac": round(committed_at_kill / n_blocks, 3),
        "rows_resumed_from_log": crow["rows_from_log"],
        "identical": files_a == files_c,
        "lost_rows": lost, "dup_rows": dup,
        "uninterrupted_wall_s": wall_a,
        "resume_wall_s": crow["wall_s"],
        "smoke": bool(args.smoke),
    }
    print(json.dumps(row))
    return row


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="nano")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--engines", type=int, default=1)
    p.add_argument("--rows", type=int, default=192)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-new", type=int, default=32,
                   help="middle of the mixed output-length schedule")
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--queue-factor", type=float, default=2.0)
    p.add_argument("--cost-per-hour", type=float, default=1.2,
                   help="accelerator price input for cost-per-Mtok")
    p.add_argument("--throttle", type=float, default=0.0,
                   help="driver_slow per-loop stall (resume-kill child)")
    p.add_argument("--smoke", action="store_true",
                   help="shrink both phases for tier-1 CI")
    p.add_argument("--no-resume", action="store_true",
                   help="saturation phase only")
    p.add_argument("--resume-only", action="store_true",
                   help="kill/resume phase only (the preemption tests "
                        "run this at temp 0 AND seeded temp > 0)")
    p.add_argument("--child", action="store_true",
                   help="driver subprocess (resume phase internal)")
    p.add_argument("--out", default="")
    p.add_argument("--progress", default="")
    args = p.parse_args()
    if args.smoke:
        args.slots = min(args.slots, 4)
        args.rows = min(args.rows, 48)
        args.block_size = min(args.block_size, 8)
        args.max_new = min(args.max_new, 12)
    if not args.max_len:
        args.max_len = 16 + 2 * args.max_new + args.chunk
    if args.child:
        child_main(args)
        return
    if not args.resume_only:
        run_saturation(args)
    if not args.no_resume:
        # The resume children are smaller still: three subprocess
        # compiles already dominate their wall time.
        if args.smoke:
            args.rows, args.block_size = 24, 4
        run_resume(args)


if __name__ == "__main__":
    main()
