"""Streaming GPT serving benchmark (VERDICT round 2, item 4): a decode-
loop replica with bucketed prefill and per-token streaming through
Serve's streaming path (replica generator → handle → chunked HTTP).

Reports per-stream TTFT (time to first token), per-token latency, and
aggregate decoded tokens/s as JSON lines.

Run: ``python benchmarks/serve_gpt.py [--clients 4] [--tokens 32]``
(CPU fallback shrinks the model so the benchmark completes).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--tokens", type=int, default=32)
    parser.add_argument("--streams", type=int, default=8,
                        help="total streams per client")
    parser.add_argument("--config", default="")
    args = parser.parse_args()

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8, ignore_reinit_error=True)
    serve.start(proxy=False)

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg_name = args.config or ("small" if on_tpu else "nano")
    max_new = args.tokens

    @serve.deployment(max_ongoing_requests=8)
    class GPTStream:
        """Decode-loop replica: bucketed prefill (one compile per prompt
        bucket), then one jitted decode step per streamed token."""

        def __init__(self, cfg_name: str, max_len: int):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self._prefill = jax.jit(gpt_decode.prefill, static_argnums=(2,))
            self._step = jax.jit(gpt_decode.decode_step, static_argnums=(3,))

        def warm(self, prompt_bucket: int, _=None):
            import jax.numpy as jnp

            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(
                self.params, jnp.zeros((1, prompt_bucket), jnp.int32),
                self.cfg, cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            self._step(self.params, cache, tok, self.cfg)
            return "warm"

        def __call__(self, request):
            """request = {"prompt_len": int, "max_new": int}; yields one
            token id per step."""
            import jax.numpy as jnp

            if hasattr(request, "json"):  # HTTP ingress
                request = request.json()
            plen = int(request.get("prompt_len", 16))
            max_new = int(request.get("max_new", 16))
            prompt = jnp.asarray(
                np.random.randint(0, self.cfg.vocab_size, (1, plen),
                                  dtype=np.int32))
            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(self.params, prompt, self.cfg,
                                          cache)
            for _ in range(max_new):
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                yield int(tok[0])
                logits, cache = self._step(self.params, cache, tok,
                                           self.cfg)

    max_len = 16 + max_new + 8
    handle = serve.run(GPTStream.bind(cfg_name, max_len),
                       name="gpt_stream", route_prefix="/generate")
    assert handle.options(method_name="warm").remote(16).result(
        timeout=600) == "warm"
    # End-to-end warm stream (covers the streaming transport itself).
    list(handle.options(stream=True).remote(
        {"prompt_len": 16, "max_new": 2}))

    ttfts, tok_lats = [], []
    total_tokens = [0]
    lock = threading.Lock()

    def client():
        for _ in range(args.streams):
            t0 = time.perf_counter()
            gen = handle.options(stream=True).remote(
                {"prompt_len": 16, "max_new": max_new})
            last = t0
            first = None
            n = 0
            for _tok in gen:
                now = time.perf_counter()
                if first is None:
                    first = now - t0
                else:
                    tok_lats.append(now - last)
                last = now
                n += 1
            with lock:
                ttfts.append(first)
                total_tokens[0] += n

    threads = [threading.Thread(target=client)
               for _ in range(args.clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    ttfts.sort()
    tok_lats.sort()
    model = f"gpt_{cfg_name}"
    print(json.dumps({
        "metric": f"serve_{model}_ttft_p50_ms",
        "value": round(ttfts[len(ttfts) // 2] * 1000, 2), "unit": "ms",
        "p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1000, 2),
        "clients": args.clients}))
    if tok_lats:
        print(json.dumps({
            "metric": f"serve_{model}_tok_latency_p50_ms",
            "value": round(tok_lats[len(tok_lats) // 2] * 1000, 2),
            "unit": "ms",
            "p95_ms": round(tok_lats[int(len(tok_lats) * 0.95)] * 1000, 2)}))
    print(json.dumps({
        "metric": f"serve_{model}_decode_throughput",
        "value": round(total_tokens[0] / wall, 1), "unit": "tokens/s",
        "clients": args.clients, "streams": args.clients * args.streams}))
    serve.shutdown()
    rt.shutdown()


if __name__ == "__main__":
    main()
