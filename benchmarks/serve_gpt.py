"""Streaming GPT serving benchmark (VERDICT round 2 item 4; round 5
weak #5): a decode-loop replica with bucketed prefill streaming through
Serve (replica generator → handle → chunked HTTP), now with an A/B
chunked-decode mode.

``--chunk`` takes a comma-separated list of decode chunk sizes and runs
the full client load once per size, side by side in one artifact:

- ``1``  — the legacy path: one jitted ``decode_step`` dispatch (and
  one device→host scalar read) per generated token.
- ``k>1`` — the fused path: ``decode_chunk`` runs k steps in a single
  jitted ``lax.scan`` dispatch and the replica streams one per-chunk
  token slice per dispatch.

Per mode, reports per-stream TTFT, amortized per-token latency
(p50/p95/p99), aggregate decoded tokens/s, and — the dispatch
amortization itself — jitted dispatches per generated token counted on
the replica. JSON lines; chunk 1 keeps the legacy metric names.

Run: ``python benchmarks/serve_gpt.py [--clients 4] [--tokens 32]
[--chunk 1,8]`` (CPU fallback shrinks the model).

``--overload`` switches to the request-lifecycle A/B instead: offered
load ~3x a 4-slot replica, once with an effectively unbounded admission
queue and once with the bounded queue + 503/BackPressure shedding;
reports shed rate, goodput, and completion p50/p99 per mode.

``--trace`` (ISSUE 4) switches to the observability check: tracing on,
one streamed request through the FULL data plane (HTTP proxy → router →
replica → @serve.batch streaming flush → chunked decode), then dumps
that request's span tree, asserts the stage timings sum to within 10%
of the measured e2e latency, and verifies the serve latency histograms
(`serve_request_e2e_seconds`, `serve_ttft_seconds`,
`serve_tpot_seconds`) reached /metrics with non-zero counts.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--tokens", type=int, default=32)
    parser.add_argument("--streams", type=int, default=8,
                        help="total streams per client")
    parser.add_argument("--config", default="")
    parser.add_argument("--chunk", default="1,8",
                        help="comma-separated decode chunk sizes to A/B "
                             "(1 = per-token decode_step loop)")
    parser.add_argument("--overload", action="store_true",
                        help="overload A/B instead of the chunk A/B: drive "
                             "the deployment past saturation twice — "
                             "unbounded queue vs bounded queue + shedding — "
                             "and report shed rate, goodput, and completion "
                             "p99 per mode")
    parser.add_argument("--overload-duration", type=float, default=8.0)
    parser.add_argument("--overload-clients", type=int, default=24,
                        help="concurrent clients (~3x a 4-slot replica)")
    parser.add_argument("--trace", action="store_true",
                        help="observability mode: trace one streamed "
                             "request end to end, dump its span tree, "
                             "assert stage sums ≈ e2e, and check the "
                             "serve latency histograms on /metrics")
    args = parser.parse_args()
    chunks = [int(c) for c in args.chunk.split(",") if c.strip()]

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8, ignore_reinit_error=True)
    if args.trace:
        from ray_tpu.util import tracing

        tracing.enable()  # before start(): proxies mirror the flag
        serve.start(http_options={"host": "127.0.0.1", "port": 0})
    else:
        serve.start(proxy=False)

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg_name = args.config or ("small" if on_tpu else "nano")
    max_new = args.tokens

    @serve.deployment(max_ongoing_requests=8)
    class GPTStream:
        """Decode-loop replica. chunk=1: one jitted decode step per
        streamed token. chunk=k: one fused k-step scan per streamed
        per-chunk token slice."""

        def __init__(self, cfg_name: str, max_len: int, chunk_sizes):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self._prefill = jax.jit(gpt_decode.prefill, static_argnums=(2,))
            self._step = jax.jit(gpt_decode.decode_step, static_argnums=(3,))
            self._chunk_steps = {
                k: gpt_decode.jit_decode_chunk(self.cfg, k)
                for k in chunk_sizes if k > 1}
            # Jitted-dispatch accounting for the A/B artifact; locked —
            # up to max_ongoing_requests threads decode concurrently.
            import threading as _threading

            self._stats_lock = _threading.Lock()
            self._dispatches = 0
            self._tokens = 0

        def _count(self, dispatches: int, tokens: int):
            with self._stats_lock:
                self._dispatches += dispatches
                self._tokens += tokens

        def warm(self, prompt_bucket: int, _=None):
            import jax.numpy as jnp

            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(
                self.params, jnp.zeros((1, prompt_bucket), jnp.int32),
                self.cfg, cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            self._step(self.params, cache, tok, self.cfg)
            rng = jax.random.PRNGKey(0)
            for step in self._chunk_steps.values():
                step(self.params, cache, tok, rng)
            return "warm"

        def reset_stats(self):
            with self._stats_lock:
                self._dispatches = 0
                self._tokens = 0
            return "reset"

        def stats(self):
            with self._stats_lock:
                return {"dispatches": self._dispatches,
                        "tokens": self._tokens}

        def __call__(self, request):
            """request = {"prompt_len", "max_new", "chunk"}; yields one
            token id per step (chunk=1) or one token-id list per fused
            chunk (chunk=k)."""
            import jax.numpy as jnp

            if hasattr(request, "json"):  # HTTP ingress
                request = request.json()
            plen = int(request.get("prompt_len", 16))
            max_new = int(request.get("max_new", 16))
            chunk = int(request.get("chunk", 1))
            prompt = jnp.asarray(
                np.random.randint(0, self.cfg.vocab_size, (1, plen),
                                  dtype=np.int32))
            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(self.params, prompt, self.cfg,
                                          cache)
            self._count(1, 0)
            if chunk <= 1:
                for _ in range(max_new):
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    self._count(0, 1)
                    yield int(tok[0])
                    logits, cache = self._step(self.params, cache, tok,
                                               self.cfg)
                    self._count(1, 0)
                return
            if max_new <= 0:
                return
            # Unlisted chunk size (e.g. ad-hoc HTTP request): jit on
            # demand instead of dying with a KeyError mid-stream. No
            # lock: dict get/set are GIL-atomic and jit_decode_chunk is
            # lru_cached, so racing threads get the same wrapper.
            step = self._chunk_steps.get(chunk)
            if step is None:
                step = self._chunk_steps[chunk] = \
                    self.gd.jit_decode_chunk(self.cfg, chunk)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            self._count(0, 1)
            yield [int(tok[0])]
            # The library driver IS the measured path: decode_until
            # yields exactly one trimmed slice per fused dispatch.
            for slice_ in self.gd.decode_until(
                    step, self.params, cache, tok, max_new - 1):
                self._count(1, slice_.shape[1])
                yield [int(t) for t in slice_[0]]

    # Cache sized for the worst chunk over-run: the last fused chunk may
    # execute up to (chunk - 1) steps past max_new before truncation.
    max_len = 16 + max_new + max(max(chunks), 8)
    if args.trace:
        run_trace_mode(args, rt, serve, np, cfg_name, max(chunks),
                       f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    if args.overload:
        run_overload_ab(args, serve, GPTStream, cfg_name, max_len, chunks,
                        f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    handle = serve.run(GPTStream.bind(cfg_name, max_len, chunks),
                       name="gpt_stream", route_prefix="/generate")
    assert handle.options(method_name="warm").remote(16).result(
        timeout=600) == "warm"
    # End-to-end warm stream per mode (covers the streaming transport).
    for c in chunks:
        list(handle.options(stream=True).remote(
            {"prompt_len": 16, "max_new": 2, "chunk": c}))

    model = f"gpt_{cfg_name}"

    def run_mode(chunk: int):
        handle.options(method_name="reset_stats").remote().result(
            timeout=60)
        ttfts, tok_lats = [], []
        total_tokens = [0]
        lock = threading.Lock()

        def client():
            for _ in range(args.streams):
                t0 = time.perf_counter()
                gen = handle.options(stream=True).remote(
                    {"prompt_len": 16, "max_new": max_new, "chunk": chunk})
                last = t0
                first = None
                n = 0
                lats = []
                for item in gen:
                    now = time.perf_counter()
                    width = len(item) if isinstance(item, list) else 1
                    if first is None:
                        first = now - t0
                    else:
                        # Amortized per-token latency: a fused chunk
                        # lands j tokens in one arrival.
                        lats.extend([(now - last) / width] * width)
                    last = now
                    n += width
                with lock:
                    ttfts.append(first)
                    tok_lats.extend(lats)
                    total_tokens[0] += n

        threads = [threading.Thread(target=client)
                   for _ in range(args.clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        stats = handle.options(method_name="stats").remote().result(
            timeout=60)
        dpt = stats["dispatches"] / max(stats["tokens"], 1)
        suffix = "" if chunk == 1 else f"_chunk{chunk}"
        ttfts.sort()
        tok_lats.sort()
        print(json.dumps({
            "metric": f"serve_{model}_ttft_p50_ms{suffix}",
            "value": round(ttfts[len(ttfts) // 2] * 1000, 2), "unit": "ms",
            "p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1000, 2),
            "clients": args.clients, "chunk": chunk}))
        if tok_lats:
            print(json.dumps({
                "metric": f"serve_{model}_tok_latency_p50_ms{suffix}",
                "value": round(tok_lats[len(tok_lats) // 2] * 1000, 2),
                "unit": "ms",
                "p95_ms": round(tok_lats[int(len(tok_lats) * 0.95)] * 1000,
                                2),
                "p99_ms": round(tok_lats[int(len(tok_lats) * 0.99)] * 1000,
                                2),
                "chunk": chunk}))
        print(json.dumps({
            "metric": f"serve_{model}_decode_throughput{suffix}",
            "value": round(total_tokens[0] / wall, 1), "unit": "tokens/s",
            "clients": args.clients, "streams": args.clients * args.streams,
            "chunk": chunk}))
        print(json.dumps({
            "metric": f"serve_{model}_dispatches_per_token{suffix}",
            "value": round(dpt, 4), "unit": "dispatches/token",
            "dispatches": stats["dispatches"], "tokens": stats["tokens"],
            "chunk": chunk}))
        return {"chunk": chunk,
                "tok_p50_ms": round(
                    tok_lats[len(tok_lats) // 2] * 1000, 2)
                if tok_lats else None,
                "tok_s": round(total_tokens[0] / wall, 1),
                "dispatches_per_token": round(dpt, 4)}

    results = [run_mode(c) for c in chunks]
    _finish_chunk_ab(results, model, serve, rt)


def _finish_chunk_ab(results, model, serve, rt):
    if len(results) > 1:
        base = next((r for r in results if r["chunk"] == 1), results[0])
        best = min(results, key=lambda r: r["dispatches_per_token"])
        print(json.dumps({
            "metric": f"serve_{model}_chunked_decode_ab",
            "value": round(base["dispatches_per_token"]
                           / max(best["dispatches_per_token"], 1e-9), 2),
            "unit": "x_fewer_dispatches", "modes": results}))
    serve.shutdown()
    rt.shutdown()


def make_traced_deployment(serve, np):
    """Batched chunked-decode deployment for --trace: the ingress
    streams per-chunk token slices pulled from a ``@serve.batch``
    streaming handler, so ONE traced request crosses every serve stage
    — proxy admission, router queue, replica dispatch, batch flush, and
    one fused decode dispatch per chunk."""
    import jax

    @serve.deployment(max_ongoing_requests=4)
    class GPTTraced:
        def __init__(self, cfg_name: str, max_len: int, chunk: int):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self.chunk = chunk
            self._prefill = jax.jit(gpt_decode.prefill,
                                    static_argnums=(2,))
            self._chunk_step = gpt_decode.jit_decode_chunk(self.cfg,
                                                           chunk)

        def _stream_one(self, request):
            import jax.numpy as jnp

            plen = int(request.get("prompt_len", 16))
            max_new = int(request.get("max_new", 16))
            prompt = jnp.asarray(np.random.randint(
                0, self.cfg.vocab_size, (1, plen), dtype=np.int32))
            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(self.params, prompt, self.cfg,
                                          cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            yield [int(tok[0])]
            for slice_ in self.gd.decode_until(
                    self._chunk_step, self.params, cache, tok,
                    max_new - 1):
                yield [int(t) for t in slice_[0]]

        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.005,
                     stream=True)
        def decode_batch(self, requests):
            # Lockstep drive of the batched per-request generators; a
            # finished caller receives empty slices until the batch
            # drains (single-request trace mode never hits that path).
            gens = [self._stream_one(r) for r in requests]
            done = [False] * len(gens)
            while True:
                out = []
                for i, g in enumerate(gens):
                    if done[i]:
                        out.append([])
                        continue
                    try:
                        out.append(next(g))
                    except StopIteration:
                        done[i] = True
                        out.append([])
                if all(done):
                    return
                yield out

        def warm(self, plen: int = 16):
            list(self._stream_one({"prompt_len": plen,
                                   "max_new": self.chunk + 1}))
            return "warm"

        def __call__(self, request):
            if hasattr(request, "json"):  # HTTP ingress
                request = request.json()
            for slice_ in self.decode_batch(request):
                if slice_:
                    yield slice_

    return GPTTraced


def _span_tree(spans, root):
    """Children-of index for one trace + pretty printer."""
    kids = {}
    for s in spans:
        kids.setdefault(s.get("parent_id"), []).append(s)
    for v in kids.values():
        v.sort(key=lambda s: s["start"])
    lines = []

    def walk(span, depth):
        dur_ms = (span["end"] - span["start"]) * 1000
        lines.append(f"{'  ' * depth}{span['name']}  "
                     f"[{dur_ms:.2f} ms]  kind={span['kind']}")
        for c in kids.get(span["span_id"], []):
            walk(c, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def run_trace_mode(args, rt, serve, np, cfg_name, chunk, model):
    """One traced streamed request through the full data plane; dump the
    span tree, check the stage partition sums to ~e2e, and confirm the
    latency histograms landed on /metrics."""
    import urllib.request

    from ray_tpu.util import tracing

    # Enough decode work that the measured stages dominate the fixed
    # per-request overheads the partition cannot see (RPC transit,
    # chunk relay) — the 10% tolerance is on e2e.
    max_new = max(args.tokens, 64)
    max_len = 16 + max_new + max(chunk, 8)
    GPTTraced = make_traced_deployment(serve, np)
    handle = serve.run(
        GPTTraced.bind(cfg_name, max_len, chunk),
        name="gpt_trace", route_prefix="/trace")
    assert handle.options(method_name="warm").remote(16).result(
        timeout=600) == "warm"
    port = serve.status()["http"]["port"]

    body = json.dumps({"prompt_len": 16, "max_new": max_new}).encode()
    want = {"proxy.admission", "router.queue_wait", "replica.queue_wait",
            "user_code", "batch.wait", "decode.chunk"}

    def traced_request():
        """One streamed request; returns (its trace, server span,
        client-side e2e, head drop total)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/trace", data=body, method="POST")
        sent_at = time.time()
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=120) as resp:
            tokens = 0
            for line in resp:
                if line.strip():
                    tokens += len(json.loads(line))
        e2e_client = time.perf_counter() - t0
        assert tokens >= max_new, f"stream returned {tokens} tokens"
        # The proxy flushes spans on a ~1s cadence; wait for the tree.
        deadline = time.time() + 30
        spans = []
        while time.time() < deadline:
            meta = tracing.get_spans(limit=100_000, with_meta=True)
            spans = meta["spans"]
            for s in spans:
                if s["kind"] == "server" and "[stream]" in s["name"] \
                        and s["start"] >= sent_at - 1.0:
                    mine = [x for x in spans
                            if x["trace_id"] == s["trace_id"]]
                    if want <= {x["name"] for x in mine}:
                        return mine, s, e2e_client, meta["dropped_total"]
            time.sleep(0.5)
        raise AssertionError(
            f"incomplete span tree; stages seen: "
            f"{sorted({x['name'] for x in spans})}")

    def dur(trace, name):
        return sum(s["end"] - s["start"] for s in trace
                   if s["name"] == name)

    # Stage partition of the critical path (batch.wait and decode.chunk
    # nest inside user_code): submission overhead + transit + handler
    # stream time should account for ~all of the server-observed e2e.
    # The residue is per-chunk relay overhead, which balloons when the
    # HOST is oversubscribed — take the best of a few attempts so the
    # check measures the instrumentation, not ambient machine load.
    best = None
    for attempt in range(3):
        trace, server, e2e_client, dropped = traced_request()
        e2e = server["end"] - server["start"]
        stage_sum = (dur(trace, "proxy.admission")
                     + dur(trace, "replica.queue_wait")
                     + dur(trace, "user_code"))
        gap = abs(e2e - stage_sum) / max(e2e, 1e-9)
        if best is None or gap < best[0]:
            best = (gap, trace, server, e2e, stage_sum, e2e_client,
                    dropped)
        if gap <= 0.10:
            break
    gap, trace, server, e2e, stage_sum, e2e_client, dropped = best
    print(_span_tree(trace, server))
    n_chunks = sum(1 for s in trace if s["name"] == "decode.chunk")
    print(json.dumps({
        "metric": f"serve_{model}_trace_stage_coverage",
        "value": round(stage_sum / max(e2e, 1e-9), 4),
        "unit": "fraction_of_e2e",
        "e2e_ms": round(e2e * 1000, 2),
        "client_e2e_ms": round(e2e_client * 1000, 2),
        "stage_sum_ms": round(stage_sum * 1000, 2),
        "decode_chunks": n_chunks,
        "spans_in_trace": len(trace),
        "spans_dropped_total": dropped,
    }))
    assert gap <= 0.10, \
        f"stage sum {stage_sum * 1000:.1f} ms deviates " \
        f"{gap:.0%} from e2e {e2e * 1000:.1f} ms (>10%)"
    assert n_chunks >= max_new // chunk, \
        f"expected ≥{max_new // chunk} decode.chunk spans, got {n_chunks}"

    # Histograms reach the head with the ~1s metric flush.
    needed = ["serve_request_e2e_seconds", "serve_ttft_seconds",
              "serve_tpot_seconds"]
    deadline = time.time() + 30
    counts = {}
    while time.time() < deadline:
        text = rt.metrics_text()
        counts = {}
        for n in needed:
            for line in text.splitlines():
                if line.startswith(f"ray_tpu_{n}_count"):
                    counts[n] = counts.get(n, 0.0) + float(line.rsplit(
                        " ", 1)[1])
        if all(counts.get(n, 0) > 0 for n in needed):
            break
        time.sleep(0.5)
    for n in needed:
        assert counts.get(n, 0) > 0, \
            f"{n} has no observations on /metrics: {counts}"
    print(json.dumps({
        "metric": f"serve_{model}_trace_histograms",
        "value": 1, "unit": "ok", "counts": counts}))


def run_overload_ab(args, serve, GPTStream, cfg_name, max_len, chunks,
                    model):
    """Overload A/B (ISSUE 2 CI satellite): offered load ~3x a 4-slot
    replica, once with an effectively unbounded admission queue and once
    with the bounded queue + shedding. Reports shed rate, goodput
    (completed tokens/s), and completion p50/p99 of ACCEPTED streams per
    mode — the bounded mode should hold p99 roughly at the service time
    of a full pipeline while the unbounded mode's p99 grows with the
    queue."""
    from ray_tpu.serve import BackPressureError, RequestDeadlineExceeded

    chunk = max(chunks)
    max_new = min(args.tokens, 8)
    timeout_s = 10.0
    summary = []
    for mode, max_queued in (("unshed", 1_000_000), ("shed", 4)):
        handle = serve.run(
            GPTStream.options(num_replicas=1, max_ongoing_requests=4,
                              max_queued_requests=max_queued)
            .bind(cfg_name, max_len, chunks),
            name="gpt_overload", route_prefix="/overload")
        handle.options(method_name="warm").remote(16).result(timeout=600)
        list(handle.options(stream=True).remote(
            {"prompt_len": 16, "max_new": 2, "chunk": chunk}))

        lock = threading.Lock()
        stats = {"offered": 0, "completed": 0, "shed": 0, "expired": 0,
                 "errors": 0, "tokens": 0}
        completion_s = []
        stop_at = time.perf_counter() + args.overload_duration

        def client():
            while time.perf_counter() < stop_at:
                with lock:
                    stats["offered"] += 1
                t0 = time.perf_counter()
                try:
                    gen = handle.options(
                        stream=True, timeout_s=timeout_s).remote(
                        {"prompt_len": 16, "max_new": max_new,
                         "chunk": chunk})
                    n = 0
                    for item in gen:
                        n += len(item) if isinstance(item, list) else 1
                    with lock:
                        stats["completed"] += 1
                        stats["tokens"] += n
                        completion_s.append(time.perf_counter() - t0)
                except BackPressureError:
                    with lock:
                        stats["shed"] += 1
                    time.sleep(0.05)  # honor the backoff contract
                except (RequestDeadlineExceeded, TimeoutError):
                    with lock:
                        stats["expired"] += 1
                except Exception:  # noqa: BLE001
                    with lock:
                        stats["errors"] += 1

        threads = [threading.Thread(target=client)
                   for _ in range(args.overload_clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        completion_s.sort()
        p50 = completion_s[len(completion_s) // 2] if completion_s else None
        p99 = completion_s[int(len(completion_s) * 0.99)] \
            if completion_s else None
        row = {
            "metric": f"serve_{model}_overload_{mode}",
            "value": round(stats["tokens"] / wall, 1),
            "unit": "goodput_tokens_s",
            "offered": stats["offered"], "completed": stats["completed"],
            "shed": stats["shed"], "expired": stats["expired"],
            "errors": stats["errors"],
            "shed_rate": round(stats["shed"] / max(stats["offered"], 1), 3),
            "completion_p50_s": round(p50, 3) if p50 else None,
            "completion_p99_s": round(p99, 3) if p99 else None,
            "clients": args.overload_clients,
            "max_queued_requests": max_queued,
        }
        print(json.dumps(row))
        summary.append(row)
        serve.delete("gpt_overload")
    if len(summary) == 2:
        unshed, shed = summary
        print(json.dumps({
            "metric": f"serve_{model}_overload_ab_p99_ratio",
            "value": round((unshed["completion_p99_s"] or 0)
                           / max(shed["completion_p99_s"] or 1e-9, 1e-9), 2),
            "unit": "x_p99_unshed_vs_shed",
            "goodput_ratio": round(shed["value"]
                                   / max(unshed["value"], 1e-9), 2)}))


if __name__ == "__main__":
    main()
