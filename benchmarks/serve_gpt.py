"""Streaming GPT serving benchmark (VERDICT round 2 item 4; round 5
weak #5): a decode-loop replica with bucketed prefill streaming through
Serve (replica generator → handle → chunked HTTP), now with an A/B
chunked-decode mode.

``--chunk`` takes a comma-separated list of decode chunk sizes and runs
the full client load once per size, side by side in one artifact:

- ``1``  — the legacy path: one jitted ``decode_step`` dispatch (and
  one device→host scalar read) per generated token.
- ``k>1`` — the fused path: ``decode_chunk`` runs k steps in a single
  jitted ``lax.scan`` dispatch and the replica streams one per-chunk
  token slice per dispatch.

Per mode, reports per-stream TTFT, amortized per-token latency
(p50/p95/p99), aggregate decoded tokens/s, and — the dispatch
amortization itself — jitted dispatches per generated token counted on
the replica. JSON lines; chunk 1 keeps the legacy metric names.

Run: ``python benchmarks/serve_gpt.py [--clients 4] [--tokens 32]
[--chunk 1,8]`` (CPU fallback shrinks the model).

``--overload`` switches to the request-lifecycle A/B instead: offered
load ~3x a 4-slot replica, once with an effectively unbounded admission
queue and once with the bounded queue + 503/BackPressure shedding;
reports shed rate, goodput, and completion p50/p99 per mode.

``--trace`` (ISSUE 4) switches to the observability check: tracing on,
one streamed request through the FULL data plane (HTTP proxy → router →
replica → @serve.batch streaming flush → chunked decode), then dumps
that request's span tree, asserts the stage timings sum to within 10%
of the measured e2e latency, and verifies the serve latency histograms
(`serve_request_e2e_seconds`, `serve_ttft_seconds`,
`serve_tpot_seconds`) reached /metrics with non-zero counts.

``--continuous`` (ISSUE 5) switches to the continuous-batching A/B:
the SAME Poisson arrival schedule with mixed output lengths is driven
twice at equal offered load — once through a static gang-scheduled
``@serve.batch(stream=True)`` deployment (batch forms once, rides out
the whole generation, mid-flight arrivals wait for the next gang) and
once through the slot-pool ``DecodeEngine``
(``@serve.batch(continuous=True)``: admission at chunk boundaries,
slots freed per-request at EOS/max_new). Reports p50/p95 TTFT,
completion latency, total decoded tok/s, and — continuous only — slot
occupancy and dispatches/token from the engine's own accounting.
``--smoke`` shrinks the load so the A/B runs inside tier-1 CI.

``--paged`` (ISSUE 6) switches to the paged-KV A/B: a flat slot pool
and a paged pool built from the SAME KV-byte budget (``n_pages *
page_size == flat_slots * max_len`` positions) are driven with an
identical saturating burst of mixed-length requests sharing a common
system prompt. Reports, per pool: decoded tok/s, TTFT/completion
percentiles, and PEAK CONCURRENT SLOTS (the paged pool runs ~3x the
lanes on the same bytes because real sequences are shorter than
max_len); then a shared-prefix TTFT probe — median TTFT of a request
whose system prompt is prefix-cached (page-table copy + short-suffix
prefill) vs the flat pool's full prefill. ``--smoke`` shrinks it for
tier-1 CI.

``--spec`` (ISSUE 9) switches to the speculative-decoding A/B: the
SAME saturating burst of repetitive-suffix prompts is driven through
three engines built on identical weights — spec off, the n-gram
drafter, and the tied-embedding model drafter — off/ngram driven
back-to-back in every pass with best-of-5 per mode, the same one-sided
noise discipline as ``--paged``/``--continuous``. The workload is
SCREENED: candidate prompts' greedy continuations are simulated once
against the n-gram drafter and the most predictable drive the A/B.
Reports, per mode: decoded tok/s, TTFT p50, TPOT p50/p95, and — spec
modes — accepted-tokens-per-target-forward and the acceptance rate
from the engine's own accounting. ``--smoke`` shrinks it (off vs
n-gram only) for tier-1 CI.

``--disagg`` (ISSUE 14) switches to the disaggregated prefill/decode
A/B: the SAME bursty-prefill Poisson mix — steady long decode streams
plus bursts of long-prompt/2-token requests — is driven through a
colocated 2-replica deployment and a roles-split one (1 prefill + 1
decode) at equal offered load. Colocated, every burst's prefill
dispatch lands between decode chunk dispatches and inflates decode
TPOT; disaggregated, bursts prefill on the prefill replica and reach
the decode engine as a cheap KV import. Reports decode TPOT p50/p95
isolation per mode, handoff latency/bytes from the engines' own
accounting, and asserts ZERO broken streams and NO handoff leaks
(pages free back to baseline, no outstanding leases). ``--smoke``
shrinks it for tier-1 CI.

``--tp N`` (ISSUE 20) switches to the tensor-parallel A/B: the SAME
saturating burst is driven through a single-chip engine and one whose
weights and paged KV are sharded over an N-wide ``tp`` mesh, at equal
offered load. Asserts the exactness contract live — temp-0 token
identity stream for stream, and dispatch accounting equal chunk for
chunk (the mesh moves FLOPs, never driver-loop boundaries) — and
reports TPOT p50 and tok/s per arm. On CPU the mesh is forced host
devices (plumbing + exactness, not speed); the ratio is the headline
only on a real multi-chip host. ``--smoke`` shrinks it for tier-1 CI.

``--chaos`` (ISSUE 7) switches to the crash-safety acceptance run: a
2-replica continuous-engine deployment serves seeded (deterministic)
streams under load while a replica is KILLED mid-stream; every client
stream holds a replay token (``resumable=True``) and must complete
token-identical to its uninterrupted reference — the row asserts ZERO
broken client streams and reports resumes, kills, and the recovery
stall. ``--smoke`` shrinks it for tier-1 CI.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--tokens", type=int, default=32)
    parser.add_argument("--streams", type=int, default=8,
                        help="total streams per client")
    parser.add_argument("--config", default="")
    parser.add_argument("--chunk", default="1,8",
                        help="comma-separated decode chunk sizes to A/B "
                             "(1 = per-token decode_step loop)")
    parser.add_argument("--overload", action="store_true",
                        help="overload A/B instead of the chunk A/B: drive "
                             "the deployment past saturation twice — "
                             "unbounded queue vs bounded queue + shedding — "
                             "and report shed rate, goodput, and completion "
                             "p99 per mode")
    parser.add_argument("--overload-duration", type=float, default=8.0)
    parser.add_argument("--overload-clients", type=int, default=24,
                        help="concurrent clients (~3x a 4-slot replica)")
    parser.add_argument("--trace", action="store_true",
                        help="observability mode: trace one streamed "
                             "request end to end, dump its span tree, "
                             "assert stage sums ≈ e2e, and check the "
                             "serve latency histograms on /metrics")
    parser.add_argument("--continuous", action="store_true",
                        help="continuous-batching A/B: static gang "
                             "@serve.batch vs the slot-pool DecodeEngine "
                             "under the same Poisson arrivals with mixed "
                             "output lengths")
    parser.add_argument("--paged", action="store_true",
                        help="paged-KV A/B: flat slot pool vs paged "
                             "pool at the SAME KV-byte budget, plus a "
                             "shared-prefix TTFT probe (direct engine "
                             "drive, no serve stack)")
    parser.add_argument("--chaos", action="store_true",
                        help="crash-safety run: kill a replica of a "
                             "2-replica engine deployment mid-load and "
                             "assert zero broken client streams "
                             "(deterministic replay resume)")
    parser.add_argument("--disagg", action="store_true",
                        help="disaggregated prefill/decode A/B "
                             "(ISSUE 14): the same bursty-prefill "
                             "Poisson mix driven through a colocated "
                             "deployment and a roles-split one at "
                             "equal offered load; reports decode TPOT "
                             "p50/p95 isolation, handoff latency, and "
                             "asserts zero broken streams and no "
                             "handoff leaks")
    parser.add_argument("--spec", action="store_true",
                        help="speculative-decoding A/B: spec off vs "
                             "n-gram vs tied-embedding model drafter "
                             "at equal offered load (direct engine "
                             "drive, no serve stack)")
    parser.add_argument("--draft-k", type=int, default=32,
                        help="proposals per verify round for --spec (a "
                             "verify forward's cost is dominated by the "
                             "max_len attention sweep, so wide drafts "
                             "are nearly free and locked-in repetitive "
                             "streams commit k+1 tokens per forward)")
    parser.add_argument("--page-size", type=int, default=8)
    parser.add_argument("--kv-dtype", default="fp",
                        choices=("fp", "int8"),
                        help="with --paged: int8 adds an equal-HBM-byte "
                             "fp-vs-int8 A/B arm (concurrent lanes, "
                             "TTFT p50, tok/s) after the flat/paged "
                             "rows (ISSUE 16)")
    parser.add_argument("--attn-kernel", default="gather",
                        choices=("gather", "pallas"),
                        help="with --paged: pallas adds a kernel-on vs "
                             "kernel-off TPOT A/B arm (CPU runs the "
                             "kernel in interpret mode — correctness "
                             "plumbing, not speed) (ISSUE 16)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel A/B (ISSUE 20): the same "
                             "saturating burst through a tp=1 engine "
                             "and one sharded over a --tp-wide mesh at "
                             "equal offered load; asserts temp-0 token "
                             "identity and equal dispatch accounting, "
                             "reports TPOT p50 and tok/s per arm (on "
                             "CPU the mesh is forced host devices — "
                             "plumbing and exactness, not speed)")
    parser.add_argument("--smoke", action="store_true",
                        help="with --continuous/--paged: shrunk load "
                             "for tier-1 CI (fewer requests, shorter "
                             "outputs)")
    parser.add_argument("--slots", type=int, default=8,
                        help="engine slot count == static max_batch_size")
    parser.add_argument("--rate", type=float, default=0.0,
                        help="Poisson arrival rate in req/s "
                             "(0 = calibrate from a single warm stream)")
    parser.add_argument("--requests", type=int, default=48,
                        help="requests per continuous A/B mode")
    args = parser.parse_args()
    chunks = [int(c) for c in args.chunk.split(",") if c.strip()]

    import numpy as np

    if args.tp > 1:
        # Direct engine drive: the A/B isolates the sharded compute
        # graph (column/row-parallel weights, head-sharded KV) from the
        # serve transport. On a host platform the mesh needs forced
        # devices — set the flag BEFORE jax initializes.
        if "jax" not in sys.modules and \
                "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count="
                f"{max(8, args.tp)}").strip()
        import jax as _jax

        cfg_name = args.config or (
            "small" if _jax.devices()[0].platform == "tpu" else "nano")
        run_tp_ab(args, np, cfg_name, f"gpt_{cfg_name}")
        return

    if args.paged:
        # Direct engine drive: the A/B isolates the pool architecture
        # (flat reservation vs pages) from the serve transport.
        import jax as _jax

        cfg_name = args.config or (
            "small" if _jax.devices()[0].platform == "tpu" else "nano")
        run_paged_ab(args, np, cfg_name, f"gpt_{cfg_name}")
        return

    if args.spec:
        # Direct engine drive again: the A/B isolates the dispatch-loop
        # arithmetic (k sequential target steps vs draft + one verify
        # forward) from the serve transport.
        import jax as _jax

        cfg_name = args.config or (
            "small" if _jax.devices()[0].platform == "tpu" else "nano")
        run_spec_ab(args, np, cfg_name, f"gpt_{cfg_name}")
        return

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8, ignore_reinit_error=True)
    if args.trace:
        from ray_tpu.util import tracing

        tracing.enable()  # before start(): proxies mirror the flag
        serve.start(http_options={"host": "127.0.0.1", "port": 0})
    else:
        serve.start(proxy=False)

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg_name = args.config or ("small" if on_tpu else "nano")
    max_new = args.tokens

    @serve.deployment(max_ongoing_requests=8)
    class GPTStream:
        """Decode-loop replica. chunk=1: one jitted decode step per
        streamed token. chunk=k: one fused k-step scan per streamed
        per-chunk token slice."""

        def __init__(self, cfg_name: str, max_len: int, chunk_sizes):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self._prefill = jax.jit(gpt_decode.prefill, static_argnums=(2,))
            self._step = jax.jit(gpt_decode.decode_step, static_argnums=(3,))
            self._chunk_steps = {
                k: gpt_decode.jit_decode_chunk(self.cfg, k)
                for k in chunk_sizes if k > 1}
            # Jitted-dispatch accounting for the A/B artifact; locked —
            # up to max_ongoing_requests threads decode concurrently.
            import threading as _threading

            self._stats_lock = _threading.Lock()
            self._dispatches = 0
            self._tokens = 0

        def _count(self, dispatches: int, tokens: int):
            with self._stats_lock:
                self._dispatches += dispatches
                self._tokens += tokens

        def warm(self, prompt_bucket: int, _=None):
            import jax.numpy as jnp

            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(
                self.params, jnp.zeros((1, prompt_bucket), jnp.int32),
                self.cfg, cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            self._step(self.params, cache, tok, self.cfg)
            rng = jax.random.PRNGKey(0)
            for step in self._chunk_steps.values():
                step(self.params, cache, tok, rng)
            return "warm"

        def reset_stats(self):
            with self._stats_lock:
                self._dispatches = 0
                self._tokens = 0
            return "reset"

        def stats(self):
            with self._stats_lock:
                return {"dispatches": self._dispatches,
                        "tokens": self._tokens}

        def __call__(self, request):
            """request = {"prompt_len", "max_new", "chunk"}; yields one
            token id per step (chunk=1) or one token-id list per fused
            chunk (chunk=k)."""
            import jax.numpy as jnp

            if hasattr(request, "json"):  # HTTP ingress
                request = request.json()
            plen = int(request.get("prompt_len", 16))
            max_new = int(request.get("max_new", 16))
            chunk = int(request.get("chunk", 1))
            prompt = jnp.asarray(
                np.random.randint(0, self.cfg.vocab_size, (1, plen),
                                  dtype=np.int32))
            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(self.params, prompt, self.cfg,
                                          cache)
            self._count(1, 0)
            if chunk <= 1:
                for _ in range(max_new):
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    self._count(0, 1)
                    yield int(tok[0])
                    logits, cache = self._step(self.params, cache, tok,
                                               self.cfg)
                    self._count(1, 0)
                return
            if max_new <= 0:
                return
            # Unlisted chunk size (e.g. ad-hoc HTTP request): jit on
            # demand instead of dying with a KeyError mid-stream. No
            # lock: dict get/set are GIL-atomic and jit_decode_chunk is
            # lru_cached, so racing threads get the same wrapper.
            step = self._chunk_steps.get(chunk)
            if step is None:
                step = self._chunk_steps[chunk] = \
                    self.gd.jit_decode_chunk(self.cfg, chunk)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            self._count(0, 1)
            yield [int(tok[0])]
            # The library driver IS the measured path: decode_until
            # yields exactly one trimmed slice per fused dispatch.
            for slice_ in self.gd.decode_until(
                    step, self.params, cache, tok, max_new - 1):
                self._count(1, slice_.shape[1])
                yield [int(t) for t in slice_[0]]

    # Cache sized for the worst chunk over-run: the last fused chunk may
    # execute up to (chunk - 1) steps past max_new before truncation.
    max_len = 16 + max_new + max(max(chunks), 8)
    if args.disagg:
        run_disagg_ab(args, serve, np, cfg_name, f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    if args.chaos:
        run_chaos_mode(args, serve, np, cfg_name, f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    if args.continuous:
        run_continuous_ab(args, serve, np, cfg_name, f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    if args.trace:
        run_trace_mode(args, rt, serve, np, cfg_name, max(chunks),
                       f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    if args.overload:
        run_overload_ab(args, serve, GPTStream, cfg_name, max_len, chunks,
                        f"gpt_{cfg_name}")
        serve.shutdown()
        rt.shutdown()
        return
    handle = serve.run(GPTStream.bind(cfg_name, max_len, chunks),
                       name="gpt_stream", route_prefix="/generate")
    assert handle.options(method_name="warm").remote(16).result(
        timeout=600) == "warm"
    # End-to-end warm stream per mode (covers the streaming transport).
    for c in chunks:
        list(handle.options(stream=True).remote(
            {"prompt_len": 16, "max_new": 2, "chunk": c}))

    model = f"gpt_{cfg_name}"

    def run_mode(chunk: int):
        handle.options(method_name="reset_stats").remote().result(
            timeout=60)
        ttfts, tok_lats = [], []
        total_tokens = [0]
        lock = threading.Lock()

        def client():
            for _ in range(args.streams):
                t0 = time.perf_counter()
                gen = handle.options(stream=True).remote(
                    {"prompt_len": 16, "max_new": max_new, "chunk": chunk})
                last = t0
                first = None
                n = 0
                lats = []
                for item in gen:
                    now = time.perf_counter()
                    width = len(item) if isinstance(item, list) else 1
                    if first is None:
                        first = now - t0
                    else:
                        # Amortized per-token latency: a fused chunk
                        # lands j tokens in one arrival.
                        lats.extend([(now - last) / width] * width)
                    last = now
                    n += width
                with lock:
                    ttfts.append(first)
                    tok_lats.extend(lats)
                    total_tokens[0] += n

        threads = [threading.Thread(target=client)
                   for _ in range(args.clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        stats = handle.options(method_name="stats").remote().result(
            timeout=60)
        dpt = stats["dispatches"] / max(stats["tokens"], 1)
        suffix = "" if chunk == 1 else f"_chunk{chunk}"
        ttfts.sort()
        tok_lats.sort()
        print(json.dumps({
            "metric": f"serve_{model}_ttft_p50_ms{suffix}",
            "value": round(ttfts[len(ttfts) // 2] * 1000, 2), "unit": "ms",
            "p95_ms": round(ttfts[int(len(ttfts) * 0.95)] * 1000, 2),
            "clients": args.clients, "chunk": chunk}))
        if tok_lats:
            print(json.dumps({
                "metric": f"serve_{model}_tok_latency_p50_ms{suffix}",
                "value": round(tok_lats[len(tok_lats) // 2] * 1000, 2),
                "unit": "ms",
                "p95_ms": round(tok_lats[int(len(tok_lats) * 0.95)] * 1000,
                                2),
                "p99_ms": round(tok_lats[int(len(tok_lats) * 0.99)] * 1000,
                                2),
                "chunk": chunk}))
        print(json.dumps({
            "metric": f"serve_{model}_decode_throughput{suffix}",
            "value": round(total_tokens[0] / wall, 1), "unit": "tokens/s",
            "clients": args.clients, "streams": args.clients * args.streams,
            "chunk": chunk}))
        print(json.dumps({
            "metric": f"serve_{model}_dispatches_per_token{suffix}",
            "value": round(dpt, 4), "unit": "dispatches/token",
            "dispatches": stats["dispatches"], "tokens": stats["tokens"],
            "chunk": chunk}))
        return {"chunk": chunk,
                "tok_p50_ms": round(
                    tok_lats[len(tok_lats) // 2] * 1000, 2)
                if tok_lats else None,
                "tok_s": round(total_tokens[0] / wall, 1),
                "dispatches_per_token": round(dpt, 4)}

    results = [run_mode(c) for c in chunks]
    _finish_chunk_ab(results, model, serve, rt)


def _finish_chunk_ab(results, model, serve, rt):
    if len(results) > 1:
        base = next((r for r in results if r["chunk"] == 1), results[0])
        best = min(results, key=lambda r: r["dispatches_per_token"])
        print(json.dumps({
            "metric": f"serve_{model}_chunked_decode_ab",
            "value": round(base["dispatches_per_token"]
                           / max(best["dispatches_per_token"], 1e-9), 2),
            "unit": "x_fewer_dispatches", "modes": results}))
    serve.shutdown()
    rt.shutdown()


def make_traced_deployment(serve, np):
    """Batched chunked-decode deployment for --trace: the ingress
    streams per-chunk token slices pulled from a ``@serve.batch``
    streaming handler, so ONE traced request crosses every serve stage
    — proxy admission, router queue, replica dispatch, batch flush, and
    one fused decode dispatch per chunk."""
    import jax

    @serve.deployment(max_ongoing_requests=4)
    class GPTTraced:
        def __init__(self, cfg_name: str, max_len: int, chunk: int):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self.chunk = chunk
            self._prefill = jax.jit(gpt_decode.prefill,
                                    static_argnums=(2,))
            self._chunk_step = gpt_decode.jit_decode_chunk(self.cfg,
                                                           chunk)

        def _stream_one(self, request):
            import jax.numpy as jnp

            plen = int(request.get("prompt_len", 16))
            max_new = int(request.get("max_new", 16))
            prompt = jnp.asarray(np.random.randint(
                0, self.cfg.vocab_size, (1, plen), dtype=np.int32))
            cache = self.gd.init_cache(self.cfg, 1, self.max_len)
            logits, cache = self._prefill(self.params, prompt, self.cfg,
                                          cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            yield [int(tok[0])]
            for slice_ in self.gd.decode_until(
                    self._chunk_step, self.params, cache, tok,
                    max_new - 1):
                yield [int(t) for t in slice_[0]]

        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.005,
                     stream=True)
        def decode_batch(self, requests):
            # Lockstep drive of the batched per-request generators; a
            # finished caller receives empty slices until the batch
            # drains (single-request trace mode never hits that path).
            gens = [self._stream_one(r) for r in requests]
            done = [False] * len(gens)
            while True:
                out = []
                for i, g in enumerate(gens):
                    if done[i]:
                        out.append([])
                        continue
                    try:
                        out.append(next(g))
                    except StopIteration:
                        done[i] = True
                        out.append([])
                if all(done):
                    return
                yield out

        def warm(self, plen: int = 16):
            list(self._stream_one({"prompt_len": plen,
                                   "max_new": self.chunk + 1}))
            return "warm"

        def __call__(self, request):
            if hasattr(request, "json"):  # HTTP ingress
                request = request.json()
            for slice_ in self.decode_batch(request):
                if slice_:
                    yield slice_

    return GPTTraced


def _span_tree(spans, root):
    """Children-of index for one trace + pretty printer."""
    kids = {}
    for s in spans:
        kids.setdefault(s.get("parent_id"), []).append(s)
    for v in kids.values():
        v.sort(key=lambda s: s["start"])
    lines = []

    def walk(span, depth):
        dur_ms = (span["end"] - span["start"]) * 1000
        lines.append(f"{'  ' * depth}{span['name']}  "
                     f"[{dur_ms:.2f} ms]  kind={span['kind']}")
        for c in kids.get(span["span_id"], []):
            walk(c, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def run_trace_mode(args, rt, serve, np, cfg_name, chunk, model):
    """One traced streamed request through the full data plane; dump the
    span tree, check the stage partition sums to ~e2e, and confirm the
    latency histograms landed on /metrics."""
    import urllib.request

    from ray_tpu.util import tracing

    # Enough decode work that the measured stages dominate the fixed
    # per-request overheads the partition cannot see (RPC transit,
    # chunk relay) — the 10% tolerance is on e2e.
    max_new = max(args.tokens, 64)
    max_len = 16 + max_new + max(chunk, 8)
    GPTTraced = make_traced_deployment(serve, np)
    handle = serve.run(
        GPTTraced.bind(cfg_name, max_len, chunk),
        name="gpt_trace", route_prefix="/trace")
    assert handle.options(method_name="warm").remote(16).result(
        timeout=600) == "warm"
    port = serve.status()["http"]["port"]

    body = json.dumps({"prompt_len": 16, "max_new": max_new}).encode()
    want = {"proxy.admission", "router.queue_wait", "replica.queue_wait",
            "user_code", "batch.wait", "decode.chunk"}

    def traced_request():
        """One streamed request; returns (its trace, server span,
        client-side e2e, head drop total)."""
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/trace", data=body, method="POST")
        sent_at = time.time()
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=120) as resp:
            tokens = 0
            for line in resp:
                if line.strip():
                    tokens += len(json.loads(line))
        e2e_client = time.perf_counter() - t0
        assert tokens >= max_new, f"stream returned {tokens} tokens"
        # The proxy flushes spans on a ~1s cadence; wait for the tree.
        deadline = time.time() + 30
        spans = []
        while time.time() < deadline:
            meta = tracing.get_spans(limit=100_000, with_meta=True)
            spans = meta["spans"]
            for s in spans:
                if s["kind"] == "server" and "[stream]" in s["name"] \
                        and s["start"] >= sent_at - 1.0:
                    mine = [x for x in spans
                            if x["trace_id"] == s["trace_id"]]
                    if want <= {x["name"] for x in mine}:
                        return mine, s, e2e_client, meta["dropped_total"]
            time.sleep(0.5)
        raise AssertionError(
            f"incomplete span tree; stages seen: "
            f"{sorted({x['name'] for x in spans})}")

    def dur(trace, name):
        return sum(s["end"] - s["start"] for s in trace
                   if s["name"] == name)

    # Stage partition of the critical path (batch.wait and decode.chunk
    # nest inside user_code): submission overhead + transit + handler
    # stream time should account for ~all of the server-observed e2e.
    # The residue is per-chunk relay overhead, which balloons when the
    # HOST is oversubscribed — take the best of a few attempts so the
    # check measures the instrumentation, not ambient machine load.
    best = None
    for attempt in range(3):
        trace, server, e2e_client, dropped = traced_request()
        e2e = server["end"] - server["start"]
        stage_sum = (dur(trace, "proxy.admission")
                     + dur(trace, "replica.queue_wait")
                     + dur(trace, "user_code"))
        gap = abs(e2e - stage_sum) / max(e2e, 1e-9)
        if best is None or gap < best[0]:
            best = (gap, trace, server, e2e, stage_sum, e2e_client,
                    dropped)
        if gap <= 0.10:
            break
    gap, trace, server, e2e, stage_sum, e2e_client, dropped = best
    print(_span_tree(trace, server))
    n_chunks = sum(1 for s in trace if s["name"] == "decode.chunk")
    print(json.dumps({
        "metric": f"serve_{model}_trace_stage_coverage",
        "value": round(stage_sum / max(e2e, 1e-9), 4),
        "unit": "fraction_of_e2e",
        "e2e_ms": round(e2e * 1000, 2),
        "client_e2e_ms": round(e2e_client * 1000, 2),
        "stage_sum_ms": round(stage_sum * 1000, 2),
        "decode_chunks": n_chunks,
        "spans_in_trace": len(trace),
        "spans_dropped_total": dropped,
    }))
    assert gap <= 0.10, \
        f"stage sum {stage_sum * 1000:.1f} ms deviates " \
        f"{gap:.0%} from e2e {e2e * 1000:.1f} ms (>10%)"
    assert n_chunks >= max_new // chunk, \
        f"expected ≥{max_new // chunk} decode.chunk spans, got {n_chunks}"

    # Histograms reach the head with the ~1s metric flush.
    needed = ["serve_request_e2e_seconds", "serve_ttft_seconds",
              "serve_tpot_seconds"]
    deadline = time.time() + 30
    counts = {}
    while time.time() < deadline:
        text = rt.metrics_text()
        counts = {}
        for n in needed:
            for line in text.splitlines():
                if line.startswith(f"ray_tpu_{n}_count"):
                    counts[n] = counts.get(n, 0.0) + float(line.rsplit(
                        " ", 1)[1])
        if all(counts.get(n, 0) > 0 for n in needed):
            break
        time.sleep(0.5)
    for n in needed:
        assert counts.get(n, 0) > 0, \
            f"{n} has no observations on /metrics: {counts}"
    print(json.dumps({
        "metric": f"serve_{model}_trace_histograms",
        "value": 1, "unit": "ok", "counts": counts}))


def pct(xs, q):
    """Nearest-rank percentile (no interpolation); None on empty."""
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(int(len(xs) * q), len(xs) - 1)]


def _mk_prompt(rid: int, plen: int, vocab: int):
    """Deterministic per-request prompt, identical across A/B modes."""
    import numpy as _np

    return _np.random.default_rng(1000 + rid).integers(
        0, vocab, (plen,)).astype(_np.int32)


def make_continuous_deployments(serve, np, plen: int, slots: int):
    """The two contenders, built on identical model weights.

    - ``GPTStatic``: the PRE-engine architecture — gang-scheduled
      ``@serve.batch(stream=True)`` with bucketed padding: a batch
      forms once, allocates a fresh KV cache, prefills all lanes
      together, and decodes in lockstep until the LONGEST lane
      finishes (shorter lanes ride along emitting nothing). A request
      arriving mid-generation waits for the next gang.
    - ``GPTContinuous``: the slot-pool engine behind
      ``@serve.batch(continuous=True)`` — persistent KV pool, per-slot
      admission at chunk boundaries, per-slot freeing at max_new.
    """
    import jax

    @serve.deployment(max_ongoing_requests=128)
    class GPTStatic:
        def __init__(self, cfg_name: str, max_len: int, chunk: int):
            from ray_tpu.models import gpt, gpt_decode

            self.cfg = gpt.CONFIGS[cfg_name]
            self.gd = gpt_decode
            self.params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.max_len = max_len
            self.chunk = chunk
            self._prefill = jax.jit(gpt_decode.prefill,
                                    static_argnums=(2,))

        @serve.batch(max_batch_size=slots, batch_wait_timeout_s=0.02,
                     pad_to_bucket=True, buckets=(slots,),
                     stream=True)
        def decode_batch(self, requests):
            import jax.numpy as jnp

            B = len(requests)        # == slots after padding
            prompts = np.stack([
                _mk_prompt(int(r["rid"]), plen, self.cfg.vocab_size)
                for r in requests])
            mns = [int(r["max_new"]) for r in requests]
            top = max(mns)
            # Fresh per-gang cache: exactly the allocation the engine's
            # persistent pool removes.
            cache = self.gd.init_cache(self.cfg, B, self.max_len)
            logits, cache = self._prefill(
                self.params, jnp.asarray(prompts), self.cfg, cache)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            first = np.asarray(tok)
            sent = [1] * B
            yield [[int(first[i])] if mns[i] >= 1 else []
                   for i in range(B)]
            if top <= 1:
                return
            step = self.gd.jit_decode_chunk(self.cfg, self.chunk)
            for slice_ in self.gd.decode_until(
                    step, self.params, cache, tok, top - 1):
                out = []
                for i in range(B):
                    take = slice_[i][:max(0, mns[i] - sent[i])]
                    sent[i] += len(take)
                    out.append([int(t) for t in take])
                yield out

        def warm(self, max_new: int = 2):
            return "warm"

        def __call__(self, request):
            if hasattr(request, "json"):
                request = request.json()
            return self.decode_batch(request)

    @serve.deployment(max_ongoing_requests=128)
    class GPTContinuous:
        def __init__(self, cfg_name: str, max_len: int, slots: int,
                     chunk: int):
            from ray_tpu.models import gpt
            from ray_tpu.serve.engine import DecodeEngine

            self.cfg = gpt.CONFIGS[cfg_name]
            params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.engine = DecodeEngine(
                params, self.cfg, slots=slots, chunk=chunk,
                max_len=max_len, prompt_buckets=(plen,),
                deployment="gpt_continuous")

        @serve.batch(continuous=True)
        def decode(self, request):
            return self.engine, {
                "prompt": _mk_prompt(int(request["rid"]), plen,
                                     self.cfg.vocab_size),
                "max_new": int(request["max_new"]),
                "seed": int(request["rid"])}

        def warm(self, max_new: int = 2):
            list(self.engine.stream(_mk_prompt(0, plen,
                                               self.cfg.vocab_size),
                                    max_new))
            return "warm"

        def stats(self):
            return self.engine.stats()

        def __call__(self, request):
            if hasattr(request, "json"):
                request = request.json()
            return self.decode(request)

    return GPTStatic, GPTContinuous


def run_continuous_ab(args, serve, np, cfg_name, model):
    """ISSUE 5 acceptance A/B: identical Poisson arrivals + mixed output
    lengths through the static gang and the slot engine; continuous mode
    should beat static on BOTH p50 TTFT and total tok/s."""
    import threading as _th

    slots = max(2, args.slots if not args.smoke else min(args.slots, 4))
    chunk = 8
    plen = 16
    n_req = args.requests if not args.smoke else min(args.requests, 12)
    base = args.tokens if not args.smoke else min(args.tokens, 8)
    # Wide output-length spread — the workload continuous batching
    # exists for: the gang rides every batch out to its LONGEST lane,
    # so its wasted lane-steps scale with max/mean of the mix.
    mix = sorted({max(2, base // 4), base, 2 * base}) if not args.smoke \
        else sorted({max(2, base // 4), max(3, base // 2), base})
    max_len = plen + mix[-1] + chunk
    sched = np.random.default_rng(42)
    max_news = sched.choice(mix, size=n_req)
    mean_new = float(np.mean(max_news))
    GPTStatic, GPTContinuous = make_continuous_deployments(
        serve, np, plen, slots)

    def drive(handle, rate):
        inter = np.random.default_rng(7).exponential(1.0 / rate,
                                                     size=n_req)
        arrivals = np.cumsum(inter)
        ttfts = [None] * n_req
        comps = [None] * n_req
        toks = [0] * n_req
        errs = [None] * n_req
        start = time.perf_counter()

        def one(i):
            delay = start + arrivals[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            try:
                gen = handle.options(stream=True, timeout_s=300).remote(
                    {"rid": int(i), "max_new": int(max_news[i])})
                first = None
                n = 0
                for item in gen:
                    w = len(item)
                    if w == 0:
                        continue  # gang lane finished early: empty slices
                    if first is None:
                        first = time.perf_counter() - t0
                    n += w
            except Exception as e:  # noqa: BLE001 - report in the assert
                errs[i] = repr(e)
                return
            ttfts[i] = first
            comps[i] = time.perf_counter() - t0
            toks[i] = n

        threads = [_th.Thread(target=one, args=(i,))
                   for i in range(n_req)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        bad = [(i, toks[i], int(max_news[i]), errs[i])
               for i in range(n_req) if toks[i] != max_news[i]]
        assert not bad, f"short/failed streams (i, got, want, err): {bad}"
        return ttfts, comps, wall, sum(toks)

    # Both deployments stay up for the whole A/B and the drive passes
    # INTERLEAVE (static, continuous, static, continuous): this box's
    # throughput drifts minutes-to-minutes, so back-to-back passes keep
    # the modes under the same machine conditions; best-of-N per mode
    # then discards the contention-slowed passes (noise on a shared
    # host is one-sided — it only ever slows a pass down).
    passes = 1 if args.smoke else 2
    handles = {}
    for mode, app in (("static", GPTStatic.bind(cfg_name, max_len, chunk)),
                      ("continuous",
                       GPTContinuous.bind(cfg_name, max_len, slots,
                                          chunk))):
        handle = serve.run(app, name=f"gpt_{mode}",
                           route_prefix=f"/{mode}")
        handle.options(method_name="warm").remote(2).result(timeout=600)
        # Compile the full-width programs before the clock starts.
        warm_threads = [_th.Thread(target=lambda: list(
            handle.options(stream=True).remote(
                {"rid": 0, "max_new": 2}))) for _ in range(slots)]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()
        handles[mode] = handle
    rate = args.rate
    if rate <= 0:
        # Calibrate offered load once, from a DEEP saturating burst
        # through the static gang: 3x`slots` UNIFORM-length streams all
        # queued at t=0, so every gang forms full-width (thread-start
        # jitter can't split gangs — the backlog refills them) and has
        # no ride-out waste. The aggregate rate approximates the ideal
        # full-width decode rate at THIS moment on THIS machine (an
        # UNDER-estimate when client-side overhead inflates elapsed
        # time, so err high). Offer 2x of it: both modes run
        # capacity-bound in every machine regime, so tok/s measures
        # architecture (gang ride-out waste vs slot recycling), not the
        # arrival schedule. Identical offered load for both modes.
        n_cal = 3 * slots
        t0 = time.perf_counter()
        burst = [_th.Thread(target=lambda: list(
            handles["static"].options(stream=True, timeout_s=300).remote(
                {"rid": 0, "max_new": int(base)})))
            for _ in range(n_cal)]
        for t in burst:
            t.start()
        for t in burst:
            t.join()
        ideal = n_cal * base / (time.perf_counter() - t0)
        rate = max(2.0, 2.0 * ideal / mean_new)
    runs = {"static": [], "continuous": []}
    for _ in range(passes):
        for mode in ("static", "continuous"):
            runs[mode].append(drive(handles[mode], rate))
    results = {}
    for mode in ("static", "continuous"):
        # Best pass by tok/s; its TTFT/completion percentiles ride along
        # so each reported row is one coherent measurement.
        ttfts, comps, wall, total = max(runs[mode],
                                        key=lambda r: r[3] / r[2])
        row = {
            "metric": f"serve_{model}_{mode}_mode",
            "value": round(total / wall, 1), "unit": "tokens/s",
            "ttft_p50_ms": round(pct(ttfts, 0.50) * 1000, 2),
            "ttft_p95_ms": round(pct(ttfts, 0.95) * 1000, 2),
            "completion_p50_ms": round(pct(comps, 0.50) * 1000, 2),
            "completion_p95_ms": round(pct(comps, 0.95) * 1000, 2),
            "requests": n_req, "passes": passes,
            "offered_rate_req_s": round(rate, 2),
            "offered_tok_s": round(rate * mean_new, 1),
            "tok_s_per_pass": [round(r[3] / r[2], 1) for r in runs[mode]],
            "slots": slots, "chunk": chunk,
            "output_len_mix": [int(m) for m in mix],
        }
        if mode == "continuous":
            st = handles[mode].options(
                method_name="stats").remote().result(timeout=60)
            row["avg_slot_occupancy"] = round(st["avg_occupancy"], 3)
            row["dispatches_per_token"] = round(
                st["dispatches_per_token"], 4)
            row["engine"] = {k: st[k] for k in
                             ("admitted", "completed", "dispatches",
                              "prefills", "tokens")}
        print(json.dumps(row))
        results[mode] = row
        serve.delete(f"gpt_{mode}")
    st, co = results["static"], results["continuous"]
    print(json.dumps({
        "metric": f"serve_{model}_continuous_ab",
        "value": round(co["value"] / max(st["value"], 1e-9), 2),
        "unit": "x_tokens_s_vs_static",
        "ttft_p50_ratio": round(st["ttft_p50_ms"]
                                / max(co["ttft_p50_ms"], 1e-9), 2),
        "continuous_wins_ttft": co["ttft_p50_ms"] < st["ttft_p50_ms"],
        "offered_rate_req_s": co["offered_rate_req_s"],
        "smoke": bool(args.smoke),
    }))


def run_paged_ab(args, np, cfg_name, model):
    """ISSUE 6 acceptance A/B: flat slot pool vs paged pool on the SAME
    KV-byte budget (``n_pages * page_size == flat_slots * max_len``
    cache positions), identical burst workload with a shared system
    prompt; then a shared-prefix TTFT probe (prefix-cached admission vs
    full prefill). Drives the engines directly — no serve stack — so
    the rows measure pool architecture, not transport."""
    import threading as _th

    import jax

    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import DecodeEngine

    cfg = gpt.CONFIGS[cfg_name]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ps = args.page_size
    chunk = 8
    flat_slots = 4 if args.smoke else max(4, args.slots // 2)
    max_len = 96 if args.smoke else min(192, cfg.max_seq)
    if ps < 1 or max_len % ps:
        sys.exit(f"--page-size {ps} must be a positive divisor of "
                 f"max_len={max_len} so the flat and paged pools can "
                 f"hold the same KV bytes (try one of "
                 f"{[d for d in (4, 8, 12, 16, 24, 32, 48) if max_len % d == 0]})")
    lanes = 3 * flat_slots            # paged lane count, same KV bytes
    n_pages = flat_slots * (max_len // ps)
    sys_len = 16 if args.smoke else 64
    tail_len = 8
    plen = sys_len + tail_len
    mix = [8, 16, 24] if args.smoke else [16, 32, 48]
    n_req = 4 * flat_slots if args.smoke else 6 * flat_slots
    buckets = tuple(b for b in (8, 16, 32, 64, 128)
                    if b <= max_len and b >= tail_len) or (max_len,)
    buckets = tuple(sorted(set(buckets) | {
        next(b for b in (8, 16, 32, 64, 128, max_len) if b >= plen)}))
    kv_positions = flat_slots * max_len
    assert n_pages * ps == kv_positions, "budgets must match"

    rng = np.random.default_rng(42)
    sysp = rng.integers(0, cfg.vocab_size, (sys_len,)).astype(np.int32)

    def mk_prompt(rid):
        tail = np.random.default_rng(500 + rid).integers(
            0, cfg.vocab_size, (tail_len,)).astype(np.int32)
        return np.concatenate([sysp, tail])

    max_news = np.random.default_rng(7).choice(mix, size=n_req)

    def build(paged):
        if paged:
            return DecodeEngine(
                params, cfg, slots=lanes, chunk=chunk, max_len=max_len,
                prompt_buckets=buckets, paged=True, page_size=ps,
                n_pages=n_pages, prefix_cache=True,
                deployment="paged_bench")
        return DecodeEngine(params, cfg, slots=flat_slots, chunk=chunk,
                            max_len=max_len, prompt_buckets=buckets,
                            deployment="flat_bench")

    def drive(eng):
        """Saturating burst: all n_req requests queued at t=0."""
        ttfts = [None] * n_req
        comps = [None] * n_req
        toks = [0] * n_req

        def one(i):
            t0 = time.perf_counter()
            first = None
            n = 0
            for s in eng.stream(mk_prompt(i), int(max_news[i]), seed=i):
                if first is None:
                    first = time.perf_counter() - t0
                n += s.shape[0]
            ttfts[i] = first
            comps[i] = time.perf_counter() - t0
            toks[i] = n

        threads = [_th.Thread(target=one, args=(i,))
                   for i in range(n_req)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        bad = [(i, toks[i], int(max_news[i]))
               for i in range(n_req) if toks[i] != max_news[i]]
        assert not bad, f"short streams (i, got, want): {bad}"
        return ttfts, comps, wall, sum(toks)

    def ttft_probe(eng, repeats=7):
        """Median TTFT of a lone request on an idle engine (the paged
        engine's prefix cache is warm by now: admission is a page-table
        copy + tail-bucket prefill instead of a full-prompt prefill)."""
        outs = []
        for r in range(repeats):
            t0 = time.perf_counter()
            it = eng.stream(mk_prompt(1000 + r), 2, seed=r)
            next(iter(it))
            outs.append(time.perf_counter() - t0)
            list(it)
        return pct(outs, 0.5)

    results = {}
    for mode in ("flat", "paged"):
        eng = build(mode == "paged")
        try:
            # Warm every compile path (and, paged, the prefix cache)
            # before the clock starts.
            for r in range(2):
                list(eng.stream(mk_prompt(0), max(mix), seed=0))
            ttfts, comps, wall, total = drive(eng)
            st = eng.stats()
            probe_ms = ttft_probe(eng) * 1000
            row = {
                "metric": f"serve_{model}_paged_{mode}_mode",
                "value": round(total / wall, 1), "unit": "tokens/s",
                "ttft_p50_ms": round(pct(ttfts, 0.5) * 1000, 2),
                "ttft_p95_ms": round(pct(ttfts, 0.95) * 1000, 2),
                "completion_p50_ms": round(pct(comps, 0.5) * 1000, 2),
                "completion_p95_ms": round(pct(comps, 0.95) * 1000, 2),
                "lone_ttft_p50_ms": round(probe_ms, 2),
                "slots_configured": st["slots"],
                "peak_concurrent_slots": st["peak_active"],
                "avg_occupancy": round(st["avg_occupancy"], 3),
                "dispatches_per_token": round(
                    st["dispatches_per_token"], 4),
                "kv_budget_positions": kv_positions,
                "requests": n_req, "chunk": chunk,
                "output_len_mix": [int(m) for m in mix],
                "prompt_len": plen, "shared_prefix_len": sys_len,
            }
            if mode == "paged":
                row.update({
                    "page_size": ps, "n_pages": n_pages,
                    "prefix_hits": st["prefix_hits"],
                    "prefix_tokens_reused": st["prefix_tokens_reused"],
                    "cow_copies": st["cow_copies"],
                    "lane_parks": st["lane_parks"],
                    "admissions_deferred": st["admissions_deferred"],
                    "preempted": st["preempted"],
                    "pages_free": st["pages_free"],
                })
            print(json.dumps(row))
            results[mode] = row
        finally:
            eng.shutdown()
    fl, pg = results["flat"], results["paged"]
    print(json.dumps({
        "metric": f"serve_{model}_paged_ab",
        "value": round(pg["peak_concurrent_slots"]
                       / max(fl["slots_configured"], 1), 2),
        "unit": "x_concurrent_slots_equal_kv_bytes",
        "tok_s_ratio": round(pg["value"] / max(fl["value"], 1e-9), 2),
        "ttft_p50_ratio": round(fl["ttft_p50_ms"]
                                / max(pg["ttft_p50_ms"], 1e-9), 2),
        "prefix_hit_ttft_ms": pg["lone_ttft_p50_ms"],
        "full_prefill_ttft_ms": fl["lone_ttft_p50_ms"],
        "prefix_ttft_speedup": round(
            fl["lone_ttft_p50_ms"]
            / max(pg["lone_ttft_p50_ms"], 1e-9), 2),
        "kv_budget_positions": kv_positions,
        "smoke": bool(args.smoke),
    }))
    if args.kv_dtype == "int8":
        _run_kv_dtype_arm(args, np, cfg, params, model)
    if args.attn_kernel == "pallas":
        _run_attn_kernel_arm(args, np, cfg, params, model)


def _drive_burst(eng, prompts, max_new, *, np):
    """Saturating burst shared by the ISSUE 16 arms: every request
    queued at t=0, one thread per request. Returns per-request
    (ttft, completion, tokens) plus the emitted token streams (for the
    kernel arm's token-identity check)."""
    import threading as _th

    n = len(prompts)
    ttfts = [None] * n
    comps = [None] * n
    streams = [None] * n

    def one(i):
        t0 = time.perf_counter()
        first = None
        out = []
        for s in eng.stream(prompts[i], int(max_new), seed=i):
            if first is None:
                first = time.perf_counter() - t0
            out.append(np.asarray(s))
        ttfts[i] = first
        comps[i] = time.perf_counter() - t0
        streams[i] = np.concatenate(out) if out else np.zeros(0, np.int32)

    threads = [_th.Thread(target=one, args=(i,)) for i in range(n)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    short = [(i, streams[i].shape[0]) for i in range(n)
             if streams[i].shape[0] != max_new]
    assert not short, f"short streams (i, got): {short}"
    return ttfts, comps, wall, streams


def _run_kv_dtype_arm(args, np, cfg, params, model):
    """ISSUE 16 A/B: fp-paged vs int8-paged pools on the SAME HBM byte
    budget, prefix cache OFF so every lane pays for its own pages. The
    binding resource is page BYTES: an int8 page (codes + amortized
    per-page scales) costs about half a bf16 page, so the equal-byte
    int8 pool holds ~2x the pages and admits ~2x the concurrent lanes.
    The workload is sized so a lane's admission-time page demand equals
    its lifetime demand (the prompt's last page absorbs the whole
    generation), making measured peak concurrency the page-capacity
    ratio rather than an admission-timing artifact."""
    from ray_tpu.models import gpt_decode
    from ray_tpu.serve.engine import DecodeEngine

    ps = args.page_size
    # plen one short of a page boundary; max_new fills the rest of the
    # final page: admit-time pages == lifetime pages == T.
    T = 4 if args.smoke else 6
    plen = (T - 1) * ps + 1
    max_new = T * ps - plen
    max_len = T * ps
    base_lanes = 3 if args.smoke else 4      # fp lane capacity
    fp_bytes = gpt_decode.kv_bytes_per_page(cfg, ps)
    i8_bytes = gpt_decode.kv_bytes_per_page(cfg, ps, "int8")
    n_pages_fp = base_lanes * T
    n_pages_i8 = (n_pages_fp * fp_bytes) // i8_bytes   # equal bytes
    cap_fp = n_pages_fp // T
    cap_i8 = n_pages_i8 // T
    slots = cap_i8 + 2                        # pages bind, not slots
    n_req = 3 * cap_i8
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32)
               for _ in range(n_req)]

    rows = {}
    for dt, n_pages in (("fp", n_pages_fp), ("int8", n_pages_i8)):
        eng = DecodeEngine(
            params, cfg, slots=slots, chunk=8, max_len=max_len,
            prompt_buckets=(plen,), paged=True, page_size=ps,
            n_pages=n_pages, prefix_cache=False, kv_dtype=dt,
            deployment=f"kv_{dt}_bench")
        try:
            list(eng.stream(prompts[0], max_new, seed=0))   # warm
            ttfts, comps, wall, streams = _drive_burst(
                eng, prompts, max_new, np=np)
            st = eng.stats()
            rows[dt] = {
                "metric": f"serve_{model}_kv_{dt}_mode",
                "value": round(n_req * max_new / wall, 1),
                "unit": "tokens/s",
                "ttft_p50_ms": round(pct(ttfts, 0.5) * 1000, 2),
                "completion_p50_ms": round(pct(comps, 0.5) * 1000, 2),
                "peak_concurrent_slots": st["peak_active"],
                "lane_capacity": n_pages // T,
                "n_pages": n_pages, "page_size": ps,
                "kv_bytes_per_page": fp_bytes if dt == "fp"
                else i8_bytes,
                "kv_bytes_per_token": st["kv_bytes_per_token"],
                "kv_budget_bytes": n_pages_fp * fp_bytes,
                "admissions_deferred": st["admissions_deferred"],
                "requests": n_req, "max_new": max_new,
                "prompt_len": plen,
            }
            print(json.dumps(rows[dt]))
        finally:
            eng.shutdown()
    # The sizing-fix satellite, shown live: an int8 engine left to the
    # DEFAULT n_pages computes its budget from the int8 element size
    # and gets ~2x the pages of the same-slot fp default.
    dflt = DecodeEngine(params, cfg, slots=base_lanes, chunk=8,
                        max_len=max_len, prompt_buckets=(plen,),
                        paged=True, page_size=ps, prefix_cache=False,
                        kv_dtype="int8", deployment="kv_dflt_bench")
    default_n_pages = dflt.n_pages
    dflt.shutdown()
    fp_row, i8_row = rows["fp"], rows["int8"]
    print(json.dumps({
        "metric": f"serve_{model}_kv_dtype_ab",
        "value": round(i8_row["peak_concurrent_slots"]
                       / max(fp_row["peak_concurrent_slots"], 1), 2),
        "unit": "x_concurrent_lanes_equal_kv_bytes",
        "lane_capacity_ratio": round(cap_i8 / max(cap_fp, 1), 2),
        "tok_s_ratio": round(i8_row["value"]
                             / max(fp_row["value"], 1e-9), 2),
        "ttft_p50_ratio": round(fp_row["ttft_p50_ms"]
                                / max(i8_row["ttft_p50_ms"], 1e-9), 2),
        "bytes_per_token_ratio": round(
            fp_row["kv_bytes_per_token"]
            / max(i8_row["kv_bytes_per_token"], 1e-9), 2),
        "default_n_pages_int8": int(default_n_pages),
        "default_n_pages_fp_equiv": base_lanes * T,
        "kv_budget_bytes": n_pages_fp * fp_bytes,
        "smoke": bool(args.smoke),
    }))


def _run_attn_kernel_arm(args, np, cfg, params, model):
    """ISSUE 16 A/B: paged decode with the fused paged-attention kernel
    on vs off (XLA gather reference), same engine geometry and burst.
    Reports TPOT p50 per arm and checks the exactness contract live:
    at temperature 0 the two arms must emit IDENTICAL token streams.
    On CPU the kernel runs in Pallas interpret mode — the arm proves
    plumbing and exactness there, not speed; the TPOT ratio is the
    headline only when lowered to a real TPU."""
    from ray_tpu.serve.engine import DecodeEngine

    ps = args.page_size
    plen = 2 * ps                             # two pages of history
    max_new = 8 if args.smoke else 16
    max_len = plen + max_new + ps
    slots = 2 if args.smoke else 4
    n_req = slots + 1                         # one lane reuses a slot
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32)
               for _ in range(n_req)]

    rows = {}
    token_streams = {}
    for kern in ("gather", "pallas"):
        eng = DecodeEngine(
            params, cfg, slots=slots, chunk=4, max_len=max_len,
            prompt_buckets=(plen,), paged=True, page_size=ps,
            prefix_cache=False, attn_kernel=kern,
            deployment=f"attn_{kern}_bench")
        try:
            list(eng.stream(prompts[0], max_new, seed=0))   # warm
            ttfts, comps, wall, streams = _drive_burst(
                eng, prompts, max_new, np=np)
            token_streams[kern] = streams
            tpots = [(comps[i] - ttfts[i]) / max(max_new - 1, 1)
                     for i in range(n_req)]
            st = eng.stats()
            rows[kern] = {
                "metric": f"serve_{model}_attn_{kern}_mode",
                "value": round(pct(tpots, 0.5) * 1000, 3),
                "unit": "tpot_p50_ms",
                "ttft_p50_ms": round(pct(ttfts, 0.5) * 1000, 2),
                "tok_s": round(n_req * max_new / wall, 1),
                "kernel_dispatches": st.get("attn_kernel_dispatches",
                                            0),
                "requests": n_req, "max_new": max_new,
                "prompt_len": plen,
            }
            print(json.dumps(rows[kern]))
        finally:
            eng.shutdown()
    identical = all(
        np.array_equal(token_streams["gather"][i],
                       token_streams["pallas"][i])
        for i in range(n_req))
    assert identical, "kernel arm diverged from gather at temp 0"
    import jax as _jax

    print(json.dumps({
        "metric": f"serve_{model}_attn_kernel_ab",
        "value": round(rows["gather"]["value"]
                       / max(rows["pallas"]["value"], 1e-9), 2),
        "unit": "x_tpot_gather_vs_kernel",
        "token_identical_temp0": identical,
        "kernel_dispatches": rows["pallas"]["kernel_dispatches"],
        "interpret_mode": _jax.default_backend() != "tpu",
        "smoke": bool(args.smoke),
    }))


def run_tp_ab(args, np, cfg_name, model):
    """ISSUE 20 acceptance A/B: the SAME saturating burst through a
    single-chip engine and one whose weights + paged KV are sharded
    over a ``tp``-wide mesh, at equal offered load. The exactness
    contract is checked live: at temperature 0 the sharded arm must
    emit IDENTICAL token streams (psum'd row-parallel partials, not
    approximately-equal ones), and its dispatch accounting must match
    chunk for chunk — the mesh changes where the FLOPs run, never how
    many driver-loop boundaries the stream crosses. On CPU the mesh is
    forced host devices, so the rows prove plumbing and exactness; the
    TPOT/tok-s ratio is the headline only on a real multi-chip host."""
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import DecodeEngine

    cfg = gpt.CONFIGS[cfg_name]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    ps = args.page_size
    plen = 2 * ps                             # two pages of history
    max_new = 8 if args.smoke else 24
    max_len = plen + max_new + ps
    slots = 2 if args.smoke else 4
    n_req = 2 * slots                         # lanes reuse slots
    rng = np.random.default_rng(20)
    prompts = [rng.integers(0, cfg.vocab_size, (plen,)).astype(np.int32)
               for _ in range(n_req)]

    rows = {}
    token_streams = {}
    accounting = {}
    for tp in (1, args.tp):
        eng = DecodeEngine(
            params, cfg, slots=slots, chunk=4, max_len=max_len,
            prompt_buckets=(plen,), paged=True, page_size=ps,
            prefix_cache=False, tp=tp, deployment=f"tp{tp}_bench")
        try:
            list(eng.stream(prompts[0], max_new, seed=0))   # warm
            ttfts, comps, wall, streams = _drive_burst(
                eng, prompts, max_new, np=np)
            token_streams[tp] = streams
            tpots = [(comps[i] - ttfts[i]) / max(max_new - 1, 1)
                     for i in range(n_req)]
            st = eng.stats()
            accounting[tp] = (st["prefills"], st["dispatches"])
            rows[tp] = {
                "metric": f"serve_{model}_tp{tp}_mode",
                "value": round(pct(tpots, 0.5) * 1000, 3),
                "unit": "tpot_p50_ms",
                "ttft_p50_ms": round(pct(ttfts, 0.5) * 1000, 2),
                "tok_s": round(n_req * max_new / wall, 1),
                "dispatches": st["dispatches"],
                "prefills": st["prefills"],
                "mesh": [["tp", tp]] if tp > 1 else [],
                "requests": n_req, "max_new": max_new,
                "prompt_len": plen,
            }
            print(json.dumps(rows[tp]))
        finally:
            eng.shutdown()
    identical = all(
        np.array_equal(token_streams[1][i], token_streams[args.tp][i])
        for i in range(n_req))
    assert identical, \
        f"tp={args.tp} arm diverged from tp=1 at temp 0"
    assert accounting[1] == accounting[args.tp], (
        f"dispatch accounting diverged: tp=1 {accounting[1]} vs "
        f"tp={args.tp} {accounting[args.tp]} (prefills, dispatches)")
    print(json.dumps({
        "metric": f"serve_{model}_tp_ab",
        "value": round(rows[1]["value"]
                       / max(rows[args.tp]["value"], 1e-9), 2),
        "unit": "x_tpot_tp1_vs_sharded",
        "tp": args.tp,
        "token_identical_temp0": identical,
        "dispatches_equal": accounting[1] == accounting[args.tp],
        "tok_s_tp1": rows[1]["tok_s"],
        "tok_s_sharded": rows[args.tp]["tok_s"],
        "host_mesh": jax.default_backend() != "tpu",
        "smoke": bool(args.smoke),
    }))


def run_spec_ab(args, np, cfg_name, model):
    """ISSUE 9 acceptance A/B: identical saturating bursts of
    repetitive-suffix prompts through three engines on the same
    weights — spec off, n-gram drafter, tied-embedding model drafter —
    INTERLEAVED passes with best-of-N per mode (same discipline as
    --continuous/--paged: noise on a shared host is one-sided). The
    workload is the one speculative decoding exists for — locally
    repetitive continuations — and is SCREENED for it: candidate
    repetitive-suffix prompts are generated, their greedy
    continuations simulated once against the n-gram drafter
    (host-side, deterministic), and the most predictable ones drive
    the A/B; the screen's acceptance distribution is reported so the
    selection is visible. Spec modes run with ``spec_threshold=2.5``
    (pool-wide adaptive speculation — on CPU a verify forward costs a
    sizable fraction of a fused chunk, so speculating through
    unpredictable phases would only burn forwards; on
    bandwidth-bound accelerators the threshold belongs at 0). Reports
    per mode: tok/s, TTFT p50, TPOT p50/p95; spec modes add
    accepted-tokens-per-target-forward and acceptance rate from the
    engine's own accounting."""
    import threading as _th

    import jax

    from ray_tpu.models import gpt, gpt_decode
    from ray_tpu.serve.draft import NGramDrafter
    from ray_tpu.serve.engine import DecodeEngine

    cfg = gpt.CONFIGS[cfg_name]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    chunk = 8
    draft_k = max(1, args.draft_k)
    spec_threshold = 2.5
    # Half the serving default: per-boundary host work is amortized
    # over committed tokens, and the spec path runs ~3x the boundaries
    # (cheaper each) — a leaner pool keeps the A/B measuring dispatch
    # arithmetic rather than python bookkeeping.
    slots = 4 if args.smoke else max(4, args.slots // 2)
    plen = 24
    mix = [12, 24] if args.smoke else [64, 88]
    n_req = 2 * slots if args.smoke else 4 * slots
    n_cand = n_req if args.smoke else 6 * n_req
    max_len = min(cfg.max_seq,
                  plen + mix[-1] + max(chunk, draft_k + 1))
    buckets = (plen,)
    modes = ("off", "ngram") if args.smoke else ("off", "ngram", "model")
    # This box's throughput drifts ~2x minutes-to-minutes (see the
    # --continuous calibration note): off/ngram run back-to-back in
    # EVERY pass and best-of-5 discards the contention-slowed passes
    # (noise on a shared host is one-sided). The model drafter is not
    # the headline — one pass documents it.
    passes = 1 if args.smoke else 5

    def mk_candidate(cid):
        # Repetitive-suffix prompt families: a repeated pattern of
        # period 1, 2, or 4 — the structure prompt-lookup drafting
        # feeds on.
        r = np.random.default_rng(700 + cid)
        kind = cid % 3
        if kind == 0:
            return np.full((plen,),
                           r.integers(0, cfg.vocab_size), np.int32)
        per = 2 if kind == 1 else 4
        pat = r.integers(0, cfg.vocab_size, (per,)).astype(np.int32)
        return np.concatenate([pat] * (plen // per))

    def sim_acceptance(prompt, toks):
        """Rounds of the n-gram drafter against a known greedy stream:
        the deterministic host-side screen (and a preview of what the
        engine's verify rounds will accept)."""
        d = NGramDrafter()
        d.configure(slots=1, max_len=max_len, prompt_buckets=buckets,
                    draft_k=draft_k)
        d.admit(0, prompt, int(toks[0]))
        i, rounds, acc = 1, 0, 0
        active = np.array([True])
        last = np.array([toks[0]], np.int32)
        while i < len(toks):
            props = d.propose(active, last)[0]
            a = 0
            while a < draft_k and i + a < len(toks) \
                    and props[a] == toks[i + a]:
                a += 1
            j = min(a + 1, len(toks) - i)
            d.observe(0, np.asarray(toks[i:i + j]), min(a, j - 1))
            last[0] = toks[i + j - 1]
            i += j
            rounds += 1
            acc += a
        d.free(0)
        return acc / max(rounds, 1)

    # Screen: greedy-decode every candidate once (also warms the
    # library programs) and keep the n_req most n-gram-predictable.
    scores = []
    for cid in range(n_cand):
        p = mk_candidate(cid)
        toks = np.concatenate([s[0] for s in gpt_decode.generate_chunked(
            params, p[None], cfg, mix[-1], chunk=chunk,
            max_len=max_len)]).tolist()
        scores.append((sim_acceptance(p, toks), cid))
    scores.sort(reverse=True)
    chosen = [cid for _score, cid in scores[:n_req]]
    screen = [round(s, 2) for s, _cid in scores[:n_req]]

    def mk_prompt(rid):
        return mk_candidate(chosen[rid % len(chosen)])

    max_news = np.random.default_rng(7).choice(mix, size=n_req)

    def build(mode):
        return DecodeEngine(
            params, cfg, slots=slots, chunk=chunk, max_len=max_len,
            prompt_buckets=buckets, draft_k=draft_k,
            spec_decode=None if mode == "off" else mode,
            spec_threshold=spec_threshold,
            deployment=f"spec_{mode}_bench")

    def drive(eng):
        """Saturating burst: all n_req requests queued at t=0 — equal
        offered load for every mode."""
        ttfts = [None] * n_req
        comps = [None] * n_req
        toks = [0] * n_req

        def one(i):
            t0 = time.perf_counter()
            first = None
            n = 0
            for s in eng.stream(mk_prompt(i), int(max_news[i]), seed=i):
                if first is None:
                    first = time.perf_counter() - t0
                n += s.shape[0]
            ttfts[i] = first
            comps[i] = time.perf_counter() - t0
            toks[i] = n

        threads = [_th.Thread(target=one, args=(i,))
                   for i in range(n_req)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        bad = [(i, toks[i], int(max_news[i]))
               for i in range(n_req) if toks[i] != max_news[i]]
        assert not bad, f"short streams (i, got, want): {bad}"
        # Amortized TPOT per stream: decode time after the first token.
        tpots = [(comps[i] - ttfts[i]) / max(toks[i] - 1, 1)
                 for i in range(n_req)]
        return ttfts, tpots, wall, sum(toks)

    engines = {}
    for mode in modes:
        eng = build(mode)
        # Warm every compile path (prefill bucket, chunk, verify, and
        # the model drafter's own programs) before the clock starts.
        list(eng.stream(mk_prompt(0), max(mix), seed=0))
        engines[mode] = eng
    runs = {m: [] for m in modes}
    try:
        for p in range(passes):
            for mode in modes:
                if mode == "model" and p > 0:
                    continue
                runs[mode].append(drive(engines[mode]))
        results = {}
        for mode in modes:
            ttfts, tpots, wall, total = max(runs[mode],
                                            key=lambda r: r[3] / r[2])
            st = engines[mode].stats()
            row = {
                "metric": f"serve_{model}_spec_{mode}_mode",
                "value": round(total / wall, 1), "unit": "tokens/s",
                "ttft_p50_ms": round(pct(ttfts, 0.50) * 1000, 2),
                "tpot_p50_ms": round(pct(tpots, 0.50) * 1000, 3),
                "tpot_p95_ms": round(pct(tpots, 0.95) * 1000, 3),
                "requests": n_req, "passes": passes,
                "tok_s_per_pass": [round(r[3] / r[2], 1)
                                   for r in runs[mode]],
                "slots": slots, "chunk": chunk,
                "output_len_mix": [int(m) for m in mix],
                "offered_tokens": int(sum(max_news)),
                "dispatches_per_token": round(
                    st["dispatches_per_token"], 4),
            }
            if mode != "off":
                sp = st["spec"]
                row.update({
                    "draft_k": draft_k,
                    "spec_threshold": spec_threshold,
                    "accepted_per_forward": round(
                        sp["accepted_per_forward"], 3),
                    "acceptance_rate": round(sp["acceptance_rate"], 4),
                    "mean_accept_len": round(sp["mean_accept_len"], 3),
                    "verify_rounds": sp["rounds"],
                    "fallback_rounds": sp["fallback_rounds"],
                })
            print(json.dumps(row))
            results[mode] = row
    finally:
        for eng in engines.values():
            eng.shutdown()
    off = results["off"]
    ng = results["ngram"]
    summary = {
        "metric": f"serve_{model}_spec_ab",
        "value": round(ng["value"] / max(off["value"], 1e-9), 2),
        "unit": "x_tokens_s_ngram_vs_off",
        "ngram_accepted_per_forward": ng["accepted_per_forward"],
        "ngram_acceptance_rate": ng["acceptance_rate"],
        "tpot_p50_ratio": round(off["tpot_p50_ms"]
                                / max(ng["tpot_p50_ms"], 1e-9), 2),
        "draft_k": draft_k,
        "spec_threshold": spec_threshold,
        "screen_sim_acceptance": screen,
        "screened_from": n_cand,
        "smoke": bool(args.smoke),
    }
    if "model" in results:
        md = results["model"]
        summary["model_x_tokens_s_vs_off"] = round(
            md["value"] / max(off["value"], 1e-9), 2)
        summary["model_accepted_per_forward"] = \
            md["accepted_per_forward"]
    print(json.dumps(summary))


def run_disagg_ab(args, serve, np, cfg_name, model):
    """ISSUE 14 acceptance: colocated vs disaggregated prefill/decode
    under a bursty-prefill Poisson mix at EQUAL offered load and equal
    replica counts (2 colocated vs 1 prefill + 1 decode).

    Steady decode streams (short prompts, long outputs) share the
    deployment with Poisson BURSTS of prefill-heavy requests (long
    prompts, 2 output tokens). Colocated, every burst prefill dispatch
    lands between the decode engine's chunk dispatches and inflates
    decode TPOT; disaggregated, bursts prefill on the prefill replica
    and reach the decode engine as a cheap KV import. Reports decode
    TPOT p50/p95 per mode, handoff latency/bytes, and asserts ZERO
    broken streams and NO handoff leaks (pages free back to baseline,
    no outstanding leases)."""
    import threading as _th

    import jax

    import ray_tpu as rt
    from ray_tpu.models import gpt, gpt_decode
    from ray_tpu.testing import _serve_replica_handles

    # Slots exceed the steady decode lanes so burst admissions always
    # find a free slot — the contention being measured is for the
    # DRIVER's dispatch stream (prefill programs between decode
    # chunks), not for slots.
    slots = 8
    chunk = 4
    plen_dec, plen_burst = 8, 112
    n_dec = 4 if args.smoke else 6
    dec_new = 64 if args.smoke else 96
    burst_size = 6
    burst_gap_s = 0.03
    max_len = 128
    cfg = gpt.CONFIGS[cfg_name]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)

    @serve.deployment(max_ongoing_requests=64,
                      health_check_period_s=1.0,
                      graceful_shutdown_timeout_s=10.0)
    class DisaggGPT:
        def __init__(self, cfg_name, max_len, slots, chunk, buckets):
            from ray_tpu.models import gpt as _gpt
            from ray_tpu.serve.engine import DecodeEngine

            self.cfg = _gpt.CONFIGS[cfg_name]
            p = _gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            # prefix_cache off: the leak check below wants pages_free
            # to return EXACTLY to baseline, with no cache pins.
            self.engine = DecodeEngine(
                p, self.cfg, slots=slots, chunk=chunk, max_len=max_len,
                prompt_buckets=tuple(buckets), paged=True, page_size=8,
                prefix_cache=False, deployment="gpt_disagg")

        @serve.batch(continuous=True)
        def decode(self, request):
            return self.engine, {
                "prompt": _mk_prompt(int(request["rid"]),
                                     int(request["plen"]),
                                     self.cfg.vocab_size),
                "max_new": int(request["max_new"]),
                "seed": int(request["rid"])}

        def warm(self, plen: int, max_new: int = 2):
            list(self.engine.stream(
                _mk_prompt(0, plen, self.cfg.vocab_size), max_new))
            return "warm"

        def __call__(self, request):
            return self.decode(request)

    refs = {i: np.concatenate([s[0] for s in gpt_decode.generate_chunked(
        params, _mk_prompt(1000 + i, plen_dec, cfg.vocab_size)[None],
        cfg, dec_new, chunk=chunk, max_len=max_len)])
        for i in range(n_dec)}

    def run_mode(disagg: bool):
        name = "gpt_disagg"
        dep = DisaggGPT.options(
            name=name,
            num_replicas=None if disagg else 2,
            engine_config={"roles": {"prefill": 1, "decode": 1},
                           "handoff_ttl_s": 15.0} if disagg else None)
        handle = serve.run(dep.bind(cfg_name, max_len, slots, chunk,
                                    (plen_dec, plen_burst)),
                           name=name, route_prefix=None)
        # Warm every replica's programs (prefill buckets + chunk +
        # export/import) before the clock starts.
        for h in _serve_replica_handles(name, name).values():
            for plen in (plen_dec, plen_burst):
                try:
                    rt.get(h.handle_request.remote(
                        "warm", (plen,), {}, {}), timeout=600)
                except Exception:  # noqa: BLE001 - prefill-role engine
                    pass           # warms through the handoff below
        for _ in range(2):
            list(handle.options(stream=True).remote(
                {"rid": 0, "plen": plen_dec, "max_new": 2}))
            list(handle.options(stream=True).remote(
                {"rid": 0, "plen": plen_burst, "max_new": 2}))

        tpot_ms, ttft_ms = [], []
        results = [None] * n_dec
        errors = [None] * n_dec
        done = _th.Event()

        def dec_stream(i):
            try:
                toks = []
                t0 = time.perf_counter()
                last = None
                it = handle.options(stream=True, resumable=True,
                                    timeout_s=300.0).remote(
                    {"rid": 1000 + i, "plen": plen_dec,
                     "max_new": dec_new})
                for item in it:
                    now = time.perf_counter()
                    w = np.asarray(item).ravel()
                    if last is None:
                        ttft_ms.append((now - t0) * 1000)
                    elif len(w):
                        tpot_ms.extend([(now - last) * 1000 / len(w)]
                                       * len(w))
                    last = now
                    toks.extend(int(t) for t in w)
                results[i] = toks
            except Exception as e:  # noqa: BLE001 - counted as broken
                errors[i] = repr(e)

        bursts = {"offered": 0, "errors": 0}

        def burst_client():
            # Poisson bursts of prefill-heavy requests, identical
            # schedule both modes (seeded RNG), until decode finishes.
            import random as _rnd

            r = _rnd.Random(77)
            rid = 5000
            while not done.is_set():
                time.sleep(r.expovariate(1.0 / burst_gap_s))
                ths = []
                for _ in range(burst_size):
                    rid += 1

                    def one(rid=rid):
                        try:
                            list(handle.options(
                                stream=True, timeout_s=120.0).remote(
                                {"rid": rid, "plen": plen_burst,
                                 "max_new": 2}))
                        except Exception:  # noqa: BLE001 - counted
                            bursts["errors"] += 1
                    t = _th.Thread(target=one)
                    t.start()
                    ths.append(t)
                    bursts["offered"] += 1
                for t in ths:
                    t.join()

        t_start = time.perf_counter()
        dec_threads = [_th.Thread(target=dec_stream, args=(i,))
                       for i in range(n_dec)]
        burst_thread = _th.Thread(target=burst_client)
        for t in dec_threads:
            t.start()
            time.sleep(0.02)
        burst_thread.start()
        for t in dec_threads:
            t.join()
        done.set()
        burst_thread.join()
        wall = time.perf_counter() - t_start

        broken = [(i, errors[i]) for i in range(n_dec)
                  if errors[i] is not None
                  or results[i] != [int(t) for t in refs[i]]]

        # Handoff accounting + leak check across the surviving fleet:
        # every lease claimed or swept, every page back on the free
        # list (prefix cache off, so baseline == n_pages).
        handles = _serve_replica_handles(name, name)
        agg = {"exported": 0, "imported": 0, "import_fallbacks": 0,
               "ship_bytes": 0, "leases_outstanding": 0,
               "leases_reclaimed": 0}
        leaks = None
        deadline = time.time() + 20
        while time.time() < deadline:
            agg = {k: 0 for k in agg}
            leaked_pages = 0
            for h in handles.values():
                m = rt.get(h.get_metrics.remote(), timeout=10)
                est = (m.get("engines") or [{}])[0]
                for k in agg:
                    agg[k] += int(est.get("handoff", {}).get(k, 0))
                if est.get("paged"):
                    leaked_pages += int(est.get("pages_used", 0))
            leaks = agg["leases_outstanding"] + leaked_pages
            if leaks == 0:
                break
            time.sleep(0.5)

        mode = "disagg" if disagg else "colocated"
        row = {
            "metric": f"serve_{model}_disagg_{mode}_mode",
            "value": round(pct(tpot_ms, 0.95) or 0.0, 3),
            "unit": "decode_tpot_p95_ms",
            "tpot_p50_ms": round(pct(tpot_ms, 0.5) or 0.0, 3),
            "tpot_p95_ms": round(pct(tpot_ms, 0.95) or 0.0, 3),
            "ttft_p50_ms": round(pct(ttft_ms, 0.5) or 0.0, 1),
            "decode_streams": n_dec,
            "decode_tokens": int(sum(len(r) for r in results
                                     if r is not None)),
            "burst_requests": bursts["offered"],
            "burst_errors": bursts["errors"],
            "broken_streams": len(broken),
            "handoffs_exported": agg["exported"],
            "handoffs_imported": agg["imported"],
            "import_fallbacks": agg["import_fallbacks"],
            "ship_bytes": agg["ship_bytes"],
            "leases_reclaimed": agg["leases_reclaimed"],
            "handoff_leaks": leaks,
            "wall_s": round(wall, 2),
        }
        print(json.dumps(row))
        assert not broken, f"broken decode streams ({mode}): {broken[:3]}"
        serve.delete(name)
        return row

    coloc = run_mode(disagg=False)
    disagg = run_mode(disagg=True)

    # Mean handoff latency from the head-merged histogram (observed by
    # the decode replicas; the bench process cannot see it locally).
    handoff_ms = None
    try:
        total = {"sum": 0.0, "count": 0.0}
        for line in rt.metrics_text().splitlines():
            if line.startswith("ray_tpu_serve_kv_handoff_seconds_sum"):
                total["sum"] += float(line.rsplit(" ", 1)[1])
            elif line.startswith(
                    "ray_tpu_serve_kv_handoff_seconds_count"):
                total["count"] += float(line.rsplit(" ", 1)[1])
        if total["count"]:
            handoff_ms = round(total["sum"] / total["count"] * 1000, 2)
    except Exception:  # noqa: BLE001 - head mid-flush
        pass

    summary = {
        "metric": f"serve_{model}_disagg_ab",
        "value": round(coloc["tpot_p95_ms"]
                       / max(disagg["tpot_p95_ms"], 1e-9), 2),
        "unit": "x_decode_tpot_p95_colocated_vs_disagg",
        "tpot_p50_ratio": round(coloc["tpot_p50_ms"]
                                / max(disagg["tpot_p50_ms"], 1e-9), 2),
        "colocated_tpot_p95_ms": coloc["tpot_p95_ms"],
        "disagg_tpot_p95_ms": disagg["tpot_p95_ms"],
        "handoff_mean_ms": handoff_ms,
        "handoffs_imported": disagg["handoffs_imported"],
        "import_fallbacks": disagg["import_fallbacks"],
        "ship_bytes": disagg["ship_bytes"],
        "broken_streams": coloc["broken_streams"]
        + disagg["broken_streams"],
        "handoff_leaks": (coloc["handoff_leaks"] or 0)
        + (disagg["handoff_leaks"] or 0),
        "burst_requests": [coloc["burst_requests"],
                           disagg["burst_requests"]],
        "smoke": bool(args.smoke),
    }
    print(json.dumps(summary))
    assert summary["handoff_leaks"] == 0, \
        "handoff leaked pages or leases past the run"
    assert disagg["handoffs_imported"] >= 1, \
        "disaggregated mode never imported a handoff"


def run_chaos_mode(args, serve, np, cfg_name, model):
    """ISSUE 7 acceptance: a 2-replica continuous-engine deployment
    serves seeded deterministic streams under load; ONE replica is
    hard-killed mid-load. Every client stream is submitted with
    ``resumable=True`` — a stream cut mid-flight re-routes to the
    survivor with its replay token and must complete TOKEN-IDENTICAL to
    its uninterrupted reference. The row asserts zero broken streams."""
    import threading as _th

    import jax

    import ray_tpu as rt
    from ray_tpu._private.metrics import serve_metrics
    from ray_tpu.models import gpt, gpt_decode
    from ray_tpu.testing import _serve_replica_handles, inject_engine_fault

    slots = 4
    chunk = 8
    plen = 16
    n_req = 10 if args.smoke else min(args.requests, 32)
    base = min(args.tokens, 16) if args.smoke else max(args.tokens, 32)
    max_len = plen + 2 * base + chunk
    cfg = gpt.CONFIGS[cfg_name]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    max_news = np.random.default_rng(7).integers(base, 2 * base + 1,
                                                 size=n_req)

    @serve.deployment(num_replicas=2, max_ongoing_requests=64,
                      health_check_period_s=0.5,
                      graceful_shutdown_timeout_s=10.0)
    class ChaosGPT:
        def __init__(self, cfg_name, max_len, slots, chunk, plen):
            from ray_tpu.models import gpt as _gpt
            from ray_tpu.serve.engine import DecodeEngine

            self.cfg = _gpt.CONFIGS[cfg_name]
            p = _gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.plen = plen
            self.engine = DecodeEngine(
                p, self.cfg, slots=slots, chunk=chunk, max_len=max_len,
                prompt_buckets=(plen,), deployment="gpt_chaos")

        @serve.batch(continuous=True)
        def decode(self, request):
            rid = int(request["rid"])
            return self.engine, {
                "prompt": _mk_prompt(rid, self.plen,
                                     self.cfg.vocab_size),
                "max_new": int(request["max_new"]), "seed": rid}

        def warm(self, max_new: int = 2):
            list(self.engine.stream(
                _mk_prompt(0, self.plen, self.cfg.vocab_size), max_new))
            return "warm"

        def __call__(self, request):
            if hasattr(request, "json"):
                request = request.json()
            return self.decode(request)

    handle = serve.run(
        ChaosGPT.bind(cfg_name, max_len, slots, chunk, plen),
        name="gpt_chaos", route_prefix="/chaos")
    handle.options(method_name="warm").remote(2).result(timeout=600)
    # Compile both replicas' programs before the clock starts.
    warm_threads = [_th.Thread(target=lambda: list(
        handle.options(stream=True).remote({"rid": 0, "max_new": 2})))
        for _ in range(4)]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()
    # Throttle the engines so the kill reliably lands while streams are
    # mid-flight. The smoke run carries far fewer tokens, so it needs a
    # heavier per-chunk stall to stay airborne past the kill (the total
    # dispatch count times the throttle must comfortably exceed the
    # time it takes the first third of the streams to yield a token).
    inject_engine_fault("gpt_chaos", "ChaosGPT", kind="driver_slow",
                        wedge_s=0.05 if args.smoke else 0.02)

    refs = {int(i): gpt_decode.generate_chunked(
        params, _mk_prompt(int(i), plen, cfg.vocab_size)[None], cfg,
        int(max_news[i]), chunk=chunk, max_len=max_len)
        for i in range(n_req)}
    refs = {i: np.concatenate([s[0] for s in r]) for i, r in refs.items()}

    resumes0 = sum(v for _k, v in
                   serve_metrics()["stream_resumes"].collect())
    first_tokens = _th.Semaphore(0)
    results = [None] * n_req
    errors = [None] * n_req
    stalls = [0.0] * n_req

    def one(i):
        try:
            toks = []
            last = time.perf_counter()
            it = handle.options(stream=True, resumable=True,
                                timeout_s=300.0).remote(
                {"rid": int(i), "max_new": int(max_news[i])})
            for item in it:
                now = time.perf_counter()
                stalls[i] = max(stalls[i], now - last)
                last = now
                w = np.asarray(item).ravel()
                if not toks:
                    first_tokens.release()
                toks.extend(int(t) for t in w)
            results[i] = np.asarray(toks, np.int32)
        except Exception as e:  # noqa: BLE001 - counted as broken
            errors[i] = repr(e)

    def launch():
        for i in range(n_req):
            results[i], errors[i], stalls[i] = None, None, 0.0
        ths = [_th.Thread(target=one, args=(i,)) for i in range(n_req)]
        for t in ths:
            t.start()
            time.sleep(0.02)       # staggered arrivals
        return ths

    def count_resumes():
        return sum(v for _k, v in
                   serve_metrics()["stream_resumes"].collect()) - resumes0

    handles = _serve_replica_handles("gpt_chaos", "ChaosGPT")
    t_start = time.perf_counter()
    threads = launch()

    # Arm a deterministic mid-stream kill on the BUSIER replica once a
    # third of the streams are flowing: the engine hard-exits the
    # replica process at the NEXT delivered token, so the kill lands
    # while a stream is delivering BY CONSTRUCTION — an outside-in
    # rt.kill races stream completion on a loaded box.
    for _ in range(max(2, n_req // 3)):
        first_tokens.acquire(timeout=60)
    busiest, busiest_slots, busiest_toks = None, -1, 0
    for rid_, h in handles.items():
        try:
            m = rt.get(h.get_metrics.remote(), timeout=10)
            est = (m.get("engines") or [{}])[0]
            act = est.get("active_slots", 0)
        except Exception:  # noqa: BLE001
            act, est = 0, {}
        if act > busiest_slots:
            busiest, busiest_slots = rid_, act
            busiest_toks = int(est.get("tokens", 0))
    busiest = busiest if busiest is not None else next(iter(handles))
    rt.get(handles[busiest].inject_engine_fault.remote(
        "kill_process", busiest_toks + 1, 0.0), timeout=10)
    t_kill = time.perf_counter()
    kills = 1

    for t in threads:
        t.join()
    rounds = 1
    if not any(errors) and count_resumes() == 0:
        # Every stream outran the armed kill (tiny smoke loads on a
        # contended box): the one-shot fault is STILL armed and fires
        # at the armed replica's next delivered token — one more
        # identical round guarantees a mid-stream kill.
        rounds = 2
        threads = launch()
        t_kill = time.perf_counter()
        for t in threads:
            t.join()
    wall = time.perf_counter() - t_start

    broken = []
    for i in range(n_req):
        if errors[i] is not None:
            broken.append((i, errors[i]))
        elif results[i] is None or len(results[i]) != len(refs[i]) \
                or not (results[i] == refs[i]).all():
            broken.append((i, f"token mismatch: got "
                              f"{None if results[i] is None else len(results[i])}"
                              f" want {len(refs[i])}"))
    resumes = count_resumes()
    completed = sum(r is not None for r in results)
    # Runtime-sanitizer verdict from the SURVIVING replicas (ISSUE 13):
    # under RT_SAN=1 every replica engine carries a sanitizer block in
    # stats(); a chaos run that recovered cleanly must also have zero
    # runtime findings (no lock-order cycles, no blocking-under-lock).
    from ray_tpu.testing import engine_sanitizer_findings

    san_findings = engine_sanitizer_findings("gpt_chaos", "ChaosGPT")
    row = {
        "metric": f"serve_{model}_chaos_recovery",
        "value": len(broken), "unit": "broken_streams",
        "broken_streams": len(broken),
        "requests": n_req, "completed": completed,
        "kills": kills, "killed_replica": busiest,
        "rounds": rounds,
        "active_slots_at_kill": busiest_slots,
        "stream_resumes": int(resumes),
        "max_stall_ms": round(max(stalls) * 1000, 1),
        "stall_p50_ms": round(sorted(stalls)[len(stalls) // 2] * 1000, 1),
        "kill_at_s": round(t_kill - t_start, 2),
        "wall_s": round(wall, 2),
        "tokens_total": int(sum(len(r) for r in results
                                if r is not None)),
        "output_tokens": [int(m) for m in max_news],
        "sanitizer_findings": san_findings,
        "smoke": bool(args.smoke),
    }
    print(json.dumps(row))
    assert not broken, f"broken client streams after replica kill: " \
                       f"{broken[:4]}"
    assert resumes >= 1, \
        "the kill interrupted no stream — chaos run proved nothing"
    assert san_findings in (None, 0), \
        f"rtsan found {san_findings} runtime findings during chaos"
    serve.delete("gpt_chaos")


def run_overload_ab(args, serve, GPTStream, cfg_name, max_len, chunks,
                    model):
    """Overload A/B (ISSUE 2 CI satellite): offered load ~3x a 4-slot
    replica, once with an effectively unbounded admission queue and once
    with the bounded queue + shedding. Reports shed rate, goodput
    (completed tokens/s), and completion p50/p99 of ACCEPTED streams per
    mode — the bounded mode should hold p99 roughly at the service time
    of a full pipeline while the unbounded mode's p99 grows with the
    queue."""
    from ray_tpu.serve import BackPressureError, RequestDeadlineExceeded

    chunk = max(chunks)
    max_new = min(args.tokens, 8)
    timeout_s = 10.0
    summary = []
    for mode, max_queued in (("unshed", 1_000_000), ("shed", 4)):
        handle = serve.run(
            GPTStream.options(num_replicas=1, max_ongoing_requests=4,
                              max_queued_requests=max_queued)
            .bind(cfg_name, max_len, chunks),
            name="gpt_overload", route_prefix="/overload")
        handle.options(method_name="warm").remote(16).result(timeout=600)
        list(handle.options(stream=True).remote(
            {"prompt_len": 16, "max_new": 2, "chunk": chunk}))

        lock = threading.Lock()
        stats = {"offered": 0, "completed": 0, "shed": 0, "expired": 0,
                 "errors": 0, "tokens": 0}
        completion_s = []
        stop_at = time.perf_counter() + args.overload_duration

        def client():
            while time.perf_counter() < stop_at:
                with lock:
                    stats["offered"] += 1
                t0 = time.perf_counter()
                try:
                    gen = handle.options(
                        stream=True, timeout_s=timeout_s).remote(
                        {"prompt_len": 16, "max_new": max_new,
                         "chunk": chunk})
                    n = 0
                    for item in gen:
                        n += len(item) if isinstance(item, list) else 1
                    with lock:
                        stats["completed"] += 1
                        stats["tokens"] += n
                        completion_s.append(time.perf_counter() - t0)
                except BackPressureError:
                    with lock:
                        stats["shed"] += 1
                    time.sleep(0.05)  # honor the backoff contract
                except (RequestDeadlineExceeded, TimeoutError):
                    with lock:
                        stats["expired"] += 1
                except Exception:  # noqa: BLE001
                    with lock:
                        stats["errors"] += 1

        threads = [threading.Thread(target=client)
                   for _ in range(args.overload_clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        completion_s.sort()
        p50 = completion_s[len(completion_s) // 2] if completion_s else None
        p99 = completion_s[int(len(completion_s) * 0.99)] \
            if completion_s else None
        row = {
            "metric": f"serve_{model}_overload_{mode}",
            "value": round(stats["tokens"] / wall, 1),
            "unit": "goodput_tokens_s",
            "offered": stats["offered"], "completed": stats["completed"],
            "shed": stats["shed"], "expired": stats["expired"],
            "errors": stats["errors"],
            "shed_rate": round(stats["shed"] / max(stats["offered"], 1), 3),
            "completion_p50_s": round(p50, 3) if p50 else None,
            "completion_p99_s": round(p99, 3) if p99 else None,
            "clients": args.overload_clients,
            "max_queued_requests": max_queued,
        }
        print(json.dumps(row))
        summary.append(row)
        serve.delete("gpt_overload")
    if len(summary) == 2:
        unshed, shed = summary
        print(json.dumps({
            "metric": f"serve_{model}_overload_ab_p99_ratio",
            "value": round((unshed["completion_p99_s"] or 0)
                           / max(shed["completion_p99_s"] or 1e-9, 1e-9), 2),
            "unit": "x_p99_unshed_vs_shed",
            "goodput_ratio": round(shed["value"]
                                   / max(unshed["value"], 1e-9), 2)}))


if __name__ == "__main__":
    main()
