"""Batched ResNet-50 serving benchmark (BASELINE.md:63 — "batched
ResNet-50 serving replica (p50 latency)", the reference's headline Serve
config).

One replica hosts a jitted bf16 ResNet-50; ``@serve.batch`` coalesces
concurrent requests and pads each batch to a bucket size so XLA compiles
once per bucket. N closed-loop clients fire requests; we report p50/p99
latency and throughput as JSON lines.

Run: ``python benchmarks/serve_resnet.py [--clients 16] [--secs 10]``
(CPU fallback uses a shrunken resnet18 so the benchmark completes).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--secs", type=float, default=10.0)
    parser.add_argument("--max-batch", type=int, default=16)
    args = parser.parse_args()

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8, ignore_reinit_error=True)
    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    depth, size = (50, 224) if on_tpu else (18, 64)

    @serve.deployment(max_ongoing_requests=64)
    class ResNetReplica:
        def __init__(self, depth: int, size: int, max_batch: int):
            from ray_tpu.models import resnet

            self.cfg = resnet.ResNetConfig(depth=depth)
            params = resnet.init_params(jax.random.PRNGKey(0), self.cfg)
            self.predict = resnet.make_predictor(self.cfg, params,
                                                 uint8_input=True)
            self.size = size
            self.max_batch = max_batch

        def warm(self, _=None):
            # Compile every bucket AFTER deploy (first XLA compile can
            # exceed the deploy-ready timeout) so p50 excludes compiles.
            from ray_tpu.serve.batching import default_buckets

            for b in default_buckets(self.max_batch):
                np.asarray(self.predict(np.zeros(
                    (b, self.size, self.size, 3), np.uint8)))
            return "warm"

        # Class is defined inside main(), so the decorator can take the
        # CLI's batch size — serving and warmup always agree on buckets.
        @serve.batch(max_batch_size=args.max_batch,
                     batch_wait_timeout_s=0.005, pad_to_bucket=True)
        def run_batch(self, images_list):
            batch = np.stack(images_list)
            out = np.asarray(self.predict(batch))
            return [int(row.argmax()) for row in out]

        def __call__(self, _request=None):
            img = np.random.randint(
                0, 256, (self.size, self.size, 3), np.uint8)
            return self.run_batch(img)

    handle = serve.run(
        ResNetReplica.bind(depth, size, args.max_batch),
        name="resnet", route_prefix=None)
    assert handle.options(method_name="warm").remote().result() == "warm"
    handle.remote().result()  # end-to-end warm

    latencies = []
    lock = threading.Lock()
    stop = time.time() + args.secs

    def client():
        while time.time() < stop:
            t0 = time.perf_counter()
            handle.remote().result()
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=client) for _ in range(args.clients)]
    t_start = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t_start

    latencies.sort()
    n = len(latencies)
    p50 = latencies[n // 2] * 1000
    p99 = latencies[min(n - 1, int(n * 0.99))] * 1000
    model = f"resnet{depth}@{size}px"
    print(json.dumps({"metric": f"serve_{model}_p50_ms",
                      "value": round(p50, 2), "unit": "ms",
                      "clients": args.clients,
                      "p99_ms": round(p99, 2)}))
    print(json.dumps({"metric": f"serve_{model}_throughput",
                      "value": round(n / wall, 1), "unit": "req/s",
                      "clients": args.clients}))
    serve.shutdown()
    rt.shutdown()


if __name__ == "__main__":
    main()
