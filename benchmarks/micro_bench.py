"""Core-runtime microbenchmarks vs the reference's release suite.

Workload shapes mirror the reference's ``release/microbenchmark``
definitions (reference: ``python/ray/_private/ray_perf.py:93+``) with
baselines from BASELINE.md:35-48 (``microbenchmark.json``, Ray 2.23.0
release machines). Run:

    python benchmarks/micro_bench.py [--quick]

Prints one JSON line per metric:
    {"metric": ..., "value": N, "unit": ..., "baseline": N, "vs_baseline": N}

NOTE on hardware: the recorded baselines come from multi-core release
machines; "n:n" / "multi client" shapes aggregate callers that run in
parallel there. On a single-core box every caller, actor, and the head
timeshare one CPU, so aggregate-concurrency metrics are CPU-bound at
roughly the single-caller rate (see MICROBENCH_r03.json for the
per-core accounting).
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


BASELINES = {
    # metric -> (baseline value, unit) from BASELINE.md:35-48
    "actor_calls_sync_1_1": (2005.0, "calls/s"),
    "actor_calls_async_1_1": (8766.0, "calls/s"),
    "actor_calls_async_n_n": (27322.0, "calls/s"),
    "actor_calls_n_n_ref_arg": (2672.0, "calls/s"),
    "tasks_sync_single_client": (974.0, "tasks/s"),
    "tasks_async_single_client": (7379.0, "tasks/s"),
    "tasks_async_multi_client": (22255.0, "tasks/s"),
    "get_small_objects": (10501.0, "gets/s"),
    "put_small_objects": (5286.0, "puts/s"),
    "wait_1k_refs": (5.16, "waits/s"),
    "pg_create_remove": (788.1, "pairs/s"),
    "client_overhead_sync": (528.0, "calls/s"),
}


def report(metric: str, value: float):
    base, unit = BASELINES[metric]
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, "baseline": base,
                      "vs_baseline": round(value / base, 3)}), flush=True)


def bench_calibration(scale: int = 1):
    """Fixed CPU-bound calibration rows (VERDICT r5 weak #4): a pure
    single-core busyloop score and a same-host IPC ping-pong RTT rate,
    neither touching the runtime. Recorded in EVERY microbench artifact
    so cross-boot comparisons of runtime rows (``head_vs_reference``)
    become arithmetic — divide by the calibration ratio instead of
    asserting 'the boot was slower'."""
    import socket
    import multiprocessing as mp

    n = 2_000_000 // scale
    x = 0
    t0 = time.perf_counter()
    for i in range(n):
        x += i & 7  # fixed integer work; immune to dict/alloc noise
    busy = n / (time.perf_counter() - t0)
    print(json.dumps({"metric": "calibration_busyloop",
                      "value": round(busy, 1), "unit": "iters/s",
                      "calibration": True}), flush=True)

    a, b = socket.socketpair()

    def _echo(sock):
        while True:
            d = sock.recv(16)
            if not d or d == b"q":
                return
            sock.sendall(d)

    proc = mp.get_context("fork").Process(target=_echo, args=(b,),
                                          daemon=True)
    proc.start()
    b.close()
    for _ in range(50):  # warm the scheduler handoff
        a.sendall(b"p")
        a.recv(16)
    rounds = 2000 // scale
    t0 = time.perf_counter()
    for _ in range(rounds):
        a.sendall(b"p")
        a.recv(16)
    pingpong = rounds / (time.perf_counter() - t0)
    a.sendall(b"q")
    a.close()
    proc.join(timeout=5)
    print(json.dumps({"metric": "calibration_ipc_pingpong",
                      "value": round(pingpong, 1), "unit": "rtt/s",
                      "calibration": True}), flush=True)


def bench_actor_calls(rt, n_async: int, n_sync: int):
    @rt.remote
    class Echo:
        def ping(self, x=None):
            return x

        def ok(self, x=None):
            return b"ok"

    a = Echo.remote()
    rt.get(a.ping.remote())  # warm

    t0 = time.perf_counter()
    for _ in range(n_sync):
        rt.get(a.ping.remote())
    report("actor_calls_sync_1_1", n_sync / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    rt.get([a.ping.remote() for _ in range(n_async)])
    report("actor_calls_async_1_1", n_async / (time.perf_counter() - t0))

    # n:n (reference shape, ray_perf.py "n:n actor calls async"): m=4
    # remote caller tasks, each spraying calls round-robin over a pool
    # of default (ordered) actors; aggregate rate.
    actors = [Echo.remote() for _ in range(4)]
    rt.get([b.ping.remote() for b in actors])

    @rt.remote
    def nn_work(actors, n):
        rt.get([actors[i % len(actors)].ping.remote() for i in range(n)])
        return 0

    per = max(n_async // 2, 100)
    rt.get([nn_work.remote(actors, 50) for _ in range(4)])  # warm callers
    t0 = time.perf_counter()
    rt.get([nn_work.remote(actors, per) for _ in range(4)])
    report("actor_calls_async_n_n", 4 * per / (time.perf_counter() - t0))

    # n:n with a put-ref arg (ray_perf.py "n:n actor calls with arg
    # async": ``Client.small_value_batch_arg`` passes ``ray.put(0)`` as
    # the arg of every call): client actors each drive their own actor,
    # every call carrying an ObjectRef argument the receiver resolves.
    @rt.remote
    class Client:
        def __init__(self, sink):
            self.sink = sink

        def batch(self, n):
            x = rt.put(0)
            rt.get([self.sink.ok.remote(x) for _ in range(n)])
            return 0

    sinks = [Echo.remote() for _ in range(4)]
    clients = [Client.remote(s) for s in sinks]
    rt.get([c.batch.remote(5) for c in clients])  # warm
    per_c = max(n_async // 20, 10)
    t0 = time.perf_counter()
    rt.get([c.batch.remote(per_c) for c in clients])
    report("actor_calls_n_n_ref_arg",
           4 * per_c / (time.perf_counter() - t0))


def bench_tasks(rt, n_async: int, n_sync: int):
    @rt.remote
    def nop(x=None):
        return x

    rt.get(nop.remote())  # warm the lease

    t0 = time.perf_counter()
    for _ in range(n_sync):
        rt.get(nop.remote())
    report("tasks_sync_single_client", n_sync / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    rt.get([nop.remote() for _ in range(n_async)])
    report("tasks_async_single_client",
           n_async / (time.perf_counter() - t0))

    # multi client (ray_perf.py "multi client tasks async"): remote
    # callers that each submit a task batch and get it; aggregate.
    @rt.remote
    def submit_batch(n):
        rt.get([nop.remote() for _ in range(n)])
        return 0

    rt.get([submit_batch.remote(50) for _ in range(4)])  # warm
    per = max(n_async // 2, 100)
    t0 = time.perf_counter()
    rt.get([submit_batch.remote(per) for _ in range(4)])
    report("tasks_async_multi_client", 4 * per / (time.perf_counter() - t0))


def bench_objects(rt, n: int):
    value = b"x" * 1024
    t0 = time.perf_counter()
    refs = [rt.put(value) for _ in range(n)]
    report("put_small_objects", n / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    for r in refs:
        rt.get(r)
    report("get_small_objects", n / (time.perf_counter() - t0))
    del refs
    gc.collect()


def bench_wait(rt, rounds: int):
    """ray.wait over 1k refs, half already completed (the reference
    benchmark shape: scan a large in-flight set repeatedly)."""

    @rt.remote
    def quick(i):
        return i

    @rt.remote
    def slow():
        time.sleep(30)

    refs = [quick.remote(i) for i in range(500)]
    refs += [slow.remote() for _ in range(4)]  # keep some never-ready
    rt.wait(refs, num_returns=500, timeout=30)  # settle

    t0 = time.perf_counter()
    for _ in range(rounds):
        ready, _ = rt.wait(refs, num_returns=len(refs), timeout=0.01)
    report("wait_1k_refs", rounds / (time.perf_counter() - t0))


def bench_pgs(rt, n: int):
    t0 = time.perf_counter()
    for _ in range(n):
        pg = rt.placement_group([{"CPU": 1}])
        pg.ready(timeout=30)
        rt.remove_placement_group(pg)
    report("pg_create_remove", n / (time.perf_counter() - t0))


def bench_client_overhead(n: int):
    """1:1 sync actor calls through the remote TCP client attach
    (reference: ``client__1_1_actor_calls_sync``, Ray Client)."""
    import json as _json
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    session_dir = tempfile.mkdtemp(prefix="rt_bench_client_")
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "4", "--num-tpus", "0",
         "--session-dir", session_dir, "--die-with-parent"],
        cwd=repo, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        path = os.path.join(session_dir, "session.json")
        deadline = time.time() + 30
        info = None
        while time.time() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    info = _json.load(f)
                break
            time.sleep(0.1)
        if not info:
            raise RuntimeError("standalone head never came up")
        host, port = info["tcp_address"]

        code = f"""
import sys, time
sys.path.insert(0, {repo!r})
import ray_tpu as rt
rt.init(address="{host}:{port}")

@rt.remote
class Echo:
    def ping(self):
        return b"ok"

a = Echo.remote()
rt.get(a.ping.remote())
t0 = time.perf_counter()
for _ in range({n}):
    rt.get(a.ping.remote())
print({n} / (time.perf_counter() - t0))
rt.shutdown()
"""
        r = subprocess.run([sys.executable, "-c", code], cwd=repo,
                           capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            raise RuntimeError(f"client driver failed:\n{r.stdout}\n{r.stderr}")
        report("client_overhead_sync", float(r.stdout.strip().split()[-1]))
    finally:
        head.terminate()
        try:
            head.wait(timeout=10)
        except subprocess.TimeoutExpired:
            head.kill()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="10x fewer iterations")
    args = parser.parse_args()
    scale = 10 if args.quick else 1

    import ray_tpu as rt

    # Calibration first, before the runtime exists — pure host numbers.
    bench_calibration(scale)
    rt.init(num_cpus=16, num_tpus=0, ignore_reinit_error=True)
    bench_tasks(rt, n_async=5000 // scale, n_sync=1000 // scale)
    bench_actor_calls(rt, n_async=5000 // scale, n_sync=2000 // scale)
    bench_objects(rt, n=5000 // scale)
    # PGs before wait: bench_wait leaves never-ready sleeper tasks
    # holding CPU leases, which would starve PG bundle reservation.
    bench_pgs(rt, n=100 // scale)
    bench_wait(rt, rounds=50 // scale)
    rt.shutdown()
    bench_client_overhead(n=1000 // scale)


if __name__ == "__main__":
    main()
