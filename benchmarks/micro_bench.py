"""Core-runtime microbenchmarks vs the reference's release suite.

Counterpart of the reference's ``release/microbenchmark`` numbers recorded
in BASELINE.md:35-47 (single-node microbenchmark.json). Run:

    python benchmarks/micro_bench.py [--quick]

Prints one JSON line per metric:
    {"metric": ..., "value": N, "unit": ..., "baseline": N, "vs_baseline": N}
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


BASELINES = {
    # metric -> (baseline value, unit) from BASELINE.md:35-47
    "actor_calls_sync_1_1": (2005.0, "calls/s"),
    "actor_calls_async_1_1": (8766.0, "calls/s"),
    "actor_calls_async_n_n": (27322.0, "calls/s"),
    "tasks_sync_single_client": (974.0, "tasks/s"),
    "tasks_async_single_client": (7379.0, "tasks/s"),
    "get_small_objects": (10501.0, "gets/s"),
    "put_small_objects": (5286.0, "puts/s"),
    "wait_1k_refs": (5.16, "waits/s"),
    "pg_create_remove": (788.1, "pairs/s"),
}


def report(metric: str, value: float):
    base, unit = BASELINES[metric]
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, "baseline": base,
                      "vs_baseline": round(value / base, 3)}), flush=True)


def bench_actor_calls(rt, n_async: int, n_sync: int):
    @rt.remote
    class Echo:
        def ping(self, x=None):
            return x

    a = Echo.remote()
    rt.get(a.ping.remote())  # warm

    t0 = time.perf_counter()
    for _ in range(n_sync):
        rt.get(a.ping.remote())
    report("actor_calls_sync_1_1", n_sync / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    rt.get([a.ping.remote() for _ in range(n_async)])
    report("actor_calls_async_1_1", n_async / (time.perf_counter() - t0))

    actors = [Echo.options(max_concurrency=4).remote() for _ in range(4)]
    rt.get([b.ping.remote() for b in actors])
    t0 = time.perf_counter()
    rt.get([b.ping.remote() for b in actors for _ in range(n_async // 4)])
    report("actor_calls_async_n_n",
           (n_async // 4 * 4) / (time.perf_counter() - t0))


def bench_tasks(rt, n_async: int, n_sync: int):
    @rt.remote
    def nop(x=None):
        return x

    rt.get(nop.remote())  # warm the lease

    t0 = time.perf_counter()
    for _ in range(n_sync):
        rt.get(nop.remote())
    report("tasks_sync_single_client", n_sync / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    rt.get([nop.remote() for _ in range(n_async)])
    report("tasks_async_single_client",
           n_async / (time.perf_counter() - t0))


def bench_objects(rt, n: int):
    value = b"x" * 1024
    t0 = time.perf_counter()
    refs = [rt.put(value) for _ in range(n)]
    report("put_small_objects", n / (time.perf_counter() - t0))

    t0 = time.perf_counter()
    for r in refs:
        rt.get(r)
    report("get_small_objects", n / (time.perf_counter() - t0))
    del refs
    gc.collect()


def bench_wait(rt, rounds: int):
    """ray.wait over 1k refs, half already completed (the reference
    benchmark shape: scan a large in-flight set repeatedly)."""

    @rt.remote
    def quick(i):
        return i

    @rt.remote
    def slow():
        time.sleep(30)

    refs = [quick.remote(i) for i in range(500)]
    refs += [slow.remote() for _ in range(4)]  # keep some never-ready
    rt.wait(refs, num_returns=500, timeout=30)  # settle

    t0 = time.perf_counter()
    for _ in range(rounds):
        ready, _ = rt.wait(refs, num_returns=len(refs), timeout=0.01)
    report("wait_1k_refs", rounds / (time.perf_counter() - t0))


def bench_pgs(rt, n: int):
    t0 = time.perf_counter()
    for _ in range(n):
        pg = rt.placement_group([{"CPU": 1}])
        pg.ready(timeout=30)
        rt.remove_placement_group(pg)
    report("pg_create_remove", n / (time.perf_counter() - t0))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="10x fewer iterations")
    args = parser.parse_args()
    scale = 10 if args.quick else 1

    import ray_tpu as rt

    rt.init(num_cpus=8, num_tpus=0, ignore_reinit_error=True)
    bench_tasks(rt, n_async=5000 // scale, n_sync=1000 // scale)
    bench_actor_calls(rt, n_async=5000 // scale, n_sync=2000 // scale)
    bench_objects(rt, n=5000 // scale)
    # PGs before wait: bench_wait leaves never-ready sleeper tasks
    # holding CPU leases, which would starve PG bundle reservation.
    bench_pgs(rt, n=100 // scale)
    bench_wait(rt, rounds=50 // scale)
    rt.shutdown()


if __name__ == "__main__":
    main()
