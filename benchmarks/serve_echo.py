"""Serve framework-overhead benchmark: echo deployment, zero device
work (reference budget: sub-ms proxy+router+replica overhead per
request, SURVEY.md §3.5 / ``python/ray/serve/benchmarks``).

Isolates what the framework itself costs: HTTP proxy parse →
deployment handle router → replica asyncio call → response encode,
with a no-op replica body. Two paths are measured:

- ``http``: closed-loop clients through the real HTTP/1.1 proxy with
  keep-alive (the full ingress stack).
- ``handle``: DeploymentHandle calls from a driver (router + replica
  transport only — what a composed deployment graph pays per hop).

Run: ``python benchmarks/serve_echo.py [--clients 8] [--secs 8]``;
prints one JSON line per metric.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(lat):
    import numpy as np

    a = np.asarray(lat)
    return (float(np.percentile(a, 50) * 1e3),
            float(np.percentile(a, 99) * 1e3))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--secs", type=float, default=8.0)
    args = parser.parse_args()

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=4, ignore_reinit_error=True)
    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    @serve.deployment(max_ongoing_requests=256)
    class Echo:
        async def __call__(self, request):
            return b"ok"

        async def ping(self, payload):
            return payload

    handle = serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    port = serve.status()["http"]["port"]

    # ---------------------------------------------------------- HTTP path
    import http.client

    host = "127.0.0.1"
    lat_lock = threading.Lock()
    lats: list = []

    def client_loop(stop_at):
        conn = http.client.HTTPConnection(host, int(port))
        mine = []
        while time.time() < stop_at:
            t0 = time.perf_counter()
            conn.request("GET", "/echo")
            resp = conn.getresponse()
            resp.read()
            mine.append(time.perf_counter() - t0)
        with lat_lock:
            lats.extend(mine)
        conn.close()

    # warmup (connection setup, route table, replica import)
    warm = threading.Thread(target=client_loop,
                            args=(time.time() + 1.0,))
    warm.start()
    warm.join()
    lats.clear()

    # measurement window starts NOW, full --secs long
    stop_at = time.time() + args.secs
    threads = [threading.Thread(target=client_loop, args=(stop_at,))
               for _ in range(args.clients)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    p50, p99 = _percentiles(lats)
    print(json.dumps({
        "metric": "serve_echo_http_p50_ms", "value": round(p50, 3),
        "p99_ms": round(p99, 3), "unit": "ms", "clients": args.clients,
        "throughput_rps": round(len(lats) / wall, 1)}))

    # -------------------------------------------------------- handle path
    # sequential closed loop: per-hop latency of a composed graph
    ping = handle.options(method_name="ping")
    for _ in range(200):  # warmup
        ping.remote(b"x").result()
    hl = []
    end = time.time() + args.secs / 2
    while time.time() < end:
        t0 = time.perf_counter()
        ping.remote(b"x").result()
        hl.append(time.perf_counter() - t0)
    p50h, p99h = _percentiles(hl)
    print(json.dumps({
        "metric": "serve_echo_handle_p50_ms", "value": round(p50h, 3),
        "p99_ms": round(p99h, 3), "unit": "ms", "calls": len(hl)}))

    serve.shutdown()
    rt.shutdown()


if __name__ == "__main__":
    main()
