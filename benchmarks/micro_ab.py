"""Pinned microbenchmark protocol: interleaved A/B vs a recorded
baseline commit, median-of-N with spread.

Single-run numbers on shared/VM hosts are not durable — boot-to-boot
throughput varies (MICROBENCH_r03.json's end-of-round re-measurement
moved a row from 1.15x to 0.65x on host variance alone). This driver
makes claims reproducible:

- checks out the ROUND-START commit into a scratch git worktree,
- alternates HEAD run, baseline run, HEAD, baseline … (N each), so
  slow host phases hit both sides equally,
- reports per-metric MEDIAN and spread (min-max) for both sides plus
  the median-vs-median ratio — a regression claim requires the ratio,
  not one lucky run.

Run: ``python benchmarks/micro_ab.py --base <commit> [--runs 5]
[--quick] [--out MICROBENCH_r04.json]``
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_side(tree: str, quick: bool) -> dict:
    """One micro_bench run under ``tree``; returns metric -> value."""
    for seg in glob.glob("/dev/shm/rt_*"):
        try:
            os.unlink(seg)
        except OSError:
            pass
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RT_")}
    env["PYTHONPATH"] = tree
    cmd = [sys.executable, os.path.join(tree, "benchmarks",
                                        "micro_bench.py")]
    if quick:
        cmd.append("--quick")
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          cwd=tree, timeout=1800, env=env)
    out = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
                out[rec["metric"]] = rec["value"]
            except (ValueError, KeyError):
                pass
    if not out:
        raise RuntimeError(
            f"no metrics from {tree}: {proc.stderr[-1500:]}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--base", required=True,
                    help="round-start commit for the B side")
    ap.add_argument("--runs", type=int, default=5)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--out", default="MICROBENCH_AB.json")
    args = ap.parse_args()

    base_tree = tempfile.mkdtemp(prefix="rt_ab_base_")
    subprocess.run(["git", "worktree", "add", "--detach", base_tree,
                    args.base], cwd=REPO, check=True,
                   capture_output=True)
    a_runs, b_runs = [], []
    try:
        for i in range(args.runs):
            print(f"run {i + 1}/{args.runs}: HEAD…", file=sys.stderr)
            a_runs.append(run_side(REPO, args.quick))
            print(f"run {i + 1}/{args.runs}: base…", file=sys.stderr)
            b_runs.append(run_side(base_tree, args.quick))
    finally:
        subprocess.run(["git", "worktree", "remove", "--force",
                        base_tree], cwd=REPO, capture_output=True)

    metrics = sorted(set().union(*a_runs, *b_runs))
    rows = []
    for m in metrics:
        a = sorted(r[m] for r in a_runs if m in r)
        b = sorted(r[m] for r in b_runs if m in r)
        if not a or not b:
            continue
        med_a, med_b = statistics.median(a), statistics.median(b)
        rows.append({
            "metric": m,
            "head_median": round(med_a, 2),
            "head_spread": [round(a[0], 2), round(a[-1], 2)],
            "base_median": round(med_b, 2),
            "base_spread": [round(b[0], 2), round(b[-1], 2)],
            "head_vs_base": round(med_a / med_b, 3) if med_b else None,
        })
        print(json.dumps(rows[-1]))
    doc = {
        "protocol": (f"interleaved A/B x {args.runs} runs; medians + "
                     "min-max spread; HEAD vs "
                     f"{args.base}"),
        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
        "quick": args.quick,
        "rows": rows,
    }
    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
