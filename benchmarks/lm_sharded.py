"""FSDP/ZeRO-style sharded LM training benchmark (BASELINE.md:63 —
"FSDP/ZeRO-style sharded 1B LM"; north star ≥40% MFU on v5e-16).

Builds an ``{fsdp: N}`` mesh over every visible device and measures
training throughput + MFU. Model size scales with the device count:
the 1b preset needs its optimizer state sharded across ≥8 chips
(adamw f32 master+moments ≈ 17 GB), so a single chip runs the medium
(GPT-2-medium, 350M) preset instead — same code path, same sharding
rules, smaller shapes.

Run: ``python benchmarks/lm_sharded.py [--config 1b] [--batch N]``
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    return 0.0


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default=None,
                        help="gpt preset (default: by device count)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--seq", type=int, default=None)
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()

    import jax
    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    devs = jax.devices()
    n = len(devs)
    on_tpu = devs[0].platform == "tpu"
    if args.config:
        name = args.config
    elif not on_tpu:
        name = "nano"
    elif n >= 8:
        name = "1b"
    else:
        name = "medium"
    cfg = dataclasses.replace(gpt.CONFIGS[name], remat="dots",
                              attn_backend="auto")
    batch = args.batch or (8 if name in ("medium", "1b") else 4) * n
    seq = min(args.seq or cfg.max_seq, cfg.max_seq)

    mesh = create_mesh({"fsdp": n}, devices=devs)
    init, step, state_sh, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1), np.int32),
        batch_sh)
    data = {"tokens": tokens}

    for _ in range(3):
        state, metrics = step(state, data)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, metrics = step(state, data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * args.iters / dt
    flops_per_token = (6 * cfg.num_params()
                       + 12 * cfg.n_layer * seq * cfg.d_model)
    peak = _peak_flops(devs[0]) * n
    mfu = tokens_per_sec * flops_per_token / peak if peak else 0.0
    print(json.dumps({
        "metric": f"gpt_{name}_fsdp{n}_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "params": cfg.num_params(),
        "batch": batch, "seq": seq,
        "mfu": round(mfu, 4),
        "vs_baseline": round(mfu / 0.40, 4) if peak else None,
    }))


if __name__ == "__main__":
    main()
