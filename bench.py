"""Headline benchmark: GPT-2-small training throughput on one chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

``vs_baseline`` is measured MFU / 0.40 — the north-star target from
``BASELINE.json`` (≥40% MFU on v5e). >1.0 beats the target.
"""
from __future__ import annotations

import json
import time


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12   # bf16 peak per v5e chip
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v3" in kind:
        return 123e12
    if "v2" in kind:
        return 45e12
    return 0.0          # unknown (CPU run) → MFU not computable


def main() -> None:
    import jax
    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    import dataclasses

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    # Tuned on v5e: batch 32 saturates HBM headroom with selective remat
    # + the Pallas flash kernel (block 512); larger batches OOM on the
    # f32 loss logits.
    cfg = gpt.CONFIGS["small"] if on_tpu else gpt.CONFIGS["nano"]
    cfg = dataclasses.replace(cfg, remat="dots", attn_backend="auto")
    batch, seq = (32, 1024) if on_tpu else (8, 64)
    seq = min(seq, cfg.max_seq)  # loss uses tokens[:, :-1], so seq==max_seq ok

    mesh = create_mesh({"dp": 1}, devices=[dev])
    init, step, state_sh, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1), np.int32),
        batch_sh)
    data = {"tokens": tokens}

    # Warmup/compile. Sync via a host fetch of the loss — on some PJRT
    # transports block_until_ready returns at dispatch, not completion.
    for _ in range(3):
        state, metrics = step(state, data)
    float(metrics["loss"])

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    toks_per_step = batch * seq
    tokens_per_sec = toks_per_step * iters / dt
    # 6N matmul + 12*L*S*d attention flops per token (fwd+bwd).
    flops_per_token = (6 * cfg.num_params()
                       + 12 * cfg.n_layer * seq * cfg.d_model)
    achieved = tokens_per_sec * flops_per_token
    peak = _peak_flops(dev)
    mfu = achieved / peak if peak else 0.0

    print(json.dumps({
        "metric": "gpt2s_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


if __name__ == "__main__":
    main()
