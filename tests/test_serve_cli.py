"""Serve YAML config schema + CLI (reference: ``serve/schema.py`` +
``serve/scripts.py`` serve deploy/run/config/status): import-path app
loading, per-deployment overrides, config echo, CLI subprocess."""
import json
import os
import subprocess
import sys
import textwrap
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve import schema

APP_MODULE = textwrap.dedent('''
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, max_ongoing_requests=4)
    class Greeter:
        def __call__(self, req):
            return "hello from config"

    app = Greeter.bind()

    def build_app():
        return Greeter.bind()
''')


@pytest.fixture
def app_on_path(tmp_path, monkeypatch):
    (tmp_path / "cfg_demo_app.py").write_text(APP_MODULE)
    monkeypatch.syspath_prepend(str(tmp_path))
    yield "cfg_demo_app"


def test_schema_validation():
    with pytest.raises(ValueError, match="no applications"):
        schema.ServeDeploySchema.from_dict({"applications": []})
    with pytest.raises(ValueError, match="import_path"):
        schema.ServeDeploySchema.from_dict(
            {"applications": [{"name": "x"}]})
    with pytest.raises(ValueError, match="duplicate"):
        schema.ServeDeploySchema.from_dict({"applications": [
            {"name": "a", "import_path": "m:app"},
            {"name": "a", "import_path": "m:app"}]})
    with pytest.raises(ValueError, match="unknown deployment config"):
        schema.DeploymentSchema.from_dict({"name": "d", "replicas": 2})


def test_import_application(app_on_path):
    app = schema.import_application(f"{app_on_path}:app")
    assert app.deployment.name == "Greeter"
    # builder-function form and dotted form both resolve
    app2 = schema.import_application(f"{app_on_path}:build_app")
    assert app2.deployment.name == "Greeter"
    app3 = schema.import_application(f"{app_on_path}.app")
    assert app3.deployment.name == "Greeter"
    with pytest.raises(TypeError, match="not a serve Application"):
        schema.import_application("json:dumps")


def test_deploy_config_with_overrides(rt_cluster, app_on_path):
    cfg = {
        "http_options": {"host": "127.0.0.1", "port": 0},
        "applications": [{
            "name": "greetapp",
            "route_prefix": "/greet",
            "import_path": f"{app_on_path}:app",
            "deployments": [{
                "name": "Greeter",
                "num_replicas": 2,
                "max_ongoing_requests": 9,
            }],
        }],
    }
    try:
        names = schema.deploy_config(cfg)
        assert names == ["greetapp"]
        st = serve.status()
        dep = st["applications"]["greetapp"]["deployments"]["Greeter"]
        assert dep["target"] == 2  # override beat the decorator
        # config echo round-trips through the cluster KV
        assert schema.get_last_config() == cfg
        # and the app actually serves
        h = serve.get_app_handle("greetapp")
        assert h.remote(None).result(timeout=30) == "hello from config"
        # override of an unknown deployment fails loudly
        bad = json.loads(json.dumps(cfg))
        bad["applications"][0]["deployments"][0]["name"] = "Ghost"
        with pytest.raises(ValueError, match="unknown deployments"):
            schema.deploy_config(bad)
    finally:
        serve.shutdown()


def test_serve_cli_subprocess(rt_cluster, app_on_path, tmp_path):
    from ray_tpu.core.worker import CoreWorker

    session_dir = CoreWorker.current().session_dir
    cfg_file = tmp_path / "serve_config.yaml"
    cfg_file.write_text(textwrap.dedent(f'''
        http_options:
          host: 127.0.0.1
          port: 0
        applications:
          - name: cliapp
            route_prefix: /cli
            import_path: {app_on_path}:app
    '''))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=":".join(
        [repo, str(tmp_path)]
        + ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
           else [])))

    def cli(*argv):
        return subprocess.run(
            [sys.executable, "-m", "ray_tpu", "--session-dir",
             session_dir, "serve", *argv],
            capture_output=True, text=True, env=env, timeout=120)

    try:
        out = cli("deploy", str(cfg_file))
        assert out.returncode == 0, out.stderr
        assert "cliapp" in out.stdout

        out = cli("status")
        assert out.returncode == 0, out.stderr
        assert "cliapp" in out.stdout

        out = cli("config")
        assert out.returncode == 0, out.stderr
        assert "import_path" in out.stdout and "cliapp" in out.stdout

        # The deployed app answers over HTTP on the configured route.
        http = rt.get(rt.get_actor("SERVE_PROXY").get_port.remote(),
                      timeout=10)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http}/cli", timeout=30) as resp:
            assert resp.read() == b"hello from config"

        # Redeploy from a FRESH process (get-or-create proxy, no
        # duplicate-name crash) with a config listing a different app:
        # declarative semantics remove the old one.
        cfg2 = tmp_path / "serve_config2.yaml"
        cfg2.write_text(textwrap.dedent(f'''
            applications:
              - name: cliapp2
                route_prefix: /cli2
                import_path: {app_on_path}:app
        '''))
        out = cli("deploy", str(cfg2))
        assert out.returncode == 0, out.stderr
        out = cli("status")
        assert "cliapp2" in out.stdout and '"cliapp"' not in out.stdout

        # Cross-process shutdown kills the named proxy actor too.
        out = cli("shutdown")
        assert out.returncode == 0, out.stderr
        with pytest.raises(Exception):
            rt.get_actor("SERVE_PROXY", timeout=2)
    finally:
        cli("shutdown")
