"""RLlib depth: CNN module, DQN, APPO, BC, replay buffers, connectors,
and the solved-CartPole gate.

Mirrors the reference's per-algorithm smoke + learning tests
(``rllib/tuned_examples/``): learning curves must move, numerics must
match across the numpy/jax dual paths, and the IMPALA/APPO async stack
must run end-to-end with aggregation workers on image observations.
"""
import numpy as np
import pytest

from ray_tpu import rllib
from ray_tpu.rllib.connectors import (ConnectorPipeline, FlattenObs,
                                      FrameStack, NormalizeObs)
from ray_tpu.rllib.replay_buffer import (PrioritizedReplayBuffer,
                                         ReplayBuffer)


# ------------------------------------------------------------- units
def test_replay_buffer_uniform():
    buf = ReplayBuffer(capacity=100, seed=0)
    buf.add({"obs": np.arange(150, dtype=np.float32),
             "actions": np.arange(150) % 3})
    assert len(buf) == 100  # ring wrapped
    s = buf.sample(32)
    assert len(s["obs"]) == 32
    assert s["obs"].min() >= 50  # first 50 were overwritten


def test_replay_buffer_prioritized():
    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, beta=1.0, seed=0)
    idx = buf.add({"obs": np.arange(64, dtype=np.float32)})
    # Slot 7 gets overwhelming priority → dominates samples.
    prios = np.full(64, 1e-3)
    prios[7] = 1e3
    buf.update_priorities(idx, prios)
    s = buf.sample(256)
    assert (s["obs"] == 7).mean() > 0.9
    assert s["weights"].min() > 0  # importance weights present


def test_connector_pipeline():
    pipe = ConnectorPipeline([
        NormalizeObs(scale=1 / 255.0), FrameStack(k=4)])
    obs = np.full((2, 8, 8, 1), 255, np.uint8)
    out = pipe(obs)
    assert out.shape == (2, 8, 8, 4)
    np.testing.assert_allclose(out, 1.0)
    assert pipe.out_shape((8, 8, 1)) == (8, 8, 4)
    flat = ConnectorPipeline([FlattenObs()])
    assert flat.out_shape((4, 2)) == (8,)


def test_conv_forward_numpy_jax_parity():
    import jax.numpy as jnp

    from ray_tpu.rllib.conv_module import conv_forward
    from ray_tpu.rllib.rl_module import RLModuleSpec

    spec = RLModuleSpec(obs_dim=84 * 84 * 4, num_actions=6,
                        hidden=(128,), obs_shape=(84, 84, 4), conv=True)
    module = spec.build(seed=3)
    obs = np.random.default_rng(0).random((2, 84, 84, 4),
                                          dtype=np.float32)
    logits_np, value_np = conv_forward(module.params, obs, np)
    logits_j, value_j = conv_forward(module.params, jnp.asarray(obs), jnp)
    np.testing.assert_allclose(np.asarray(logits_j), logits_np,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(value_j), value_np,
                               rtol=1e-4, atol=1e-4)


# ------------------------------------------------------- algorithms
def test_dqn_learns_cartpole(rt_cluster):
    config = (rllib.DQNConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                           rollout_fragment_length=32)
              .training(lr=1e-3, train_batch_size=64,
                        num_steps_sampled_before_learning=500,
                        target_update_freq=100, updates_per_iteration=96,
                        epsilon_decay_steps=1500, hidden=(64, 64))
              .debugging(seed=0))
    algo = config.build()
    try:
        best = 0.0
        for _ in range(90):
            m = algo.train()
            best = max(best, m.get("episode_return_mean", 0.0))
            if best >= 100:
                break
        assert best >= 100, f"DQN failed to learn: best={best}"
    finally:
        algo.stop()


def test_bc_clones_expert():
    rng = np.random.default_rng(0)
    obs = rng.standard_normal((2000, 4)).astype(np.float32)
    actions = (obs[:, 0] + obs[:, 2] > 0).astype(np.int64)  # expert rule
    config = (rllib.BCConfig()
              .offline({"obs": obs, "actions": actions},
                       obs_dim=4, num_actions=2)
              .training(lr=1e-3, minibatch_size=128, num_epochs=5))
    algo = rllib.BC(config)
    for _ in range(4):
        m = algo.train()
    acc = (algo.compute_actions(obs) == actions).mean()
    assert acc > 0.95, f"BC accuracy {acc}, loss {m['bc_loss']}"


def test_appo_smoke(rt_cluster):
    config = (rllib.APPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=64)
              .training(train_batch_size=256, minibatch_size=128,
                        num_epochs=2, lr=5e-4)
              .debugging(seed=0))
    config.num_aggregation_workers = 1
    algo = config.build()
    try:
        for _ in range(3):
            m = algo.train()
        assert np.isfinite(m["total_loss"])
        assert m["num_env_steps_trained"] > 0
    finally:
        algo.stop()


def test_impala_cnn_aggregator_smoke(rt_cluster):
    """The BASELINE IMPALA-Pong shape without Atari ROMs: a synthetic
    84x84 image env through FrameStack connectors, Nature-CNN module,
    async IMPALA with an aggregation worker."""
    def env_creator():
        import gymnasium as gym
        import numpy as np  # local: the creator ships via cloudpickle

        class TinyImageEnv(gym.Env):
            observation_space = gym.spaces.Box(0, 255, (84, 84, 1),
                                               np.uint8)
            action_space = gym.spaces.Discrete(4)

            def reset(self, seed=None, options=None):
                self._t = 0
                return self.observation_space.sample(), {}

            def step(self, action):
                self._t += 1
                obs = self.observation_space.sample()
                return obs, float(action == 1), self._t >= 20, False, {}

        return TinyImageEnv()

    config = (rllib.IMPALAConfig()
              .environment(env_creator=env_creator)
              .env_runners(
                  num_env_runners=1, num_envs_per_env_runner=1,
                  rollout_fragment_length=16,
                  env_to_module_connector=lambda: ConnectorPipeline(
                      [NormalizeObs(scale=1 / 255.0), FrameStack(k=2)]))
              .rl_module(use_conv=True, hidden=(64,))
              .training(train_batch_size=16, minibatch_size=16, lr=1e-4)
              .debugging(seed=0))
    config.num_aggregation_workers = 1
    algo = config.build()
    try:
        m = algo.train()
        assert np.isfinite(m["total_loss"])
    finally:
        algo.stop()


def test_ppo_solves_cartpole(rt_cluster):
    """The reference tuned-example gate (cartpole_ppo.py: return ≥ 450)."""
    config = (rllib.PPOConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                           rollout_fragment_length=128)
              .training(train_batch_size=2048, minibatch_size=256,
                        num_epochs=10, lr=3e-4, entropy_coeff=0.01,
                        hidden=(64, 64))
              .debugging(seed=1))
    algo = config.build()
    try:
        best = 0.0
        for i in range(60):
            m = algo.train()
            best = max(best, m.get("episode_return_mean", 0.0))
            if best >= 450:
                break
        assert best >= 450, f"CartPole not solved: best={best}"
    finally:
        algo.stop()
