"""rtsan (tools/rtsan): runtime enforcement of rtlint's concurrency
contracts (ISSUE 13).

Scenario tests run in SUBPROCESSES: the sanitizer patches
process-global state (``threading.Lock`` et al), and the session's own
sanitizer — enabled by conftest for this module — must never see the
deliberately broken locks these tests construct (its gate would fail
the suite on them). Each scenario script enables its own sanitizer,
exercises one check, and prints its findings as JSON.

In-process tests cover the shared-annotation-loader identity pin (ONE
parse for rtlint and rtsan), the RT108 static half of the contract, and
the ``engine.stats()`` sanitizer block.
"""
import json
import os
import shutil
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = (
    "import json, os, sys, threading, time\n"
    f"sys.path.insert(0, {REPO!r})\n"
    "import tools.rtsan as rtsan\n"
)

_EPILOGUE = (
    "\nprint('FINDINGS=' + json.dumps("
    "[f.to_dict() for f in rtsan.findings()]))\n"
)


def _run_scenario(tmp_path, body, name="scenario.py", extra_env=None,
                  timeout=120):
    p = tmp_path / name
    p.write_text(_PRELUDE + textwrap.dedent(body) + _EPILOGUE)
    env = {**os.environ, "RT_SAN_ROOTS": str(tmp_path), "RT_SAN": "0"}
    # Never let a scenario's atexit artifact land in the session's
    # merge dir — its deliberate findings would fail the real gate.
    env.pop("RT_SAN_DIR", None)
    return subprocess.run([sys.executable, str(p)], env=env, cwd=REPO,
                          capture_output=True, text=True,
                          timeout=timeout)


def _findings(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("FINDINGS="):
            return json.loads(line[len("FINDINGS="):])
    raise AssertionError(
        f"no FINDINGS line:\n{proc.stdout}\n{proc.stderr}")


# --------------------------------------------------------------- scenarios
def test_abba_cycle_detected_without_hang(tmp_path):
    """The acceptance scenario: a synthetic ABBA lock order is flagged
    as RS101 — with both stacks — even though the two orders run
    SEQUENTIALLY (the deadlock never fires) and the process exits
    promptly (the subprocess timeout is the no-hang assertion)."""
    proc = _run_scenario(tmp_path, """
        rtsan.enable(modules=(), active=True, wrap_dispatch=False)
        A = threading.Lock()
        B = threading.Lock()
        def ab():
            with A:
                with B:
                    pass
        def ba():
            with B:
                with A:
                    pass
        t = threading.Thread(target=ab); t.start(); t.join()
        t = threading.Thread(target=ba); t.start(); t.join()
    """, timeout=60)
    assert proc.returncode == 0, proc.stderr
    found = _findings(proc)
    cycles = [f for f in found if f["rule"] == "RS101"]
    assert len(cycles) == 1, found
    msg = cycles[0]["message"]
    assert "lock-order cycle" in msg
    # Both acquisition stacks ride the finding.
    assert msg.count("scenario.py") >= 2
    assert "Opposite-order stack" in msg


def test_holds_violation_raises_and_dangling_is_hard_error(tmp_path):
    proc = _run_scenario(tmp_path, """
        class Box:
            def __init__(self):
                self._lock = threading.Lock()
            def locked_op(self):  # rtlint: holds=_lock
                return 1
            def dangling(self):  # rtlint: holds=_missing
                return 2
        rtsan.enable(modules=("__main__",), active=True,
                     wrap_dispatch=False)
        b = Box()
        try:
            b.locked_op()
            print("VERDICT=missed")
        except rtsan.RTSanViolation as e:
            assert "RS102" in str(e) and "_lock" in str(e)
            print("VERDICT=raised")
        with b._lock:
            assert b.locked_op() == 1   # held: clean
        try:
            b.dangling()
            print("DANGLING=missed")
        except rtsan.RTSanViolation as e:
            assert "does not exist" in str(e)
            print("DANGLING=hard-error")
    """)
    assert proc.returncode == 0, proc.stderr
    assert "VERDICT=raised" in proc.stdout
    assert "DANGLING=hard-error" in proc.stdout
    rules = {f["rule"] for f in _findings(proc)}
    assert rules == {"RS102"}


def test_owner_violation_raises_from_foreign_thread(tmp_path):
    """entry=driver binds the calling thread; a foreign thread hitting
    an owner=driver method raises RS103 while the driver lives, and
    ownership rebinds once the driver is dead (the engine's documented
    ownership-transfer rule)."""
    proc = _run_scenario(tmp_path, """
        class Eng:
            # rtlint: owner=driver entry=driver
            def run_entry(self):
                return 1
            # rtlint: owner=driver
            def step(self):
                return 2
        rtsan.enable(modules=("__main__",), active=True,
                     wrap_dispatch=False)
        e = Eng()
        park, bound = threading.Event(), threading.Event()
        def driver():
            e.run_entry(); e.step(); bound.set(); park.wait()
        t = threading.Thread(target=driver); t.start(); bound.wait()
        try:
            e.step()
            print("VERDICT=missed")
        except rtsan.RTSanViolation as ex:
            assert "RS103" in str(ex)
            print("VERDICT=raised")
        park.set(); t.join()
        assert e.step() == 2           # owner dead -> rebind
        print("REBIND=ok")
    """)
    assert proc.returncode == 0, proc.stderr
    assert "VERDICT=raised" in proc.stdout
    assert "REBIND=ok" in proc.stdout
    assert {f["rule"] for f in _findings(proc)} == {"RS103"}


def test_leaked_thread_detected(tmp_path):
    proc = _run_scenario(tmp_path, """
        rtsan.enable(modules=(), active=True, wrap_dispatch=False)
        ev = threading.Event()
        with rtsan.thread_watch(targets=("scenario.py",)):
            t = threading.Thread(target=ev.wait, daemon=True)
            t.start()
        ev.set()
    """)
    assert proc.returncode == 0, proc.stderr
    leaks = [f for f in _findings(proc) if f["rule"] == "RS105"]
    assert len(leaks) == 1
    assert "still alive at watch teardown" in leaks[0]["message"]


def test_disabled_mode_is_a_noop(tmp_path):
    """disable() restores every patched identity — threading factories,
    time.sleep, Thread.start — so production processes pay zero."""
    proc = _run_scenario(tmp_path, """
        orig = (threading.Lock, threading.RLock, threading.Condition,
                time.sleep, threading.Thread.start)
        rtsan.enable(modules=(), active=True, wrap_dispatch=False)
        assert threading.Lock is not orig[0]
        assert time.sleep is not orig[3]
        lk = threading.Lock()
        assert isinstance(lk, rtsan.SanLock)
        rtsan.disable()
        now = (threading.Lock, threading.RLock, threading.Condition,
               time.sleep, threading.Thread.start)
        assert now == orig, (now, orig)
        assert type(threading.Lock()) is type(orig[0]())
        print("IDENTITY=restored")
    """)
    assert proc.returncode == 0, proc.stderr
    assert "IDENTITY=restored" in proc.stdout
    assert _findings(proc) == []


def test_inline_suppression_honored(tmp_path):
    """``# rtsan: disable=RS101 <why>`` at the reported line silences
    the finding (same placement grammar as rtlint suppressions)."""
    proc = _run_scenario(tmp_path, """
        rtsan.enable(modules=(), active=True, wrap_dispatch=False)
        A = threading.Lock()
        B = threading.Lock()
        with A:
            with B:
                pass
        with B:
            with A:  # rtsan: disable=RS101 test-only deliberate ABBA
                pass
        print("SUPPRESSED=" + str(len(rtsan.SANITIZER.suppressed)))
    """)
    assert proc.returncode == 0, proc.stderr
    assert _findings(proc) == []
    assert "SUPPRESSED=1" in proc.stdout


def test_report_cli_renders_graph_and_hold_table(tmp_path):
    """``python -m tools.rtsan --report <artifact>`` prints the
    accumulated lock-order graph and per-site hold-time table; exit 1
    flags new-vs-baseline findings (the rtlint --check contract)."""
    proc = _run_scenario(tmp_path, f"""
        rtsan.enable(modules=(), active=True, wrap_dispatch=False)
        A = threading.Lock()
        B = threading.Lock()
        def ab():
            with A:
                with B:
                    time.sleep(0.002)
        def ba():
            with B:
                with A:
                    pass
        t = threading.Thread(target=ab); t.start(); t.join()
        t = threading.Thread(target=ba); t.start(); t.join()
        rtsan.dump({str(tmp_path / "artifact.json")!r})
    """)
    assert proc.returncode == 0, proc.stderr
    rep = subprocess.run(
        [sys.executable, "-m", "tools.rtsan", "--report",
         str(tmp_path / "artifact.json")],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert rep.returncode == 1, rep.stdout + rep.stderr  # new findings
    assert "lock-order graph" in rep.stdout
    assert "->" in rep.stdout
    assert "hold times" in rep.stdout
    assert "max=" in rep.stdout and "mean=" in rep.stdout
    assert "RS101" in rep.stdout


def test_gate_fails_suite_on_new_finding(tmp_path):
    """THE tier-1 hook, end to end: a pytest session (running this
    repo's conftest under RT_SAN=1) whose tests produce a new rtsan
    finding exits 1 even though every TEST passed — the sessionfinish
    gate flips the exit status, exactly like a new rtlint finding."""
    shutil.copy(os.path.join(REPO, "tests", "conftest.py"),
                tmp_path / "conftest.py")
    (tmp_path / "test_gate_canary.py").write_text(textwrap.dedent("""
        import threading

        def test_abba_but_green():
            A = threading.Lock()
            B = threading.Lock()
            def ab():
                with A:
                    with B:
                        pass
            def ba():
                with B:
                    with A:
                        pass
            t = threading.Thread(target=ab); t.start(); t.join()
            t = threading.Thread(target=ba); t.start(); t.join()
    """))
    env = {**os.environ,
           "RT_SAN": "1",
           "RT_SAN_ROOTS": str(tmp_path),
           "RT_SAN_DIR": str(tmp_path / "artifacts"),
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", str(tmp_path), "-q",
         "-p", "no:cacheprovider"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert "1 passed" in proc.stdout, proc.stdout + proc.stderr
    assert proc.returncode == 1, (proc.returncode, proc.stdout)
    assert "RS101" in proc.stdout
    assert "rtsan: NEW runtime findings" in proc.stdout


# ---------------------------------------------------------------- in-process
def test_shared_annotation_loader_identity():
    """The acceptance pin: rtlint and rtsan consume the IDENTICAL
    annotation parse — one loader module, imported (not copied) by
    both, so a grammar change can never make the static and dynamic
    checks disagree about what a contract says."""
    from tools.rtlint import annotations as ann
    from tools.rtlint import core as lint_core
    from tools.rtsan import core as san_core

    assert san_core.load_annotations is ann.load_annotations
    assert san_core.parse_directives is ann.parse_directives
    assert lint_core.parse_directives is ann.parse_directives
    assert lint_core.func_directives is ann.func_directives

    # Behavioral agreement on a real contract comment: the Module path
    # (rtlint rules) and the loader path (rtsan instrumentation) see
    # the same owner/holds/entry facts.
    src = ("class C:\n"
           "    # rtlint: owner=driver entry=driver holds=_lock\n"
           "    def f(self):\n"
           "        pass\n")
    mod = lint_core.Module("x.py", "x.py", src)
    import ast

    fdef = mod.tree.body[0].body[0]
    d = mod.func_directives(fdef)
    loaded = ann.load_annotations(src)
    assert len(loaded) == 1
    fa = loaded[0]
    assert (d["owner"], d["entry"], d["holds"]) == ("driver", "driver",
                                                    "_lock")
    assert (fa.owner, fa.entry, fa.holds) == ("driver", "driver",
                                              ("_lock",))
    assert isinstance(fdef, ast.FunctionDef)


def test_rt108_fires_on_dangling_holds(tmp_path):
    """Acceptance: the static half of the same contract — a holds=
    naming a lock no method assigns is an RT108 finding."""
    from tools.rtlint import run_paths

    p = tmp_path / "mod.py"
    p.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def ok(self):  # rtlint: holds=_lock\n"
        "        pass\n"
        "    def bad(self):  # rtlint: holds=_gone\n"
        "        pass\n")
    report = run_paths([str(p)])
    assert [(f.rule, f.line) for f in report.findings] == [("RT108", 7)]
    assert "_gone" in report.findings[0].message


@pytest.mark.skipif(os.environ.get("RT_SAN") == "0",
                    reason="sanitizer disabled for this run")
def test_engine_stats_sanitizer_block(rt_cluster):
    """engine.stats() carries a ``sanitizer`` block while rtsan is
    active (this module is on the conftest opt-in list): process
    findings count — zero on a healthy engine — and max hold time per
    named serve lock, so chaos benchmarks can assert cleanliness."""
    import tools.rtsan as rtsan

    assert rtsan.is_active()
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.serve.engine import DecodeEngine

    cfg = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    eng = DecodeEngine(params, cfg, slots=2, chunk=4, max_len=64,
                       prompt_buckets=(8,))
    try:
        prompt = np.arange(5, dtype=np.int32) % cfg.vocab_size
        out = np.concatenate(list(eng.stream(prompt, 6)))
        assert out.shape == (6,)
        st = eng.stats()
        assert "sanitizer" in st, sorted(st)
        san = st["sanitizer"]
        assert san["findings"] == 0
        # The admission lock was named via its holds= contract and
        # held during construction/submit: it must show a hold time.
        assert any("_admit_lock" in k or "engine.py" in k
                   for k in san["max_hold_s"]), san
        assert all(v >= 0 for v in san["max_hold_s"].values())
    finally:
        eng.shutdown()


def test_annotation_coverage_summary(tmp_path, capsys):
    """ISSUE 15 satellite: the sanitizer reports how much of the driver
    surface carries the owner=/holds= contracts it shares with rtlint
    (RT108/RT110) — the summary rides the run artifact and the
    --report CLI, so the two enforcement layers visibly audit ONE
    contract set."""
    import json

    import tools.rtsan as rtsan

    cov = rtsan.annotation_coverage()
    tot = cov["totals"]
    eng = cov["modules"]["ray_tpu.serve.engine"]
    # The engine is a driver-owned class with real annotations...
    assert eng["methods"] > 0 and 0 < eng["annotated"] <= eng["methods"]
    # ...and its _admit_lock is named by the _build_pool holds=.
    assert eng["locks"] >= 1 and eng["locks_with_holds"] >= 1
    assert 0.0 < tot["method_fraction"] <= 1.0
    assert 0.0 < tot["lock_fraction"] <= 1.0

    # The snapshot (and therefore every dumped artifact) carries it.
    snap = rtsan.snapshot()
    assert snap["coverage"]["totals"] == tot

    # And the report CLI renders the section from a dumped artifact.
    art = tmp_path / "rtsan-test.json"
    art.write_text(json.dumps(snap, default=str))
    from tools.rtsan.__main__ import main as rtsan_main

    rc = rtsan_main([str(art)])
    out = capsys.readouterr().out
    assert "annotation coverage" in out
    assert "ray_tpu.serve.engine" in out
    assert f"{tot['annotated']}/{tot['methods']}" in out
    assert rc in (0, 1)
