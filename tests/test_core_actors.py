"""Actors: creation, ordering, concurrency, restarts, named actors
(reference: python/ray/tests/test_actor*.py)."""
import time

import pytest


def test_actor_basic(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Counter:
        def __init__(self, v=0):
            self.v = v

        def inc(self, n=1):
            self.v += n
            return self.v

    c = Counter.remote(5)
    assert rt.get(c.inc.remote()) == 6
    assert rt.get(c.inc.remote(4)) == 10


def test_actor_ordering(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Log:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)

        def read(self):
            return self.items

    log = Log.remote()
    for i in range(20):
        log.append.remote(i)
    assert rt.get(log.read.remote()) == list(range(20))


def test_actor_state_isolation(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Holder:
        def __init__(self):
            self.v = 0

        def bump(self):
            self.v += 1
            return self.v

    a, b = Holder.remote(), Holder.remote()
    assert rt.get(a.bump.remote()) == 1
    assert rt.get(a.bump.remote()) == 2
    assert rt.get(b.bump.remote()) == 1


def test_async_actor_concurrency(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class A:
        async def go(self):
            import asyncio

            await asyncio.sleep(0.2)
            return 1

    a = A.options(max_concurrency=10).remote()
    rt.get(a.go.remote())  # warm: actor worker spawn + first call
    t0 = time.time()
    assert sum(rt.get([a.go.remote() for _ in range(10)])) == 10
    assert time.time() - t0 < 1.5  # concurrent, not 2s serial


def test_named_actor(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc_test").remote()
    h = rt.get_actor("svc_test")
    assert rt.get(h.ping.remote()) == "pong"


def test_actor_handle_passing(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Store:
        def __init__(self):
            self.v = {}

        def set(self, k, v):
            self.v[k] = v

        def get(self, k):
            return self.v.get(k)

    @rt.remote
    def writer(store, k, v):
        rt.get(store.set.remote(k, v))
        return True

    s = Store.remote()
    assert rt.get(writer.remote(s, "x", 42))
    assert rt.get(s.get.remote("x")) == 42


def test_actor_error(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method failed")

        def ok(self):
            return 1

    b = Bad.remote()
    with pytest.raises(rt.exceptions.TaskError):
        rt.get(b.fail.remote())
    # Actor survives method errors.
    assert rt.get(b.ok.remote()) == 1


def test_kill_actor(rt_fresh):
    rt = rt_fresh

    @rt.remote
    class K:
        def ping(self):
            return 1

    k = K.remote()
    assert rt.get(k.ping.remote()) == 1
    rt.kill(k)
    time.sleep(0.3)
    with pytest.raises(Exception):
        rt.get(k.ping.remote(), timeout=10)


def test_actor_restart(rt_fresh):
    rt = rt_fresh

    @rt.remote
    class Dier:
        def __init__(self):
            self.n = 0

        def crash(self):
            import os

            os._exit(1)

        def ping(self):
            self.n += 1
            return self.n

    d = Dier.options(max_restarts=2).remote()
    assert rt.get(d.ping.remote()) == 1
    d.crash.remote()
    # Wait for head to detect death + restart.
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            # Fresh instance => counter reset to 1.
            if rt.get(d.ping.remote(), timeout=10) == 1:
                break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor did not restart")


def test_list_actors(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class L:
        def x(self):
            return 1

    L.options(name="listed_actor").remote()
    infos = rt.list_actors()
    names = {i["name"] for i in infos}
    assert "listed_actor" in names
