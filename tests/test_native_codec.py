"""Native data-plane codec: build, parity with the Python fallback,
zero-copy unpack, scatter-into-buffer.

Mirrors the reference's expectation that the data plane is native C++
(``src/ray/object_manager/plasma``): the codec must produce bit-identical
blobs to the Python path so mixed deployments interoperate.
"""
import numpy as np
import pytest

from ray_tpu import _native
from ray_tpu._private.serialization import (pack_frames, pack_frames_into,
                                            packed_size, unpack_frames)

FRAMES = [b"header", np.arange(257).tobytes(), b"", b"z" * 1009]


def test_native_builds():
    assert _native.load() is not None, \
        "native codec failed to build (g++ is in the image)"


def test_roundtrip_and_python_parity(monkeypatch):
    blob_native = pack_frames(FRAMES)
    got = [bytes(f) for f in unpack_frames(blob_native)]
    assert got == [bytes(f) for f in FRAMES]

    # force the pure-python path; blobs must be byte-identical
    monkeypatch.setattr(_native, "_mod", None)
    monkeypatch.setattr(_native, "_tried", True)
    blob_py = pack_frames(FRAMES)
    assert blob_py == blob_native
    got = [bytes(f) for f in unpack_frames(blob_native)]
    assert got == [bytes(f) for f in FRAMES]


def test_scatter_into_buffer():
    size = packed_size(FRAMES)
    buf = bytearray(size + 32)
    written = pack_frames_into(memoryview(buf), 16, FRAMES)
    assert written == size
    out = unpack_frames(memoryview(buf)[16:16 + size])
    assert [bytes(f) for f in out] == [bytes(f) for f in FRAMES]


def test_corrupt_blob_rejected():
    nat = _native.load()
    if nat is None:
        pytest.skip("native codec unavailable")
    with pytest.raises(ValueError):
        nat.frame_offsets(b"\x05\x00")  # truncated header
    bad = pack_frames([b"abcd"])[:-2]  # frame overruns blob
    with pytest.raises(ValueError):
        nat.frame_offsets(bad)


def test_shm_store_uses_codec(rt_cluster):
    import ray_tpu as rt

    arr = np.random.default_rng(0).random(1 << 18)
    ref = rt.put(arr)  # large → shm tier → pack_frames_into path
    np.testing.assert_array_equal(rt.get(ref), arr)
