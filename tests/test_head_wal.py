"""Head mutation WAL (reference: per-operation GCS persistence to
Redis, ``src/ray/gcs/store_client/redis_store_client.h``): mutations
acknowledged moments before a kill -9 survive the restart — no
snapshot-cadence loss window."""
import os
import signal
import subprocess
import sys
import tempfile
import time

import ray_tpu as rt
from ray_tpu._private.wal import HeadWAL

from test_head_failover import _start_head

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- unit level


def test_wal_roundtrip(tmp_path):
    w = HeadWAL(str(tmp_path))
    w.open_active()
    w.append({"op": "kv_put", "ns": "n", "key": "k", "value": b"v"})
    w.append({"op": "pg_remove", "pg_id": "ab" * 14})
    w.close()
    r = HeadWAL(str(tmp_path))
    recs = list(r.replay_from(0))
    assert [x["op"] for x in recs] == ["kv_put", "pg_remove"]
    assert recs[0]["value"] == b"v"


def test_wal_roll_and_drop(tmp_path):
    w = HeadWAL(str(tmp_path))
    w.open_active()
    w.append({"op": "a"})
    gen = w.roll()  # snapshot boundary
    w.append({"op": "b"})
    # replay from the snapshot's stamp sees only post-roll records
    assert [x["op"] for x in w.replay_from(gen)] == ["b"]
    w.drop_below(gen)
    assert w.existing_gens() == [gen]
    w.close()


def test_wal_torn_tail_tolerated(tmp_path):
    w = HeadWAL(str(tmp_path))
    w.open_active()
    w.append({"op": "good1"})
    w.append({"op": "good2"})
    w.close()
    path = w._path(w.gen)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-3])  # kill -9 mid-append: torn final frame
    recs = [x["op"] for x in HeadWAL(str(tmp_path)).replay_from(0)]
    assert recs == ["good1"]


# ------------------------------------------------------- kill -9 survival


def test_mutations_survive_kill9(monkeypatch):
    """KV writes, a named-actor registration, and a placement group
    made ~1s before kill -9 — i.e. well inside the 10s snapshot
    cadence — are all there after restart."""
    monkeypatch.setenv("RT_HEAD_RECONNECT_TIMEOUT_S", "180")
    if rt.is_initialized():
        rt.shutdown()
    session_dir = tempfile.mkdtemp(prefix="rt_wal_")
    head, info = _start_head(session_dir)
    host, port = info["tcp_address"]
    node = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--head", f"{host}:{port}",
         "--session-dir", session_dir,
         "--num-cpus", "4", "--die-with-parent"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    head2 = None
    try:
        rt.init(address=info["head_sock"])

        @rt.remote
        class Keeper:
            def ping(self):
                return "alive"

        # the mutations under test — all acknowledged before the kill;
        # NO forced snapshot (the failover test needs one — this test
        # exists to prove the WAL makes that unnecessary)
        from ray_tpu.api import _core

        _core().kv_put("wal-key", b"wal-value", ns="app")
        keeper = Keeper.options(name="wal-keeper", num_cpus=1,
                                max_restarts=2).remote()
        assert rt.get(keeper.ping.remote(), timeout=30) == "alive"
        pg = rt.placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.ready(timeout=30)

        time.sleep(1.0)
        os.kill(head.pid, signal.SIGKILL)
        head.wait(timeout=10)
        head2, info2 = _start_head(session_dir)
        assert info2["head_sock"] == info["head_sock"]

        # KV + named actor survived the kill (acknowledged ~1s before
        # it). Retry loop: the driver reconnects to the restarted head
        # lazily, and actor reattachment takes the reconcile window.
        deadline = time.time() + 120
        last_err = None
        while time.time() < deadline:
            try:
                assert _core().kv_get("wal-key", ns="app") == b"wal-value"
                got = rt.get_actor("wal-keeper", timeout=5)
                assert rt.get(got.ping.remote(), timeout=10) == "alive"
                break
            except AssertionError:
                raise  # data came back WRONG — fail immediately
            except Exception as e:  # noqa: BLE001 - still reconciling
                last_err = e
                time.sleep(1)
        else:
            raise AssertionError(f"state did not survive: {last_err}")
        # placement group record survived (re-placed once nodes attach)
        pgs = rt.state("placement_groups")
        assert len(pgs) == 1, pgs
    finally:
        for p in (head, head2, node):
            try:
                p and p.kill()
            except Exception:
                pass
        try:
            rt.shutdown()
        except Exception:
            pass
