"""Chunked multi-source object transfer (reference:
``object_manager/pull_manager.h:52`` 64MiB chunked pulls +
``ownership_based_object_directory.h`` location-aware sources): big
cross-node objects stream as pipelined byte ranges, pullers register as
copies, and shm domains isolate synthetic nodes like real hosts."""
import os

import numpy as np
import pytest

import ray_tpu as rt


@pytest.fixture
def two_node_cluster():
    from ray_tpu.cluster_utils import Cluster

    if rt.is_initialized():
        rt.shutdown()
    # Force tiny chunks so modest arrays exercise the chunk pipeline.
    os.environ["RT_TRANSFER_CHUNK_BYTES"] = str(256 * 1024)
    cluster = Cluster()
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()
    yield cluster, n1, n2
    os.environ.pop("RT_TRANSFER_CHUNK_BYTES", None)
    try:
        rt.shutdown()
    except Exception:
        pass
    cluster.shutdown()


def test_shm_domains_isolate(two_node_cluster):
    """A segment created in one domain must not be attachable from
    another — synthetic nodes now model real hosts faithfully."""
    from ray_tpu._private.object_store import SharedMemoryStore

    a = SharedMemoryStore(1 << 24, domain="hostA")
    b = SharedMemoryStore(1 << 24, domain="hostB")
    from ray_tpu._private.ids import ObjectID

    oid = ObjectID.from_random()
    a.create(oid, [b"h", b"x" * 1024])
    assert a.get(oid) is not None
    assert b.get(oid) is None
    a.delete(oid)


def test_create_clobbers_stale_pending_segment():
    """A producer's create must overwrite a half-written (count-0)
    leftover segment — e.g. a crashed pull racing lineage recovery —
    instead of treating it as an idempotent existing copy."""
    import time as _time

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import SharedMemoryStore

    dom = f"clobber-{os.getpid()}-{int(_time.time())}"
    store = SharedMemoryStore(1 << 24, domain=dom)
    reader = SharedMemoryStore(1 << 24, domain=dom)
    oid = ObjectID.from_random()

    # A pending (unsealed) segment: attachers must see not-ready.
    view = store.create_pending(oid, [3, 3])
    assert view is not None
    assert reader.get(oid) is None
    # A second pending for the same object in the same store is refused.
    assert store.create_pending(oid, [64]) is None

    # The producer lands the real value over the stale pending segment.
    frames = [b"hdr", b"body"]
    store2 = SharedMemoryStore(1 << 24, domain=dom)
    store2.create(oid, frames)
    # The loser's abort must NOT unlink the successor's complete copy
    # (it checks the name still maps to its own inode).
    store.abort_pending(oid)
    got = reader.get(oid)
    assert got is not None and bytes(got[1]) == b"body"
    store2.delete(oid)


def test_pending_seal_publishes():
    """create_pending → write → seal roundtrip: count lands last and
    readers in the same domain attach the sealed copy."""
    import time as _time

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import SharedMemoryStore

    dom = f"seal-{os.getpid()}-{int(_time.time())}"
    store = SharedMemoryStore(1 << 24, domain=dom)
    reader = SharedMemoryStore(1 << 24, domain=dom)
    oid = ObjectID.from_random()
    frames = [b"h", b"payload-bytes"]
    view = store.create_pending(oid, [len(f) for f in frames])
    off = 0
    for f in frames:
        view[off:off + len(f)] = f
        off += len(f)
    assert reader.get(oid) is None  # count still 0
    store.seal(oid)
    got = reader.get(oid)
    assert got is not None and bytes(got[1]) == b"payload-bytes"
    store.delete(oid)


def test_pending_ttl_sweep_reclaims_crashed_puller():
    """ISSUE 14 satellite regression: a puller that dies between
    ``create_pending`` and seal/abort must not pin its reserved bytes
    (or squat the segment name) forever — the TTL sweep, run on the
    same lease-clock discipline as the serve handoff plane, aborts the
    orphan: capacity returns and a new writer can claim the name."""
    import time as _time

    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import SharedMemoryStore

    dom = f"pend-ttl-{os.getpid()}-{int(_time.time())}"
    store = SharedMemoryStore(1 << 24, domain=dom)
    oid = ObjectID.from_random()
    frames = [b"h", b"x" * 4096]
    view = store.create_pending(oid, [len(f) for f in frames])
    assert view is not None
    reserved = store.used_bytes()
    assert reserved > 0 and store.pending_count() == 1
    # Simulate the crash: the puller never seals, never aborts. The
    # sweep is a no-op before the TTL...
    assert store.sweep_pending() == 0
    assert store.pending_count() == 1
    # ...and reclaims after it (clock injected: no real waiting).
    assert store.sweep_pending(
        now=_time.monotonic() + store.PENDING_TTL_S + 1) == 1
    assert store.pending_count() == 0
    assert store.used_bytes() == 0, "reserved bytes leaked"
    del view  # the crashed writer's view (kept alive above for realism)
    # The name is free again: a fresh transfer of the same object
    # reserves, writes, seals, and reads back.
    view2 = store.create_pending(oid, [len(f) for f in frames])
    assert view2 is not None, "swept segment still squats the name"
    off = 0
    for f in frames:
        view2[off:off + len(f)] = f
        off += len(f)
    store.seal(oid)
    got = store.get(oid)
    assert got is not None and bytes(got[1]) == frames[1]
    store.delete(oid)
    # Opportunistic sweep: an expired orphan is reclaimed by the NEXT
    # create_pending (no dedicated sweeper thread needed).
    oid2, oid3 = ObjectID.from_random(), ObjectID.from_random()
    assert store.create_pending(oid2, [1, 16]) is not None
    store._pending[oid2] = store._pending[oid2][:3] + (
        _time.monotonic() - store.PENDING_TTL_S - 1,)
    assert store.create_pending(oid3, [1, 16]) is not None
    assert store.pending_count() == 1          # oid2 swept, oid3 live
    store.abort_pending(oid3)
    # A slow-but-alive puller whose reservation was swept must get a
    # clean typed error at seal — not a KeyError, and NEVER a torn
    # publish of a retrying writer's half-written segment.
    oid4 = ObjectID.from_random()
    stale_view = store.create_pending(oid4, [1, 16])
    assert store.sweep_pending(now=_time.monotonic()
                               + store.PENDING_TTL_S + 1) == 1
    with pytest.raises(RuntimeError, match="swept"):
        store.seal(oid4, view=stale_view)
    # A retrying writer re-creates the same object id; the STALE
    # writer's seal/abort must not touch the new reservation.
    fresh_view = store.create_pending(oid4, [1, 16])
    assert fresh_view is not None
    with pytest.raises(RuntimeError, match="another writer"):
        store.seal(oid4, view=stale_view)
    store.abort_pending(oid4, view=stale_view)   # guarded no-op
    assert store.pending_count() == 1
    fresh_view[:] = b"h" + b"y" * 16
    store.seal(oid4, view=fresh_view)
    got4 = store.get(oid4)
    assert got4 is not None and bytes(got4[1]) == b"y" * 16
    store.delete(oid4)


def test_concurrent_same_ref_pulls(two_node_cluster):
    """Several tasks on one node consuming the SAME big remote ref: one
    transfer, every consumer gets the value (in-process pull dedup)."""
    cluster, n1, n2 = two_node_cluster

    @rt.remote
    def produce():
        return np.full(1 << 19, 3.0, dtype=np.float32)

    @rt.remote
    def consume(x, _i):
        return float(x[0])

    r = produce.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n1.node_id, soft=False)).remote()
    outs = [consume.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n2.node_id, soft=False)).remote(r, i)
        for i in range(6)]
    assert rt.get(outs, timeout=120) == [3.0] * 6


def test_cross_node_chunked_pull(two_node_cluster):
    """A multi-chunk array produced on node 1 is consumed on node 2 —
    only the chunk protocol can move it (domains don't share shm)."""
    cluster, n1, n2 = two_node_cluster

    @rt.remote
    def produce():
        return np.arange(1 << 19, dtype=np.float32)  # 2 MB = 8 chunks

    @rt.remote
    def consume(x):
        return float(x.sum())

    # Pin producer and consumer to different nodes via node affinity.
    r = produce.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n1.node_id, soft=False)).remote()
    out = consume.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n2.node_id, soft=False)).remote(r)
    want = float(np.arange(1 << 19, dtype=np.float32).sum())
    assert rt.get(out, timeout=120) == want


def test_pullers_register_as_copies(two_node_cluster):
    """After a cross-node pull, the head's object directory lists the
    puller as an additional copy (the broadcast fan-out substrate)."""
    cluster, n1, n2 = two_node_cluster
    from ray_tpu.core.worker import CoreWorker

    @rt.remote
    def produce():
        return np.ones(1 << 19, dtype=np.float32)

    @rt.remote
    def consume(x):
        return float(x[0])

    r = produce.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n1.node_id, soft=False)).remote()
    assert rt.get(consume.options(
        scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
            node_id=n2.node_id, soft=False)).remote(r), timeout=120) == 1.0

    core = CoreWorker._current
    locs = core.run_sync(core._head.call_simple(
        "object_loc_get", {"object_id": r.object_id.hex()}))["locations"]
    domains = {loc["domain"] for loc in locs}
    assert len(locs) >= 2, locs   # producer + puller
    assert len(domains) >= 2, locs
