"""Compiled-DAG fan-out / fan-in / multi-output + cross-node channels
(reference: ``python/ray/dag/compiled_dag_node.py:372`` general
topologies; ``node_manager.proto:430-432`` cross-node mutable objects)."""
import pytest

import ray_tpu as rt
from ray_tpu.dag import InputNode, MultiOutputNode


@rt.remote
class Adder:
    def __init__(self, k):
        self.k = k

    def add(self, x):
        return x + self.k

    def join(self, a, b):
        return a + b


def test_fan_out_fan_in(rt_cluster):
    """input → (a, b) in parallel → aggregator joins both."""
    a = Adder.remote(10)
    b = Adder.remote(100)
    agg = Adder.remote(0)
    with InputNode() as inp:
        left = a.add.bind(inp)
        right = b.add.bind(inp)
        out = agg.join.bind(left, right)
    dag = out.experimental_compile(timeout=120.0)
    try:
        for i in range(5):
            # (i+10) + (i+100)
            assert dag.execute(i) == 2 * i + 110
    finally:
        dag.teardown()


def test_multi_output(rt_cluster):
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        n1 = a.add.bind(inp)
        n2 = b.add.bind(inp)
    dag = MultiOutputNode([n1, n2]).experimental_compile(timeout=120.0)
    try:
        assert dag.execute(10) == [11, 12]
        assert dag.execute(20) == [21, 22]
    finally:
        dag.teardown()


def test_error_propagates_through_fanin(rt_cluster):
    @rt.remote
    class Bad:
        def boom(self, x):
            raise ValueError("dag boom")

    a = Adder.remote(1)
    bad = Bad.remote()
    agg = Adder.remote(0)
    with InputNode() as inp:
        out = agg.join.bind(a.add.bind(inp), bad.boom.bind(inp))
    dag = out.experimental_compile(timeout=120.0)
    try:
        with pytest.raises(Exception, match="dag boom"):
            dag.execute(1)
    finally:
        dag.teardown()


def test_cross_node_two_stage_pipeline():
    """VERDICT demo: a 2-stage pipeline across 2 nodes feeding one
    aggregator — edges that cross shm domains ride the TCP channel."""
    from ray_tpu.cluster_utils import Cluster

    if rt.is_initialized():
        rt.shutdown()
    cluster = Cluster()
    try:
        n1 = cluster.add_node(num_cpus=2)
        n2 = cluster.add_node(num_cpus=2)
        cluster.connect()
        strat = rt.NodeAffinitySchedulingStrategy

        s1 = Adder.options(
            scheduling_strategy=strat(n1.node_id, soft=False)).remote(10)
        s2 = Adder.options(
            scheduling_strategy=strat(n2.node_id, soft=False)).remote(100)
        agg = Adder.options(
            scheduling_strategy=strat(n2.node_id, soft=False)).remote(0)

        with InputNode() as inp:
            out = agg.join.bind(s1.add.bind(inp), s2.add.bind(inp))
        dag = out.experimental_compile(timeout=60)
        try:
            from ray_tpu.experimental.channel import TcpChannel

            kinds = {type(c).__name__ for c in dag._channels.values()}
            assert "TcpChannel" in kinds, kinds  # actually crossed nodes
            for i in range(3):
                assert dag.execute(i) == 2 * i + 110
        finally:
            dag.teardown()
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()
