"""Flight recorder (ISSUE 19): crash-durable event rings, the merge
that defeats wall-clock skew, and post-mortem request reconstruction.

The SIGKILL test is the tentpole's core claim — a process killed with
no chance to flush still leaves its last-N events readable on disk —
so it runs a real subprocess and a real ``SIGKILL``, not a mock."""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import pytest

from ray_tpu._private import events as ev

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_recorder():
    ev._reset_for_tests()
    yield
    ev._reset_for_tests()


# ---------------------------------------------------------------- recorder
def test_ring_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        rec = ev.Recorder(ev.ring_path(d, "t"), "t")
        for i in range(7):
            assert rec.emit("unit.test", {"i": i, "request": f"rq-{i}"})
        rec.close()
        ring = ev.read_ring(rec.path)
        assert ring["torn"] == 0
        assert [e["attrs"]["i"] for e in ring["events"]] == list(range(7))
        assert all(e["kind"] == "unit.test" for e in ring["events"])
        # monotonic stamps are non-decreasing in seq order
        monos = [e["mono"] for e in ring["events"]]
        assert monos == sorted(monos)


def test_ring_wrap_keeps_last_n():
    with tempfile.TemporaryDirectory() as d:
        rec = ev.Recorder(ev.ring_path(d, "t"), "t", n_slots=8)
        for i in range(20):
            rec.emit("unit.wrap", {"i": i})
        rec.close()
        ring = ev.read_ring(rec.path)
        assert [e["attrs"]["i"] for e in ring["events"]] == \
            list(range(12, 20))
        assert ring["events"][0]["seq"] == 13  # oldest surviving seq


def test_rate_cap_bounds_storm_and_file_size():
    """A dispatch-per-token storm cannot grow the ring file or evict
    the whole tail: drops are counted per kind, size stays fixed."""
    with tempfile.TemporaryDirectory() as d:
        rec = ev.Recorder(ev.ring_path(d, "t"), "t", rate_per_s=10.0)
        size0 = os.path.getsize(rec.path)
        for i in range(5000):
            rec.emit("engine.dispatch", {"i": i})
        assert os.path.getsize(rec.path) == size0
        st = rec.stats()
        assert st["dropped"]["engine.dispatch"] > 4000
        assert st["emitted"] + st["dropped_total"] == 5000
        # a different kind has its own bucket and still gets through
        assert rec.emit("engine.preempt", {"slot": 0})
        rec.close()


def test_disabled_is_true_noop():
    """Disabled emit must not touch attrs (no pickling, no file): it
    returns False before looking at the payload — pinned by handing it
    a value whose repr/reduce would raise."""
    class Bomb:
        def __repr__(self):
            raise RuntimeError("repr touched")

        def __reduce__(self):
            raise RuntimeError("pickle touched")

    os.environ.pop(ev.EVENTS_DIR_ENV, None)
    assert ev.emit("unit.noop", payload=Bomb()) is False
    assert ev.driver_emit("unit.noop", payload=Bomb()) is False
    # fast path is latched: resolved, no recorder, no ring file
    assert ev._resolved and ev.recorder() is None
    assert ev.stats() == {"enabled": False}


def test_init_env_fallback_and_idempotence(monkeypatch):
    with tempfile.TemporaryDirectory() as d:
        monkeypatch.setenv(ev.EVENTS_DIR_ENV, d)
        assert ev.emit("unit.env", i=1)       # lazy init via env
        rec = ev.recorder()
        assert rec is not None and ev.init() is rec
        st = ev.stats()
        assert st["enabled"] and st["emitted"] == 1
        files = [f for f in os.listdir(d) if f.endswith(".evr")]
        assert len(files) == 1


def test_unwritable_dir_degrades_to_disabled(monkeypatch):
    monkeypatch.setenv(ev.EVENTS_DIR_ENV,
                       "/proc/definitely/not/writable")
    assert ev.emit("unit.bad", i=1) is False
    assert ev.stats() == {"enabled": False}


def test_oversized_attrs_truncated_but_correlated():
    """An attrs blob too big for a slot keeps its correlation ids —
    the record degrades, the request's timeline does not lose a hop."""
    with tempfile.TemporaryDirectory() as d:
        rec = ev.Recorder(ev.ring_path(d, "t"), "t")
        rec.emit("unit.big", {"request": "rq-9", "blob": "x" * 10000})
        rec.close()
        assert rec.truncated == 1
        ring = ev.read_ring(rec.path)
        (e,) = ring["events"]
        assert e["attrs"]["request"] == "rq-9"
        assert e["attrs"]["truncated"] is True
        assert "blob" not in e["attrs"]


# ------------------------------------------------------------- crash claim
_KILLED_WRITER = r"""
import os, signal, sys
from ray_tpu._private import events as ev
rec = ev.init(sys.argv[1], proc="victim")
for i in range(200):
    rec.emit("crash.step", {"i": i, "request": "rq-dead"})
os.kill(os.getpid(), signal.SIGKILL)   # no flush, no atexit, nothing
"""


def test_sigkill_preserves_ring():
    """The crash-durability claim: SIGKILL mid-run (the writer never
    flushes or closes) still leaves every committed event readable; a
    torn FINAL record is tolerated and counted, never fatal."""
    with tempfile.TemporaryDirectory() as d:
        p = subprocess.run(
            [sys.executable, "-c", _KILLED_WRITER, d],
            cwd=REPO, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert p.returncode == -signal.SIGKILL
        files = [os.path.join(d, f) for f in os.listdir(d)
                 if f.endswith(".evr")]
        assert len(files) == 1
        ring = ev.read_ring(files[0])
        assert ring["proc"] == "victim"
        got = [e["attrs"]["i"] for e in ring["events"]
               if e["kind"] == "crash.step"]
        # Complete prefix: the commit protocol (seq stamped LAST) means
        # every readable record is whole, and at most the final in-
        # flight one is torn.
        assert got == list(range(len(got))) and len(got) >= 199
        assert ring["torn"] <= 1


# ----------------------------------------------------------------- merging
def test_merge_orders_by_monotonic_despite_wall_skew():
    """Two processes, one with its wall clock an hour in the past: the
    merged order must follow the monotonic anchors, and the unified
    stamps must keep the true spacing."""
    from tools.rtblackbox import merge_timeline

    with tempfile.TemporaryDirectory() as d:
        a = ev.Recorder(ev.ring_path(d, "a"), "a")
        b = ev.Recorder(ev.ring_path(d, "b"), "b", wall_skew_s=-3600.0)
        a.emit("m.first", {})
        time.sleep(0.02)
        b.emit("m.second", {})
        time.sleep(0.02)
        a.emit("m.third", {})
        a.close(), b.close()
        rings = [ev.read_ring(a.path), ev.read_ring(b.path)]
        # the skew is real: b's raw wall stamps sit an hour early
        wall_b = rings[1]["events"][0]["wall"]
        wall_a = rings[0]["events"][0]["wall"]
        assert wall_b < wall_a - 3000
        tl = merge_timeline(rings)
        assert [e["kind"] for e in tl["events"]] == \
            ["m.first", "m.second", "m.third"]
        ts = [e["t"] for e in tl["events"]]
        assert ts == sorted(ts) and ts[-1] - ts[0] < 5.0


def test_request_reconstruction_and_cli():
    """A synthetic kill-and-resume story across three rings (router,
    dead replica, successor): reconstruction stitches the request's own
    events with the kill/drain context that explains its fate, and the
    CLI renders it."""
    from tools.rtblackbox import (load_rings, merge_timeline,
                                  reconstruct_request)
    from tools.rtblackbox.__main__ import main as bb_main

    with tempfile.TemporaryDirectory() as d:
        rt = ev.Recorder(ev.ring_path(d, "router"), "router")
        r0 = ev.Recorder(ev.ring_path(d, "rep0"), "rep0")
        r1 = ev.Recorder(ev.ring_path(d, "rep1"), "rep1")
        rid = "rq-dead-1"
        r0.emit("replica.admit", {"request": rid, "replica": "D#0"})
        r0.emit("engine.admit", {"request": rid, "slot": 0, "epoch": 0})
        r0.emit("chaos.kill", {"replica": "D#0", "target": "replica"})
        rt.emit("router.resume", {"request": rid, "from_replica": "D#0",
                                  "to_replica": "D#1", "delivered": 3})
        r1.emit("replica.admit", {"request": rid, "replica": "D#1"})
        r1.emit("engine.resume", {"request": rid, "resume_from": 3,
                                  "epoch": 0})
        rt.emit("client.verdict", {"request": rid, "ok": True,
                                   "identical": True})
        for r in (rt, r0, r1):
            r.close()
        tl = merge_timeline(load_rings(d)["rings"])
        story = reconstruct_request(tl, rid)
        kinds = [e["kind"] for e in story["events"]]
        assert kinds == ["replica.admit", "engine.admit", "chaos.kill",
                        "router.resume", "replica.admit",
                        "engine.resume", "client.verdict"]
        assert story["replicas"] == ["D#0", "D#1"]
        ctx = [e for e in story["events"] if e["relevance"] == "context"]
        assert [e["kind"] for e in ctx] == ["chaos.kill"]
        assert bb_main([d, "--request", rid, "--json"]) == 0
        assert bb_main([d]) == 0


# -------------------------------------------------------------- metrics tie
def test_dropped_events_feed_the_counter(monkeypatch):
    from ray_tpu._private.metrics import serve_metrics

    with tempfile.TemporaryDirectory() as d:
        monkeypatch.setenv(ev.EVENTS_DIR_ENV, d)
        ev.init(rate_per_s=5.0)
        c = serve_metrics()["events_dropped"]
        key = (("kind", "unit.storm"),)
        before = dict(c.collect()).get(key, 0.0)
        for i in range(200):
            ev.emit("unit.storm", i=i)
        dropped = ev.stats()["dropped"].get("unit.storm", 0)
        assert dropped > 0
        assert dict(c.collect()).get(key, 0.0) - before == dropped
