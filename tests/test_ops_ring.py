"""Sequence-parallel attention (ring + Ulysses) vs single-device XLA.

Runs on the virtual 8-device CPU mesh from conftest. These are the
equivalence tests VERDICT round 1 asked for: the sp-sharded result must
match the unsharded einsum attention bit-for-tolerance.
"""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map
from ray_tpu.models.gpt import GPTConfig, _attention_xla
from ray_tpu.ops.ring_attention import ring_attention, ulysses_attention
from ray_tpu.parallel import create_mesh


def _qkv(key, B, S, H, hd):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, S, H, hd), jnp.float32)
                 for k in ks)


def _run_sp(fn, mesh, axis, q, k, v):
    spec = P(None, axis, None, None)
    inner = functools.partial(fn, axis_name=axis, causal=True,
                              axis_size=mesh.shape[axis])
    sharded = jax.jit(shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    return sharded(q, k, v)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention],
                         ids=["ring", "ulysses"])
def test_sp_attention_matches_xla(fn):
    B, S, H, hd = 2, 128, 4, 32
    cfg = GPTConfig(n_head=H, d_model=H * hd)
    q, k, v = _qkv(jax.random.PRNGKey(0), B, S, H, hd)
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    out = _run_sp(fn, mesh, "sp", q, k, v)
    ref = _attention_xla(q, k, v, cfg)
    err = float(jnp.max(jnp.abs(out - ref)))
    assert err < 1e-4, err


def test_ring_gradients_match_xla():
    B, S, H, hd = 1, 64, 2, 16
    cfg = GPTConfig(n_head=H, d_model=H * hd)
    q, k, v = _qkv(jax.random.PRNGKey(1), B, S, H, hd)
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    spec = P(None, "sp", None, None)
    inner = functools.partial(ring_attention, axis_name="sp", causal=True,
                              axis_size=4)
    sp_fn = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)

    def loss_sp(q, k, v):
        return jnp.sum(sp_fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_attention_xla(q, k, v, cfg) ** 2)

    gs = jax.jit(jax.grad(loss_sp, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gs, gr):
        rel = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
        assert rel < 1e-4, (name, rel)


@pytest.mark.parametrize("backend", ["ring", "ulysses"])
def test_gpt_trains_on_dp_sp_mesh(backend):
    """nano GPT trains one step with SP attention on a {dp, sp} mesh."""
    from ray_tpu.models import gpt

    # ulysses needs n_head (2 for nano) divisible by the sp size
    sp = 4 if backend == "ring" else 2
    mesh = create_mesh({"dp": 8 // sp, "sp": sp})
    cfg = dataclasses.replace(gpt.CONFIGS["nano"], attn_backend=backend,
                              sp_axis="sp")
    init, step, _, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = jax.device_put(
        rng.integers(0, cfg.vocab_size, (8, 65)).astype(np.int32), batch_sh)
    state, metrics = step(state, {"tokens": toks})
    loss1 = float(metrics["loss"])
    state, metrics = step(state, {"tokens": toks})
    loss2 = float(metrics["loss"])
    assert np.isfinite(loss1) and np.isfinite(loss2)
    assert loss2 < loss1  # it learns the (tiny, memorizable) batch


def test_ring_matches_gspmd_xla_model_level():
    """Full nano forward: ring backend == xla backend on the same mesh."""
    from ray_tpu.models import gpt

    mesh = create_mesh({"dp": 2, "sp": 4})
    cfg_x = dataclasses.replace(gpt.CONFIGS["nano"], attn_backend="xla",
                                dtype=jnp.float32)
    cfg_r = dataclasses.replace(cfg_x, attn_backend="ring", sp_axis="sp")
    params = gpt.init_params(jax.random.PRNGKey(0), cfg_x)
    toks = jnp.asarray(
        np.random.randint(0, cfg_x.vocab_size, (4, 64), np.int32))
    lx = jax.jit(lambda p, t: gpt.forward(p, t, cfg_x))(params, toks)
    lr = jax.jit(lambda p, t: gpt.forward(p, t, cfg_r, mesh))(params, toks)
    err = float(jnp.max(jnp.abs(lx - lr)))
    assert err < 1e-3, err
