"""Checkpoint storage abstraction + experiment restore.

Mirrors the reference's storage/persistence coverage
(``python/ray/train/tests/test_new_persistence.py``,
``tune/tests/test_tuner_restore.py``): URI-addressed checkpoint
upload/download, trainer runs against shared-dir ("bucket") storage, and
a killed tune experiment resuming to completion.
"""
import os
import time

import numpy as np
import pytest

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.storage import get_filesystem, is_uri


@pytest.fixture
def mock_root(tmp_path, monkeypatch):
    monkeypatch.setenv("RT_MOCK_FS_ROOT", str(tmp_path / "bucket"))
    return str(tmp_path / "bucket")


def test_filesystem_resolution(mock_root):
    fs, uri = get_filesystem("mock://exp/ckpt")
    assert fs.resolve(uri) == os.path.join(mock_root, "exp/ckpt")
    lfs, p = get_filesystem("/tmp/x")
    assert lfs.resolve(p) == "/tmp/x"
    with pytest.raises(ValueError, match="cloud"):
        get_filesystem("gs://bucket/x")


def test_checkpoint_uri_roundtrip(mock_root, tmp_path):
    state = {"w": np.arange(8.0), "b": np.float32(3)}
    local = Checkpoint.from_state(state, base_dir=str(tmp_path))
    fs, _ = get_filesystem("mock://exp1/c0")
    fs.upload_dir(local.path, "mock://exp1/c0")

    remote = Checkpoint("mock://exp1/c0")
    assert is_uri(remote.path)
    restored = remote.load_state(like=state)
    np.testing.assert_array_equal(restored["w"], state["w"])


def test_checkpoint_manager_uri_retention(mock_root, tmp_path):
    mgr = CheckpointManager("mock://exp2/ckpts", num_to_keep=2,
                            score_attribute="acc")
    fs, _ = get_filesystem("mock://exp2/ckpts")
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        local = Checkpoint.from_state({"i": np.int64(i)},
                                      base_dir=str(tmp_path))
        uri = f"mock://exp2/ckpts/c{i}"
        fs.upload_dir(local.path, uri)
        mgr.register(Checkpoint(uri), {"acc": acc})
    kept = fs.listdir("mock://exp2/ckpts")
    assert kept == ["c1", "c2"]  # worst (acc=0.1) pruned from storage
    assert mgr.best_checkpoint.path.endswith("c1")


def test_trainer_with_shared_storage(rt_cluster):
    """Workers upload checkpoints straight to the shared 'bucket'.

    No env monkeypatching here: the worker processes were spawned before
    the test, so they resolve the default RT_MOCK_FS_ROOT — the bucket
    must be the same tree in every process.
    """
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        import numpy as _np

        for step in range(2):
            ckpt = Checkpoint.from_state({"step": _np.int64(step)})
            train.report({"loss": 1.0 - step * 0.1}, checkpoint=ckpt)

    trainer = JaxTrainer(
        train_loop_per_worker=loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name=f"shared_{int(time.time())}",
                             storage_path="mock://results"))
    result = trainer.fit()
    assert result.checkpoint is not None
    assert result.checkpoint.path.startswith("mock://")
    state = result.checkpoint.load_state(
        like={"step": np.int64(0)})
    assert int(state["step"]) == 1


def test_tuner_restore_completes(rt_cluster, tmp_path):
    """A tune run stopped mid-flight resumes and completes all samples."""
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1)})
            time.sleep(0.2)

    run_config = RunConfig(name="restore_exp", storage_path=str(tmp_path))
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=tune.TuneConfig(num_samples=1, metric="score",
                                    mode="max", max_concurrent_trials=2,
                                    time_budget_s=1.5),
        run_config=run_config)
    partial = tuner.fit()  # budget cuts it off mid-experiment
    exp_dir = os.path.join(str(tmp_path), "restore_exp")
    assert os.path.exists(os.path.join(exp_dir, "experiment_state.pkl"))
    done_before = sum(1 for r in partial.results
                      if r.status == "TERMINATED")
    assert done_before < 4

    restored = tune.Tuner.restore(
        exp_dir, trainable,
        tune_config=tune.TuneConfig(num_samples=1, metric="score",
                                    mode="max", max_concurrent_trials=2))
    grid = restored.fit()
    done = [r for r in grid.results if r.status == "TERMINATED"]
    assert len(done) == 4, [(r.trial_id, r.status) for r in grid.results]
    xs = sorted(r.config["x"] for r in done)
    assert xs == [1, 2, 3, 4]
    best = grid.get_best_result()
    assert best.metrics["score"] == 12  # x=4, iter 3

    # loggers wrote per-trial artifacts
    t0 = done[0]
    assert os.path.exists(os.path.join(t0.path, "result.json"))
    assert os.path.exists(os.path.join(t0.path, "progress.csv"))


def test_sharded_checkpoint_roundtrip_and_reshard(tmp_path):
    """Orbax-backed sharded save/restore: each process writes its own
    shards (no host gather), and a restore onto a DIFFERENT mesh shape
    reshards on read — checkpoints are portable across topologies."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import create_mesh
    from ray_tpu.train.checkpoint import Checkpoint

    devs = jax.devices()
    mesh8 = create_mesh({"fsdp": 8}, devices=devs)
    sh8 = NamedSharding(mesh8, P("fsdp"))
    state = {
        "w": jax.device_put(jnp.arange(64, dtype=jnp.float32), sh8),
        "b": jax.device_put(jnp.ones((8, 4), jnp.float32),
                            NamedSharding(mesh8, P("fsdp", None))),
        "step": jnp.int32(7),
    }
    ckpt = Checkpoint.from_sharded_state(state, base_dir=str(tmp_path))

    # Same-mesh restore: exact values, target shardings respected.
    like = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)),
        state)
    got = ckpt.load_sharded_state(like)
    np.testing.assert_array_equal(np.asarray(got["w"]),
                                  np.arange(64, dtype=np.float32))
    assert got["w"].sharding == sh8
    assert int(got["step"]) == 7

    # Cross-topology restore: fsdp=4 mesh over half the devices.
    mesh4 = create_mesh({"fsdp": 4}, devices=devs[:4])
    sh4 = NamedSharding(mesh4, P("fsdp"))
    like4 = {
        "w": jax.ShapeDtypeStruct((64,), jnp.float32, sharding=sh4),
        "b": jax.ShapeDtypeStruct((8, 4), jnp.float32,
                                  sharding=NamedSharding(
                                      mesh4, P("fsdp", None))),
        "step": jax.ShapeDtypeStruct((), jnp.int32),
    }
    got4 = ckpt.load_sharded_state(like4)
    np.testing.assert_array_equal(np.asarray(got4["w"]),
                                  np.arange(64, dtype=np.float32))
    assert got4["w"].sharding == sh4
