"""BayesOptSearch tests (reference:
``tune/search/bayesopt`` — GP surrogate must beat random search on a
smooth objective within the same trial budget)."""
import numpy as np
import pytest

from ray_tpu import tune
from ray_tpu.tune.search import BayesOptSearch


def _objective(x, y):
    # smooth unimodal bowl, optimum at (0.7, 0.3), max value 0
    return -((x - 0.7) ** 2) - ((y - 0.3) ** 2)


def _run_searcher(searcher, space, n):
    searcher.set_search_space(space)
    best = -1e9
    for i in range(n):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        val = _objective(cfg["x"], cfg["y"])
        best = max(best, val)
        searcher.on_trial_complete(tid, {"score": val})
    return best


def test_bayesopt_beats_random_on_smooth_objective():
    space = {"x": tune.uniform(0.0, 1.0), "y": tune.uniform(0.0, 1.0)}
    n = 30
    bo_best = _run_searcher(
        BayesOptSearch("score", mode="max", num_initial_random=8, seed=0),
        space, n)
    # random baseline: best over the same budget, averaged over seeds
    rng_bests = []
    for seed in range(5):
        rng = np.random.default_rng(seed)
        vals = [_objective(rng.random(), rng.random()) for _ in range(n)]
        rng_bests.append(max(vals))
    assert bo_best > -0.005, f"BO did not converge: best={bo_best:.4f}"
    assert bo_best >= np.mean(rng_bests), (
        f"BO ({bo_best:.4f}) worse than mean random ({np.mean(rng_bests):.4f})")


def test_bayesopt_min_mode_and_domains():
    space = {
        "lr": tune.loguniform(1e-5, 1e-1),
        "layers": tune.randint(1, 5),
        "act": tune.choice(["relu", "tanh"]),
        "const": 42,
    }
    s = BayesOptSearch("loss", mode="min", num_initial_random=4, seed=1)
    s.set_search_space(space)
    for i in range(12):
        cfg = s.suggest(f"t{i}")
        assert 1e-5 <= cfg["lr"] <= 1e-1
        assert cfg["layers"] in (1, 2, 3, 4)
        assert cfg["act"] in ("relu", "tanh")
        assert cfg["const"] == 42
        # pretend loss = lr distance from 1e-3 (log scale)
        loss = abs(np.log10(cfg["lr"]) + 3.0)
        s.on_trial_complete(f"t{i}", {"loss": loss})
    # After warmup the GP should focus near lr=1e-3
    lrs = [s._from_unit(s._suggest_unit())["lr"] for _ in range(8)]
    assert min(abs(np.log10(lr) + 3.0) for lr in lrs) < 1.0


def test_bayesopt_register_trial_roundtrip():
    """Restored trials must train the GP on their true configs, not on
    fresh random points (unit-cube inverse mapping)."""
    space = {"x": tune.uniform(0.0, 2.0),
             "lr": tune.loguniform(1e-4, 1e-1),
             "act": tune.choice(["a", "b", "c"])}
    s = BayesOptSearch("score", seed=0)
    s.set_search_space(space)
    cfg = {"x": 1.5, "lr": 1e-2, "act": "b"}
    s.register_trial("restored", cfg)
    x = s._pending["restored"]
    roundtrip = s._from_unit(x)
    assert abs(roundtrip["x"] - 1.5) < 1e-9
    assert abs(np.log10(roundtrip["lr"]) + 2.0) < 1e-9
    assert roundtrip["act"] == "b"
    s.on_trial_complete("restored", {"score": 3.0})
    assert len(s._y) == 1 and s._y[0] == 3.0


def test_bayesopt_rejects_grid():
    s = BayesOptSearch("score")
    with pytest.raises(ValueError):
        s.set_search_space({"x": tune.grid_search([1, 2])})


def test_bayesopt_with_tuner(rt_cluster):
    def trainable(config):
        # inline objective: test-module globals don't unpickle in workers
        score = -((config["x"] - 0.7) ** 2) - ((config["y"] - 0.3) ** 2)
        tune.report({"score": score})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1), "y": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=12,
            search_alg=BayesOptSearch("score", mode="max",
                                      num_initial_random=6, seed=0)),
    )
    grid = tuner.fit()
    # num_samples caps an open-ended searcher
    assert len(grid) == 12
    best = grid.get_best_result()
    assert best.metrics["score"] > -0.25