"""Reference-counting GC + streaming generators.

Mirrors the reference's test strategy for these subsystems
(``python/ray/tests/test_reference_counting.py``,
``test_streaming_generator.py``): observe store occupancy around ref
lifetimes, and assert items stream before task completion.
"""
import gc
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.worker import CoreWorker


def _store_size():
    return CoreWorker.current().memory_store.size()


def _shm_used():
    return CoreWorker.current().shm_store.used_bytes()


def test_put_del_frees_memory_store(rt_cluster):
    # Grace-delayed borrow releases from earlier tests can free entries
    # mid-test; settle first, then allow only shrinkage.
    before = _store_size()
    deadline = time.time() + 8
    while time.time() < deadline:
        time.sleep(0.5)
        now = _store_size()
        if now == before:
            break
        before = now
    ref = rt.put({"some": "value"})
    assert _store_size() == before + 1
    del ref
    gc.collect()
    # Ref-dec processing is batched onto the IO-loop sweeper (~100ms
    # cadence); the free is asynchronous but prompt.
    deadline = time.time() + 2
    while time.time() < deadline and _store_size() > before:
        time.sleep(0.05)
    assert _store_size() <= before


def test_put_del_frees_shm(rt_cluster):
    before = _shm_used()
    ref = rt.put(np.zeros(1 << 20, dtype=np.float32))  # 4 MB -> shm tier
    assert _shm_used() >= before + (1 << 22)
    del ref
    gc.collect()
    deadline = time.time() + 2   # async sweeper-batched free
    while time.time() < deadline and _shm_used() > before:
        time.sleep(0.05)
    assert _shm_used() <= before


def test_task_results_freed_when_refs_dropped(rt_cluster):
    @rt.remote
    def f(i):
        return i

    base = _store_size()
    for i in range(200):
        rt.get(f.remote(i))  # ref dropped every iteration
    gc.collect()
    time.sleep(0.2)
    # Without GC this grows by ~200 (VERDICT: "memory grows unboundedly").
    assert _store_size() - base < 20, _store_size() - base


def test_borrower_keeps_object_alive(rt_cluster):
    @rt.remote
    class Holder:
        def __init__(self):
            self.refs = None

        def hold(self, refs):
            self.refs = refs
            return True

        def read(self):
            return float(rt.get(self.refs[0]).sum())

        def drop(self):
            import gc as _gc

            self.refs = None
            _gc.collect()
            return True

    h = Holder.remote()
    ref = rt.put(np.ones(1 << 20, dtype=np.float32))
    # Nested so the ref itself travels by pickle (top-level args deref).
    assert rt.get(h.hold.remote([ref])) is True
    oid = ref.object_id
    del ref
    gc.collect()
    time.sleep(0.3)
    # Borrower still holds it: owner must NOT have freed the object.
    assert rt.get(h.read.remote()) == float(1 << 20)
    rt.get(h.drop.remote())
    gc.collect()
    deadline = time.time() + 10
    core = CoreWorker.current()
    try:
        while time.time() < deadline:
            if not core.memory_store.contains(oid) and \
                    not core.shm_store.contains(oid):
                break
            time.sleep(0.1)
        else:
            pytest.fail("object never freed after borrower dropped it")
    finally:
        rt.kill(h)  # release the actor's CPU for later tests


def test_nested_ref_survives_repeated_gets(rt_cluster):
    """Repeated deserialization of a container must not consume the
    container's borrow on its inner ref (each deserialized ref acquires
    and pays back its own borrow)."""
    inner = rt.put(np.arange(16.0))
    outer = rt.put({"inner": inner})
    oid = inner.object_id
    del inner
    gc.collect()
    core = CoreWorker.current()
    for _ in range(5):
        got = rt.get(outer)["inner"]
        assert float(rt.get(got).sum()) == float(np.arange(16.0).sum())
        del got
        gc.collect()
    time.sleep(0.3)
    # container alive → inner must still be alive
    assert core.memory_store.contains(oid) or core.shm_store.contains(oid)
    del outer
    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline:
        if not core.memory_store.contains(oid) and \
                not core.shm_store.contains(oid):
            return
        time.sleep(0.1)
    pytest.fail("inner object not freed after container died")


def test_promoted_arg_freed_after_submission(rt_cluster):
    """A big arg promoted to shm is kept alive for the task (incl. its
    retries) and released once the submission completes."""

    @rt.remote
    def total(a):
        return float(a.sum())

    before = _shm_used()
    assert rt.get(total.remote(np.ones(1 << 20, dtype=np.float32))) == float(
        1 << 20)
    gc.collect()
    deadline = time.time() + 10
    while time.time() < deadline:
        if _shm_used() <= before:
            return
        time.sleep(0.1)
    pytest.fail(f"promoted arg leaked: {_shm_used() - before} bytes")


def test_streaming_generator_streams_before_completion(rt_cluster):
    @rt.remote
    def slow_gen(n):
        for i in range(n):
            time.sleep(0.15)
            yield i * i

    t0 = time.time()
    gen = slow_gen.options(num_returns="streaming").remote(5)
    first_ref = next(gen)
    first_latency = time.time() - t0
    assert rt.get(first_ref) == 0
    # First item must arrive well before the full 0.75s of generation.
    assert first_latency < 0.6, first_latency
    rest = [rt.get(r) for r in gen]
    assert rest == [1, 4, 9, 16]


def test_streaming_generator_for_loop_and_error(rt_cluster):
    @rt.remote
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("boom mid-stream")

    gen = bad_gen.options(num_returns="streaming").remote()
    values = []
    with pytest.raises(Exception, match="boom"):
        for ref in gen:
            values.append(rt.get(ref))
    assert values == [1, 2]


def test_actor_streaming_generator(rt_cluster):
    @rt.remote
    class Streamer:
        def stream(self, n):
            for i in range(n):
                yield {"i": i}

    s = Streamer.remote()
    try:
        gen = s.stream.options(num_returns="streaming").remote(4)
        out = [rt.get(r)["i"] for r in gen]
        assert out == [0, 1, 2, 3]
    finally:
        rt.kill(s)  # release the actor's CPU for later tests


def test_generator_drop_frees_items(rt_cluster):
    @rt.remote
    def gen(n):
        for i in range(n):
            yield np.zeros(1000)

    g = gen.options(num_returns="streaming").remote(10)
    next(g)
    time.sleep(1.0)  # let all items stream in
    base = _store_size()
    del g
    gc.collect()
    time.sleep(0.2)
    assert _store_size() < base, (base, _store_size())
