"""ResNet model tests (serving flagship; BASELINE.md:63 batched
ResNet-50 serving replica)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import resnet


@pytest.fixture(scope="module")
def tiny():
    cfg = resnet.ResNetConfig(depth=18, num_classes=10, width=16)
    params = resnet.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    x = np.random.default_rng(0).standard_normal((2, 32, 32, 3)).astype(
        np.float32)
    logits = resnet.forward(params, x, cfg)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_bottleneck_resnet50_builds():
    cfg = resnet.ResNetConfig(depth=50, num_classes=10, width=8)
    params = resnet.init_params(jax.random.PRNGKey(1), cfg)
    x = np.zeros((1, 32, 32, 3), np.float32)
    assert resnet.forward(params, x, cfg).shape == (1, 10)
    # ~parameter count sanity: full-width resnet50 is ~25.6M params
    full = resnet.ResNetConfig(depth=50)
    assert 24e6 < full.num_params() < 27e6


def test_bn_train_updates_running_stats(tiny):
    cfg, params = tiny
    x = np.random.default_rng(1).standard_normal((4, 32, 32, 3)).astype(
        np.float32) * 3 + 1
    logits, new_params = resnet.forward(params, x, cfg, train=True)
    assert logits.shape == (4, 10)
    before = np.asarray(params["stem"]["bn"]["mean"])
    after = np.asarray(new_params["stem"]["bn"]["mean"])
    assert not np.allclose(before, after)
    # original params untouched (functional update)
    assert np.allclose(np.asarray(params["stem"]["bn"]["mean"]), before)


def test_predictor_jit_and_grads(tiny):
    cfg, params = tiny
    predict = resnet.make_predictor(cfg, params)
    x = np.random.default_rng(2).standard_normal((2, 32, 32, 3)).astype(
        np.float32)
    out1 = np.asarray(predict(x))
    out2 = np.asarray(resnet.forward(params, x, cfg))
    # bf16 compute: jit fusion reassociates accumulations vs eager
    np.testing.assert_allclose(out1, out2, rtol=0.05, atol=0.05)

    def loss(p):
        logits, _ = resnet.forward(p, x, cfg, train=True)
        return jnp.mean((logits - 1.0) ** 2)

    g = jax.grad(loss)(params)
    gnorm = sum(float(jnp.abs(v).sum()) for v in jax.tree.leaves(g))
    assert np.isfinite(gnorm) and gnorm > 0
