"""Actor concurrency groups (reference:
``src/ray/core_worker/transport/concurrency_group_manager.h``,
``ray.method(concurrency_group=)``): named per-group thread pools so a
slow group can't starve another."""
import time

import pytest

import ray_tpu as rt


@pytest.fixture
def cg_actor(rt_cluster):
    @rt.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.log = []

        @rt.method(concurrency_group="io")
        def fetch(self, i, delay=0.0):
            if delay:
                time.sleep(delay)
            self.log.append(("io", i))
            return f"io-{i}"

        @rt.method(concurrency_group="compute")
        def crunch(self, i):
            self.log.append(("compute", i))
            return f"compute-{i}"

        def plain(self, i):
            return f"plain-{i}"

        def get_log(self):
            return list(self.log)

    yield Worker.remote()


def test_group_methods_run_and_route(cg_actor):
    a = cg_actor
    assert rt.get(a.fetch.remote(1), timeout=30) == "io-1"
    assert rt.get(a.crunch.remote(2), timeout=30) == "compute-2"
    # ungrouped methods use the actor's default executor
    assert rt.get(a.plain.remote(3), timeout=30) == "plain-3"


def test_slow_group_does_not_starve_other_group(cg_actor):
    """Two long io calls saturate the io group (2 threads); a compute
    call submitted AFTER them must still complete long before they do."""
    a = cg_actor
    t0 = time.time()
    slow = [a.fetch.remote(i, delay=4.0) for i in range(2)]
    got = rt.get(a.crunch.remote(99), timeout=30)
    compute_latency = time.time() - t0
    assert got == "compute-99"
    assert compute_latency < 3.0, compute_latency  # didn't wait for io
    assert rt.get(slow, timeout=30) == ["io-0", "io-1"]


def test_per_call_group_override(cg_actor):
    a = cg_actor
    # route an ungrouped method into the io group explicitly
    got = rt.get(a.plain.options(concurrency_group="io").remote(7),
                 timeout=30)
    assert got == "plain-7"


def test_unknown_group_errors(cg_actor):
    from ray_tpu.exceptions import TaskError

    a = cg_actor
    with pytest.raises(Exception) as ei:
        rt.get(a.plain.options(concurrency_group="nope").remote(1),
               timeout=30)
    assert "concurrency group" in str(ei.value)


def test_async_methods_respect_group_limit(rt_cluster):
    """Coroutine methods are bounded by a per-group semaphore of the
    same width as the group's thread pool."""
    @rt.remote(concurrency_groups={"serial": 1}, max_concurrency=8)
    class AsyncProbe:
        def __init__(self):
            self.active = 0
            self.max_active = 0

        @rt.method(concurrency_group="serial")
        async def step(self):
            import asyncio

            self.active += 1
            self.max_active = max(self.max_active, self.active)
            await asyncio.sleep(0.05)
            self.active -= 1
            return self.max_active

        async def peak(self):
            return self.max_active

    p = AsyncProbe.remote()
    rt.get([p.step.remote() for _ in range(6)], timeout=60)
    assert rt.get(p.peak.remote(), timeout=30) == 1


def test_group_limit_bounds_parallelism(rt_cluster):
    """A 1-thread group serializes its calls even under a burst."""
    @rt.remote(concurrency_groups={"serial": 1})
    class Probe:
        def __init__(self):
            self.active = 0
            self.max_active = 0

        @rt.method(concurrency_group="serial")
        def step(self):
            self.active += 1
            self.max_active = max(self.max_active, self.active)
            time.sleep(0.05)
            self.active -= 1
            return self.max_active

        def peak(self):
            return self.max_active

    p = Probe.remote()
    rt.get([p.step.remote() for _ in range(6)], timeout=60)
    assert rt.get(p.peak.remote(), timeout=30) == 1
