"""Core runtime: tasks, objects, errors (reference: python/ray/tests/test_basic.py)."""
import time

import numpy as np
import pytest


def test_put_get(rt_cluster):
    rt = rt_cluster
    ref = rt.put({"a": 1, "b": [1, 2, 3]})
    assert rt.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_large_object_shm(rt_cluster):
    rt = rt_cluster
    arr = np.random.rand(500_000).astype(np.float32)
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get(f.remote(1)) == 2


def test_task_fanout(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert rt.get(refs) == [i * i for i in range(50)]


def test_task_args_kwargs(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def g(a, b, c=0, d=0):
        return a + b + c + d

    assert rt.get(g.remote(1, 2, c=3, d=4)) == 10


def test_task_ref_arg(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def h(x):
        return x * 2

    ref = rt.put(21)
    assert rt.get(h.remote(ref)) == 42


def test_chained_tasks(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def inc(x):
        return x + 1

    r = inc.remote(0)
    for _ in range(5):
        r = inc.remote(r)
    assert rt.get(r) == 6


def test_large_arg_and_return(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def double(a):
        return a * 2

    arr = np.arange(300_000, dtype=np.float64)
    out = rt.get(double.remote(arr))
    np.testing.assert_array_equal(out, arr * 2)


def test_num_returns(rt_cluster):
    rt = rt_cluster

    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_error_propagation(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def boom():
        raise KeyError("missing")

    with pytest.raises(rt.exceptions.TaskError) as ei:
        rt.get(boom.remote())
    assert ei.value.cause_type == "KeyError"


def test_error_through_chain(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def boom():
        raise ValueError("first")

    @rt.remote
    def passthrough(x):
        return x

    with pytest.raises(rt.exceptions.TaskError):
        rt.get(passthrough.remote(boom.remote()))


def test_wait(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(1.0)
        return "slow"

    rs, rf = slow.remote(), fast.remote()
    ready, not_ready = rt.wait([rs, rf], num_returns=1, timeout=5)
    assert ready == [rf]
    assert not_ready == [rs]
    ready, not_ready = rt.wait([rs, rf], num_returns=2, timeout=10)
    assert len(ready) == 2


def test_get_timeout(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def hang():
        time.sleep(10)

    with pytest.raises(rt.exceptions.GetTimeoutError):
        rt.get(hang.remote(), timeout=0.3)


def test_cluster_resources(rt_cluster):
    rt = rt_cluster
    total = rt.cluster_resources()
    assert total["CPU"] == 8.0


def test_options_name(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def named():
        return 1

    assert rt.get(named.options(name="custom").remote()) == 1


def test_nested_tasks(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def inner(x):
        return x + 1

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x)) + 10

    assert rt.get(outer.remote(0)) == 11
