"""Data library: transforms, fusion over tasks, IO, splits, train feed."""
import numpy as np
import pytest


def test_range_and_transforms(rt_cluster):
    from ray_tpu import data

    ds = data.range(100, block_size=30)
    out = (ds.map(lambda r: {"id": r["id"] * 2})
             .filter(lambda r: r["id"] % 4 == 0)
             .take_all())
    assert [r["id"] for r in out] == [i * 2 for i in range(100)
                                      if (i * 2) % 4 == 0]


def test_map_batches_numpy(rt_cluster):
    from ray_tpu import data

    ds = data.range(50, block_size=20)
    out = ds.map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2},
        batch_format="numpy").take_all()
    assert len(out) == 50
    assert out[7]["sq"] == 49


def test_flat_map_limit_count(rt_cluster):
    from ray_tpu import data

    ds = data.from_items(list(range(10)))
    fm = ds.flat_map(lambda x: [x, x])
    assert fm.count() == 20
    assert fm.limit(5).take_all() == [0, 0, 1, 1, 2]


def test_batcher_exact_sizes(rt_cluster):
    from ray_tpu import data

    ds = data.range(100, block_size=33)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=16)]
    assert sizes == [16] * 6 + [4]


def test_shuffle_sort_union_zip(rt_cluster):
    from ray_tpu import data

    ds = data.range(20, block_size=7)
    sh = ds.random_shuffle(seed=0).take_all()
    assert sorted(r["id"] for r in sh) == list(range(20))
    assert [r["id"] for r in sh] != list(range(20))

    srt = ds.random_shuffle(seed=0).sort("id").take_all()
    assert [r["id"] for r in srt] == list(range(20))

    u = data.from_items([1, 2]).union(data.from_items([3])).take_all()
    assert u == [1, 2, 3]

    z = data.range(3).zip(data.range(3).map(
        lambda r: {"sq": r["id"] ** 2})).take_all()
    assert z[2] == {"id": 2, "sq": 4}


def test_groupby(rt_cluster):
    from ray_tpu import data

    ds = data.from_items([{"k": i % 3, "v": i} for i in range(9)])
    counts = ds.groupby("k").count().take_all()
    assert all(r["count()"] == 3 for r in counts)
    sums = ds.groupby("k").sum("v").take_all()
    assert sums[0]["sum(v)"] == 0 + 3 + 6


def test_actor_pool_map_batches(rt_cluster):
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    ds = data.range(40, block_size=10)

    def setup():
        return {"offset": 100}

    def fn(state, batch):
        return {"id": batch["id"] + state["offset"]}

    out = ds.map_batches(fn, fn_constructor=setup,
                         compute=ActorPoolStrategy(size=2)).take_all()
    assert sorted(r["id"] for r in out) == [i + 100 for i in range(40)]


def test_io_roundtrip(rt_cluster, tmp_path):
    from ray_tpu import data

    ds = data.from_items([{"a": i, "b": float(i) * 0.5} for i in range(10)])
    ds.write_json(str(tmp_path / "j"))
    ds.write_csv(str(tmp_path / "c"))
    ds.write_parquet(str(tmp_path / "p"))

    assert data.read_json(str(tmp_path / "j")).count() == 10
    back = data.read_csv(str(tmp_path / "c")).take_all()
    assert back[3]["a"] == 3 and back[3]["b"] == 1.5
    pq = data.read_parquet(str(tmp_path / "p")).take_all()
    assert pq[9]["a"] == 9


def test_streaming_split(rt_cluster):
    from ray_tpu import data

    ds = data.range(60, block_size=10)
    shards = ds.streaming_split(3, equal=True)
    got = [sorted(r["id"] for r in shard) for shard in shards]
    all_ids = sorted(x for g in got for x in g)
    assert all_ids == list(range(60))
    assert all(len(g) == 20 for g in got), [len(g) for g in got]


def test_dataset_feeds_trainer(rt_cluster, tmp_path):
    """DataConfig path: dataset shards → workers (reference
    ``train/_internal/data_config.py:112``)."""
    from ray_tpu import data, train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = data.range(64, block_size=8)

    def loop(config):
        shard = train.get_dataset_shard("train")
        count = 0
        for batch in shard.iter_batches(batch_size=8):
            count += len(batch["id"])
        # each of the 2 workers must see exactly half the rows
        assert count == 32, f"shard saw {count} rows"
        train.report({"count": count})

    r = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        datasets={"train": ds},
    ).fit()
    assert r.error is None, r.error
    assert r.metrics["count"] == 32
