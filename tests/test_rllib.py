"""RLlib: GAE math, PPO learning, remote runners/learners, IMPALA, ckpt."""
import numpy as np
import pytest


def _cartpole_config(**training):
    from ray_tpu.rllib import PPOConfig

    kw = dict(train_batch_size=1024, minibatch_size=256, num_epochs=6,
              lr=3e-4, entropy_coeff=0.001)
    kw.update(training)
    return (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=8,
                         rollout_fragment_length=32)
            .training(**kw)
            .debugging(seed=0))


def test_gae_simple():
    from ray_tpu.rllib import compute_gae

    # single env, 3 steps, no episode end: recursive check
    r = np.array([1.0, 1.0, 1.0], np.float32)
    v = np.array([0.5, 0.5, 0.5], np.float32)
    nv = np.array([0.5, 0.5, 0.5], np.float32)
    dones = np.zeros(3, bool)
    trunc = np.zeros(3, bool)
    adv, vtarg = compute_gae(r, v, nv, dones, trunc, [3, 1],
                             gamma=0.9, lam=1.0)
    d = 1.0 + 0.9 * 0.5 - 0.5  # per-step delta = 0.95
    exp2 = d
    exp1 = d + 0.9 * exp2
    exp0 = d + 0.9 * exp1
    assert np.allclose(adv, [exp0, exp1, exp2], atol=1e-5)
    assert np.allclose(vtarg, adv + v)


def test_gae_cuts_at_done():
    from ray_tpu.rllib import compute_gae

    r = np.ones(4, np.float32)
    v = np.zeros(4, np.float32)
    nv = np.array([0.0, 0.0, 5.0, 5.0], np.float32)
    dones = np.array([False, True, False, False])
    trunc = np.zeros(4, bool)
    nv[1] = 0.0  # terminated: runner zeros bootstrap
    adv, _ = compute_gae(r, v, nv, dones, trunc, [4, 1],
                         gamma=1.0, lam=1.0)
    # step1 ends episode: adv[1] = r = 1; adv[0] = r + adv[1] = 2
    assert adv[1] == pytest.approx(1.0)
    assert adv[0] == pytest.approx(2.0)
    # new episode from step2 unaffected by steps 0-1
    assert adv[3] == pytest.approx(1.0 + 5.0)
    assert adv[2] == pytest.approx(1.0 + 5.0 + adv[3])


def test_ppo_learns_cartpole_fast():
    """Quick learning gate (full ≥450 solve runs in bench_rl.py)."""
    algo = _cartpole_config().build()
    try:
        best = 0.0
        for _ in range(25):
            m = algo.train()
            best = max(best, m["episode_return_mean"])
        assert best >= 120, f"PPO failed to learn: best={best}"
    finally:
        algo.stop()


def test_ppo_remote_env_runners(rt_cluster):
    from ray_tpu.rllib import PPOConfig

    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=32)
           .training(train_batch_size=256, minibatch_size=128,
                     num_epochs=2)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        m1 = algo.train()
        m2 = algo.train()
        assert m2["num_env_steps_sampled_lifetime"] >= 512
        assert "episode_return_mean" in m2
    finally:
        algo.stop()


def test_ppo_remote_learners(rt_cluster):
    from ray_tpu.rllib import PPOConfig

    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                        rollout_fragment_length=32)
           .training(train_batch_size=256, minibatch_size=64, num_epochs=2)
           .debugging(seed=0))
    cfg = cfg.learners(num_learners=2)
    algo = cfg.build()
    try:
        m = algo.train()
        assert "total_loss" in m
    finally:
        algo.stop()


def test_impala_async(rt_cluster):
    from ray_tpu.rllib import IMPALAConfig

    cfg = (IMPALAConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                        rollout_fragment_length=32)
           .training(minibatch_size=128)
           .debugging(seed=0))
    algo = cfg.build()
    try:
        for _ in range(5):
            m = algo.train()
        assert m["num_env_steps_sampled_lifetime"] >= 5 * 128
        assert m["num_fragments"] >= 1
    finally:
        algo.stop()


def test_algorithm_checkpoint_roundtrip(tmp_path):
    algo = _cartpole_config().build()
    try:
        for _ in range(3):
            algo.train()
        w_before = algo.learner_group.get_weights()
        path = algo.save_to_path(str(tmp_path / "ckpt"))
        algo2 = _cartpole_config().build()
        try:
            algo2.restore_from_path(path)
            w_after = algo2.learner_group.get_weights()
            import jax

            leaves_eq = jax.tree.map(
                lambda a, b: np.allclose(a, b), w_before, w_after)
            assert all(jax.tree.leaves(leaves_eq))
            assert algo2.iteration == 3
        finally:
            algo2.stop()
    finally:
        algo.stop()


def test_algorithm_on_tune(rt_cluster, tmp_path):
    """RLlib sits on Tune (reference Algorithm(Trainable))."""
    from ray_tpu import tune
    from ray_tpu.rllib import PPO, PPOConfig
    from ray_tpu.train import RunConfig

    cfg = _cartpole_config()
    trainable = PPO.as_trainable(cfg, stop_iters=2)
    grid = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([3e-4, 1e-3])},
        tune_config=tune.TuneConfig(metric="episode_return_mean",
                                    mode="max", max_concurrent_trials=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert not grid.errors, grid.errors[0].error if grid.errors else None
    assert len(grid) == 2
    assert grid.get_best_result().metrics["training_iteration"] == 2