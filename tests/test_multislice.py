"""Multi-slice / DCN data parallelism (SURVEY §2.3 "Distributed comm
backend"): per-slice processes with their own device sets compose an
intra-slice ICI mesh with a cross-slice store (DCN) allreduce."""
import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_multislice_dryrun_two_slices():
    """Run in a fresh subprocess: the dryrun spawns its own cluster and
    per-slice processes, which must not inherit this test process's
    virtual-device config."""
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from ray_tpu.parallel.multislice import run_multislice_dryrun\n"
        "rep = run_multislice_dryrun(2, 2)\n"
        "assert len(rep['slices']) == 2\n"
        "assert all(r['agree'] for r in rep['slices'])\n"
        "cs = {round(r['checksum'], 3) for r in rep['slices']}\n"
        "assert len(cs) == 1, rep\n"
        "print('multislice ok')\n" % REPO)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=500)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "multislice ok" in r.stdout
