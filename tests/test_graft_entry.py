"""Driver-gate regression tests: run __graft_entry__ in fresh subprocesses.

Round 1 shipped a ``dryrun_multichip`` that passed CI (conftest forces the
8-CPU platform process-wide) yet failed the driver gate, which runs it in a
bare process where the vendor PJRT plugin sees one chip. These tests spawn
fresh interpreters with the *driver's* environment — no ``JAX_PLATFORMS``,
no ``XLA_FLAGS`` — so the entry points must do their own platform setup.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Each test spawns a fresh interpreter that compiles full multichip
# training steps on the vendor-default platform — several minutes per
# subprocess on a CPU-emulated box, far past the tier-1 wall-clock
# budget. Run them explicitly with -m slow (the driver gate exercises
# the same entry points).
pytestmark = pytest.mark.slow


def _driver_env():
    """Env a driver process would have: no test-harness jax overrides."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env.pop("RT_DRYRUN_REAL_DEVICES", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_dryrun_multichip_fresh_subprocess():
    code = "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"
    proc = subprocess.run(
        [sys.executable, "-c", code], env=_driver_env(), cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"dryrun_multichip(8) failed in a fresh subprocess:\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-4000:]}")
    assert "dryrun_multichip ok" in proc.stdout


def test_dryrun_multichip_after_jax_import():
    """Even if jax initialized a 1-device backend first, the dryrun recovers."""
    code = (
        "import jax; jax.devices();"
        "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=_driver_env(), cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"dryrun after jax import failed:\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr[-4000:]}")


def test_entry_compiles_single_chip():
    code = (
        "import jax; from __graft_entry__ import entry;"
        "fn, args = entry(); out = jax.jit(fn)(*args);"
        "jax.block_until_ready(out); print('entry ok', out.shape)")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=_driver_env(), cwd=REPO,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"entry() compile failed:\nstdout:\n{proc.stdout}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    assert "entry ok" in proc.stdout
