"""Backpressure policies + actor-pool autoscaling (reference:
``data/_internal/execution/backpressure_policy/``, ``execution/
autoscaler/`` — bounded in-flight work and demand-sized actor pools)."""
import numpy as np
import pytest

from ray_tpu.data import (ActorPoolStrategy, AdaptiveConcurrencyPolicy,
                          ConcurrencyCapPolicy, DataContext)


def test_concurrency_cap_policy():
    p = ConcurrencyCapPolicy(3)
    assert p.can_add_input(2)
    assert not p.can_add_input(3)


def test_adaptive_policy_aimd():
    p = AdaptiveConcurrencyPolicy(initial=4, min_cap=1, max_cap=8,
                                  target_task_s=1.0)
    assert p.cap == 4
    p.on_task_finished(0.1)   # fast → grow
    assert p.cap == 5
    p.on_task_finished(5.0)   # slow → halve
    assert p.cap == 2
    for _ in range(20):
        p.on_task_finished(0.1)
    assert p.cap == 8         # clamped at max

    q = AdaptiveConcurrencyPolicy(initial=1, min_cap=1, target_task_s=1.0)
    q.on_task_finished(99.0)
    assert q.cap == 1         # clamped at min


def test_pool_strategy_bounds():
    p = ActorPoolStrategy(min_size=1, max_size=4)
    assert p.min_size == 1 and p.max_size == 4
    fixed = ActorPoolStrategy(size=3)
    assert fixed.min_size == 3 and fixed.max_size == 3
    with pytest.raises(ValueError):
        ActorPoolStrategy(min_size=3, max_size=1)
    with pytest.raises(ValueError):
        ActorPoolStrategy(min_size=0)


def test_task_pool_respects_custom_policy(rt_cluster):
    from ray_tpu.data.executor import task_pool_stage

    class SpyPolicy(ConcurrencyCapPolicy):
        def __init__(self):
            super().__init__(2)
            self.max_seen = 0
            self.finished = 0

        def can_add_input(self, n):
            self.max_seen = max(self.max_seen, n)
            return super().can_add_input(n)

        def on_task_finished(self, duration_s):
            self.finished += 1

    import ray_tpu as rt

    spy = SpyPolicy()
    blocks = [rt.put([i]) for i in range(6)]
    out = list(task_pool_stage(iter(blocks), lambda b: [b[0] * 10],
                               backpressure=spy))
    assert [rt.get(r) for r in out] == [[i * 10] for i in range(6)]
    assert spy.max_seen <= 2       # window never exceeded the cap
    assert spy.finished == 6       # every completion reported


def test_dataset_map_with_data_context(rt_cluster):
    from ray_tpu import data as rtd

    ctx = DataContext.get_current()
    old = ctx.backpressure_policy_factory
    try:
        ctx.backpressure_policy_factory = \
            lambda: AdaptiveConcurrencyPolicy(initial=2, max_cap=4)
        ds = rtd.range(40, block_size=5).map(lambda r: {"v": r["id"] * 2})
        assert sum(r["v"] for r in ds.take_all()) == 2 * sum(range(40))
    finally:
        ctx.backpressure_policy_factory = old


def test_actor_pool_autoscales_up(rt_cluster):
    from ray_tpu import data as rtd

    pool = ActorPoolStrategy(min_size=1, max_size=3)

    def slow_echo(state, batch):
        import time

        time.sleep(0.15)  # real backlog: tasks outlive dispatch
        return batch

    ds = rtd.range(64, block_size=4).map_batches(
        slow_echo,
        compute=pool,
        fn_constructor=lambda: {},
        batch_format="numpy")
    assert len(ds.take_all()) == 64
    # 16 slow blocks at in-flight cap 2/actor must force growth past 1.
    assert pool.peak_size > 1
    assert pool.peak_size <= 3


def test_actor_pool_fixed_size_does_not_scale(rt_cluster):
    from ray_tpu import data as rtd

    pool = ActorPoolStrategy(size=2)
    ds = rtd.range(32, block_size=4).map_batches(
        lambda state, batch: batch,
        compute=pool,
        fn_constructor=lambda: {},
        batch_format="numpy")
    assert len(ds.take_all()) == 32
    assert pool.peak_size == 2