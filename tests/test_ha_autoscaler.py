"""Head persistence/HA, autoscaler, usage stats.

Mirrors the reference's coverage (GCS fault-tolerance tests over Redis
restarts, ``autoscaler/v2/tests``, ``test_usage_stats.py``): durable
control-plane state survives a head restart, demand scales nodes up and
idleness scales them down, and the usage report is local-only.
"""
import json
import os
import time

import pytest

import ray_tpu as rt_mod
from ray_tpu._private import usage_stats


def test_head_state_snapshot_restore(tmp_path):
    """KV + named-actor metadata + jobs survive a head restart on the
    same session dir (GCS+Redis restart analogue)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    from ray_tpu.api import _HeadThread
    from ray_tpu._private.config import Config

    session = str(tmp_path / "session")
    os.makedirs(session)
    ht = _HeadThread(session, Config({}), {"CPU": 4.0}).start()
    rt.init(address=ht.head.sock_path)

    @rt.remote
    class Named:
        def ping(self):
            return 1

    Named.options(name="survivor").remote()
    core = __import__("ray_tpu.core.worker",
                      fromlist=["CoreWorker"]).CoreWorker.current()
    core.kv_put("durable_key", b"durable_value", ns="app")
    time.sleep(0.5)
    rt.shutdown()
    ht.stop()  # head persists its state on stop
    assert os.path.exists(os.path.join(session, "head_state.pkl"))

    # Second head on the SAME session dir adopts the state.
    ht2 = _HeadThread(session, Config({}), {"CPU": 4.0}).start()
    rt.init(address=ht2.head.sock_path)
    try:
        core2 = __import__("ray_tpu.core.worker",
                           fromlist=["CoreWorker"]).CoreWorker.current()
        assert core2.kv_get("durable_key", ns="app") == b"durable_value"
        actors = rt.state("actors")
        survivor = [a for a in actors if a["name"] == "survivor"]
        # Live-at-snapshot actors restore as RESTARTING (the reconnect
        # grace window — workers that survived a head crash reattach);
        # with its process gone, the reconcile pass marks it DEAD after
        # the grace expires. Either state is the correct record here.
        assert survivor and survivor[0]["state"] in ("RESTARTING", "DEAD")
        if survivor[0]["state"] == "DEAD":
            assert "reconnect" in survivor[0]["death_cause"] or \
                "head restart" in survivor[0]["death_cause"]
    finally:
        rt.shutdown()
        ht2.stop()


def test_autoscaler_scales_up_and_down(tmp_path):
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 0.0},
                      system_config={"worker_lease_timeout_s": 60.0})
    rt = cluster.connect()
    provider = LocalNodeProvider(cluster)
    scaler = Autoscaler(provider, node_resources={"CPU": 2.0},
                        min_nodes=0, max_nodes=2, idle_timeout_s=4.0,
                        poll_period_s=0.5).start()
    try:
        @rt.remote
        def work(x):
            time.sleep(0.3)
            return x

        # 0 CPUs in the cluster → demand queues → scaler must add nodes.
        refs = [work.remote(i) for i in range(6)]
        assert rt.get(refs, timeout=90) == list(range(6))
        assert len(provider.non_terminated_nodes()) >= 1
        assert any("scale-up" in e for e in scaler.events)

        # Idle long enough → scale back down to min_nodes.
        deadline = time.time() + 60
        while time.time() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(1.0)
        assert not provider.non_terminated_nodes(), scaler.events
        assert any("scale-down" in e for e in scaler.events)
    finally:
        scaler.stop()
        cluster.shutdown()


def test_usage_stats_local_only(tmp_path):
    usage_stats.record_feature("unit_test_feature")
    rep = usage_stats.report()
    assert rep["features"]["unit_test_feature"] >= 1
    path = usage_stats.write_report(str(tmp_path))
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["schema_version"] == 1

    os.environ["RT_USAGE_STATS_DISABLED"] = "1"
    try:
        assert usage_stats.write_report(str(tmp_path / "nope")) == ""
    finally:
        del os.environ["RT_USAGE_STATS_DISABLED"]
