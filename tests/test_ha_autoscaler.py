"""Head persistence/HA, autoscaler, usage stats.

Mirrors the reference's coverage (GCS fault-tolerance tests over Redis
restarts, ``autoscaler/v2/tests``, ``test_usage_stats.py``): durable
control-plane state survives a head restart, demand scales nodes up and
idleness scales them down, and the usage report is local-only.
"""
import json
import os
import time

import pytest

import ray_tpu as rt_mod
from ray_tpu._private import usage_stats


def test_head_state_snapshot_restore(tmp_path):
    """KV + named-actor metadata + jobs survive a head restart on the
    same session dir (GCS+Redis restart analogue)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    from ray_tpu.api import _HeadThread
    from ray_tpu._private.config import Config

    session = str(tmp_path / "session")
    os.makedirs(session)
    ht = _HeadThread(session, Config({}), {"CPU": 4.0}).start()
    rt.init(address=ht.head.sock_path)

    @rt.remote
    class Named:
        def ping(self):
            return 1

    Named.options(name="survivor").remote()
    core = __import__("ray_tpu.core.worker",
                      fromlist=["CoreWorker"]).CoreWorker.current()
    core.kv_put("durable_key", b"durable_value", ns="app")
    time.sleep(0.5)
    rt.shutdown()
    ht.stop()  # head persists its state on stop
    assert os.path.exists(os.path.join(session, "head_state.pkl"))

    # Second head on the SAME session dir adopts the state.
    ht2 = _HeadThread(session, Config({}), {"CPU": 4.0}).start()
    rt.init(address=ht2.head.sock_path)
    try:
        core2 = __import__("ray_tpu.core.worker",
                           fromlist=["CoreWorker"]).CoreWorker.current()
        assert core2.kv_get("durable_key", ns="app") == b"durable_value"
        actors = rt.state("actors")
        survivor = [a for a in actors if a["name"] == "survivor"]
        # Live-at-snapshot actors restore as RESTARTING (the reconnect
        # grace window — workers that survived a head crash reattach);
        # with its process gone, the reconcile pass marks it DEAD after
        # the grace expires. Either state is the correct record here.
        assert survivor and survivor[0]["state"] in ("RESTARTING", "DEAD")
        if survivor[0]["state"] == "DEAD":
            assert "reconnect" in survivor[0]["death_cause"] or \
                "head restart" in survivor[0]["death_cause"]
    finally:
        rt.shutdown()
        ht2.stop()


def test_autoscaler_scales_up_and_down(tmp_path):
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 0.0},
                      system_config={"worker_lease_timeout_s": 60.0})
    rt = cluster.connect()
    provider = LocalNodeProvider(cluster)
    scaler = Autoscaler(provider, node_resources={"CPU": 2.0},
                        min_nodes=0, max_nodes=2, idle_timeout_s=4.0,
                        poll_period_s=0.5).start()
    try:
        @rt.remote
        def work(x):
            time.sleep(0.3)
            return x

        # 0 CPUs in the cluster → demand queues → scaler must add nodes.
        refs = [work.remote(i) for i in range(6)]
        assert rt.get(refs, timeout=90) == list(range(6))
        assert len(provider.non_terminated_nodes()) >= 1
        assert any("scale-up" in e for e in scaler.events)

        # Idle long enough → scale back down to min_nodes. Wait on the
        # EVENT: terminate pops the provider's list before the blocking
        # node removal returns, so node emptiness races the record.
        deadline = time.time() + 90
        while time.time() < deadline:
            if any("scale-down" in e for e in scaler.events):
                break
            time.sleep(1.0)
        assert not provider.non_terminated_nodes(), scaler.events
        assert any("scale-down" in e for e in scaler.events), \
            scaler.events
    finally:
        scaler.stop()
        cluster.shutdown()


def test_tpu_slice_provider_gang_scale(tmp_path):
    """TPU demand launches a WHOLE slice (2 hosts for v5e-8), CPU demand
    launches nothing, and an idle slice retires atomically."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    from ray_tpu.autoscaler import Autoscaler, TPUSliceProvider
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_resources={"CPU": 4.0},
                      system_config={"worker_lease_timeout_s": 120.0})
    rt = cluster.connect()
    provider = TPUSliceProvider(cluster, pod_type="v5e-8")
    assert provider.hosts_per_slice == 2 and provider.chips_per_host == 4
    # Capacity-aware demand: 2 pending x TPU:4 = 8 chips = ONE v5e-8
    # slice, not one slice per pending task.
    assert provider.slices_needed(
        {"pending_resource_shapes": [{"TPU": 4.0}, {"TPU": 4.0}]}) == 1
    scaler = Autoscaler(provider, min_nodes=0, max_nodes=2,
                        idle_timeout_s=3.0, poll_period_s=0.5,
                        demand_fn=provider.slices_needed).start()
    try:
        @rt.remote
        def cpu_work():
            return "cpu"

        # CPU-only demand fits the head and must NOT launch a slice.
        assert rt.get(cpu_work.remote(), timeout=30) == "cpu"
        time.sleep(1.5)
        assert provider.non_terminated_nodes() == []

        @rt.remote(resources={"TPU": 4.0})
        def tpu_work():
            return "tpu"

        refs = [tpu_work.remote() for _ in range(2)]
        assert rt.get(refs, timeout=120) == ["tpu", "tpu"]
        slices = provider.non_terminated_nodes()
        assert len(slices) == 1
        assert len(provider.member_nodes(slices[0])) == 2
        # Host 0 of the slice carries the gang anchor, host 1 does not.
        anchored = [n for n in rt.state("nodes")
                    if "TPU-v5e-8-head" in n["total"]]
        assert len(anchored) == 1

        # Idle past the timeout → the whole gang retires together
        # (wait on the event; see the comment in the test above).
        deadline = time.time() + 90
        while time.time() < deadline:
            if any("scale-down" in e for e in scaler.events):
                break
            time.sleep(1.0)
        assert not provider.non_terminated_nodes(), scaler.events
        assert any("scale-down" in e for e in scaler.events), \
            scaler.events
    finally:
        scaler.stop()
        cluster.shutdown()


def test_usage_stats_local_only(tmp_path):
    usage_stats.record_feature("unit_test_feature")
    rep = usage_stats.report()
    assert rep["features"]["unit_test_feature"] >= 1
    path = usage_stats.write_report(str(tmp_path))
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["schema_version"] == 1

    os.environ["RT_USAGE_STATS_DISABLED"] = "1"
    try:
        assert usage_stats.write_report(str(tmp_path / "nope")) == ""
    finally:
        del os.environ["RT_USAGE_STATS_DISABLED"]
