"""Pipeline parallelism (pp) + expert parallelism (ep/MoE).

Mirrors the reference's multi-worker parallel-training coverage
(``python/ray/train/tests``): numerical parity against the single-device
path on a virtual 8-CPU mesh, plus a full sharded train step.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import gpt
from ray_tpu.parallel import create_mesh
from ray_tpu.parallel import sharding as shr


@pytest.fixture(scope="module")
def nano4():
    return dataclasses.replace(gpt.CONFIGS["nano"], n_layer=4,
                               remat="none", attn_backend="xla")


def test_pipeline_forward_parity(nano4, cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "pp": 4})
    cfg_pp = dataclasses.replace(nano4, pp_axis="pp", num_microbatches=4)
    params = gpt.init_params(jax.random.PRNGKey(0), nano4)
    tokens = jnp.asarray(
        np.random.randint(0, nano4.vocab_size, (8, 16), np.int32))

    ref = gpt.forward(params, tokens, nano4)
    params_sh = shr.shard_tree(
        params, shr.tree_shardings(params, mesh, shr.PP_LM_RULES))
    tok_sh = jax.device_put(tokens, shr.batch_sharding(mesh))
    out = jax.jit(lambda p, t: gpt.forward(p, t, cfg_pp, mesh))(
        params_sh, tok_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_pipeline_train_step(nano4, cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "pp": 4})
    cfg_pp = dataclasses.replace(nano4, pp_axis="pp", num_microbatches=2)
    init, step, _, batch_sh = gpt.make_train_step(cfg_pp, mesh)
    state = init(jax.random.PRNGKey(0))
    batch = {"tokens": jax.device_put(
        np.random.randint(0, cfg_pp.vocab_size, (8, 17), np.int32),
        batch_sh)}
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # pipeline gradients actually descend


def test_pipeline_requires_tp_aware_block(nano4):
    """A tp mesh without tp_axis/param_specs is an error, not silent
    wrong math (the plain block has no tp collectives)."""
    mesh = create_mesh({"tp": 2, "pp": 4})
    from ray_tpu.parallel.pipeline import pipeline_apply

    with pytest.raises(ValueError, match="tp"):
        pipeline_apply(lambda a, p: a, {}, jnp.zeros((4, 8, 16)),
                       mesh=mesh)


def test_pipeline_rejects_sp_mesh(nano4):
    mesh = create_mesh({"sp": 2, "pp": 4})
    from ray_tpu.parallel.pipeline import pipeline_apply

    with pytest.raises(ValueError, match="sp"):
        pipeline_apply(lambda a, p: a, {}, jnp.zeros((4, 8, 16)),
                       mesh=mesh)


def test_pipeline_tp_forward_parity(nano4, cpu_mesh_devices):
    """pp x tp (Megatron-in-stage) matches the single-device forward."""
    mesh = create_mesh({"dp": 2, "pp": 2, "tp": 2})
    cfg_pt = dataclasses.replace(nano4, pp_axis="pp", num_microbatches=2)
    params = gpt.init_params(jax.random.PRNGKey(0), nano4)
    tokens = jnp.asarray(
        np.random.randint(0, nano4.vocab_size, (8, 16), np.int32))

    ref = gpt.forward(params, tokens, nano4)
    out = jax.jit(lambda p, t: gpt.forward(p, t, cfg_pt, mesh))(
        params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_pipeline_tp_train_step(nano4, cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "pp": 2, "tp": 2})
    cfg_pt = dataclasses.replace(nano4, pp_axis="pp", num_microbatches=2)
    init, step, _, batch_sh = gpt.make_train_step(cfg_pt, mesh)
    state = init(jax.random.PRNGKey(0))
    batch = {"tokens": jax.device_put(
        np.random.randint(0, cfg_pt.vocab_size, (8, 17), np.int32),
        batch_sh)}
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_moe_forward_parity(nano4, cpu_mesh_devices):
    cfg = dataclasses.replace(nano4, n_experts=4, expert_top_k=2)
    mesh = create_mesh({"dp": 2, "ep": 4})
    params = gpt.init_params(jax.random.PRNGKey(1), cfg)
    tokens = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (8, 16), np.int32))

    ref = gpt.forward(params, tokens, cfg)
    params_sh = shr.shard_tree(
        params, shr.tree_shardings(params, mesh, shr.LM_RULES))
    tok_sh = jax.device_put(tokens, shr.batch_sharding(mesh))
    out = jax.jit(lambda p, t: gpt.forward(p, t, cfg, mesh))(
        params_sh, tok_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_moe_train_step_learns(nano4, cpu_mesh_devices):
    cfg = dataclasses.replace(nano4, n_experts=4, expert_top_k=2)
    mesh = create_mesh({"dp": 2, "ep": 4})
    init, step, _, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    batch = {"tokens": jax.device_put(
        np.random.randint(0, cfg.vocab_size, (16, 17), np.int32),
        batch_sh)}
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    assert float(metrics["moe_aux"]) > 0


def test_moe_capacity_drops_overflow():
    from ray_tpu.models.moe import capacity, top_k_gating

    T, E = 64, 4
    cap = capacity(T, E, 1, 0.25)  # deliberately tight
    probs = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (T, 1))
    dispatch, combine, aux = top_k_gating(probs, 1, cap)
    # Expert 0 receives exactly `cap` tokens; the rest are dropped.
    assert int(dispatch[:, 0].sum()) == cap
    assert float(aux) > 1.0  # imbalance shows in the load-balance loss
