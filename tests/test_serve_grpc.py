"""Serve gRPC ingress (reference: ``serve/_private/proxy.py:534``
``gRPCProxy``): unary and server-streaming calls route to deployments by
application metadata, sharing the proxy actor with HTTP."""
import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def grpc_serve(rt_cluster):
    from ray_tpu.serve import api as serve_api

    serve.start(http_options={"host": "127.0.0.1", "port": 0},
                grpc_options={"host": "127.0.0.1", "port": 0})
    port = serve_api._client["http"]["grpc_port"]
    yield port
    serve.shutdown()


def test_grpc_unary(grpc_serve):
    import grpc

    @serve.deployment
    class Echo:
        def __call__(self, req):
            # raw request bytes + the called method in headers
            return b"echo:" + req.body + b"@" + \
                req.headers["grpc-method"].encode()

    serve.run(Echo.bind(), name="echoapp", route_prefix="/echoapp")

    chan = grpc.insecure_channel(f"127.0.0.1:{grpc_serve}")
    call = chan.unary_unary("/userns.Svc/Predict")
    out = call(b"hello", metadata=(("application", "echoapp"),),
               timeout=30)
    assert out == b"echo:hello@/userns.Svc/Predict"

    # Path-segment routing works without metadata too.
    out2 = chan.unary_unary("/echoapp/Predict")(b"x", timeout=30)
    assert out2.startswith(b"echo:x@")
    chan.close()
    serve.delete("echoapp")


def test_grpc_unknown_app_unimplemented(grpc_serve):
    import grpc

    chan = grpc.insecure_channel(f"127.0.0.1:{grpc_serve}")
    with pytest.raises(grpc.RpcError) as ei:
        chan.unary_unary("/nope.Svc/Call")(
            b"", metadata=(("application", "ghost"),), timeout=10)
    assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
    chan.close()


def test_grpc_server_streaming(grpc_serve):
    import grpc

    @serve.deployment
    class Tokens:
        def __call__(self, req):
            n = int(req.body or b"0")
            for i in range(n):
                yield f"tok{i}"

    serve.run(Tokens.bind(), name="tokapp", route_prefix="/tokapp")

    chan = grpc.insecure_channel(f"127.0.0.1:{grpc_serve}")
    stream = chan.unary_stream("/tokapp/Generate")
    items = list(stream(b"3", metadata=(("application", "tokapp"),),
                        timeout=60))
    assert items == [b"tok0", b"tok1", b"tok2"]
    chan.close()
    serve.delete("tokapp")


def test_response_encode_tuple_order():
    """Response.encode() returns (status, content_type, body) — a swap
    here sent the mime string as the payload on both ingresses."""
    from ray_tpu.serve.request import Response

    status, ctype, body = Response(body=b"abc").encode()
    assert status == 200
    assert ctype == "application/octet-stream"
    assert body == b"abc"
    status, ctype, body = Response(body={"a": 1}, status=201).encode()
    assert (status, ctype) == (201, "application/json")
    assert body == b'{"a": 1}'


def test_grpc_enable_after_proxy_started(rt_cluster):
    """serve.start(grpc_options=...) after the proxy already exists must
    bind the gRPC ingress on it, not silently no-op."""
    import grpc

    from ray_tpu.serve import api as serve_api

    serve.start(http_options={"host": "127.0.0.1", "port": 0})
    try:
        assert "grpc_port" not in serve_api._client["http"]

        @serve.deployment
        class Late:
            def __call__(self, req):
                return b"late-ok"

        serve.run(Late.bind(), name="lateapp", route_prefix="/lateapp")
        serve.start(grpc_options={"host": "127.0.0.1", "port": 0})
        port = serve_api._client["http"]["grpc_port"]
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        out = chan.unary_unary("/lateapp/Call")(b"", timeout=30)
        assert out == b"late-ok"
        chan.close()
        serve.delete("lateapp")
    finally:
        serve.shutdown()


def test_grpc_error_surfaces_as_internal(grpc_serve):
    import grpc

    @serve.deployment
    class Boom:
        def __call__(self, req):
            raise RuntimeError("kaput")

    serve.run(Boom.bind(), name="boomapp", route_prefix="/boomapp")
    chan = grpc.insecure_channel(f"127.0.0.1:{grpc_serve}")
    with pytest.raises(grpc.RpcError) as ei:
        chan.unary_unary("/boomapp/Call")(
            b"", metadata=(("application", "boomapp"),), timeout=30)
    assert ei.value.code() == grpc.StatusCode.INTERNAL
    assert "kaput" in ei.value.details()
    chan.close()
    serve.delete("boomapp")
