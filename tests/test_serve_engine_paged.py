"""Paged KV cache + shared-prefix reuse (ISSUE 6): paged engines must be
token-identical to flat (temp 0 AND seeded temp > 0), COW prefix sharing
must survive frees of the sharing lanes, page exhaustion must be a
defined backpressure path (defer / park / preempt-by-recompute — never a
corrupting write), the compiled-program set must stay at
``len(prompt_buckets) + 1`` across admission storms WITH prefix hits,
and the shutdown path must fail queued lanes unconditionally."""
import threading
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _make(nano, nano_params, **kw):
    from ray_tpu.serve.engine import DecodeEngine

    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    return DecodeEngine(nano_params, nano, **kw)


def _drain_concurrent(eng, prompts, max_news, seeds=None):
    outs = {}

    def consume(i):
        kw = {"seed": seeds[i]} if seeds else {}
        outs[i] = np.concatenate(
            list(eng.stream(prompts[i], max_news[i], **kw)))

    threads = [threading.Thread(target=consume, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs


def test_paged_flat_token_identity_greedy(nano, nano_params):
    """Mixed prompt/output lengths through a starv-able 2-slot pool:
    every paged stream is bit-identical to the flat engine's (which is
    itself pinned to generate_chunked)."""
    flat = _make(nano, nano_params)
    paged = _make(nano, nano_params, paged=True, page_size=8,
                  prefix_cache=False)
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, nano.vocab_size, (n,)).astype(np.int32)
                   for n in (5, 8, 11, 16)]
        max_news = [10, 7, 12, 3]
        of = _drain_concurrent(flat, prompts, max_news)
        op = _drain_concurrent(paged, prompts, max_news)
        for i in range(4):
            assert (of[i] == op[i]).all(), (i, of[i], op[i])
        st = paged.stats()
        assert st["paged"] and st["completed"] == 4
        assert st["pages_free"] == st["n_pages"]  # all recycled
    finally:
        flat.shutdown()
        paged.shutdown()


def test_paged_flat_token_identity_temperature(nano, nano_params):
    """Seeded sampling: the paged engine reproduces the flat engine's
    per-slot PRNG chains exactly — same seeds, same tokens; different
    seed diverges."""
    flat = _make(nano, nano_params, temperature=1.0)
    paged = _make(nano, nano_params, temperature=1.0, paged=True,
                  page_size=8, prefix_cache=False)
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, nano.vocab_size, (n,)).astype(np.int32)
                   for n in (8, 11, 16)]
        max_news = [8, 10, 6]
        seeds = [7, 11, 13]
        of = _drain_concurrent(flat, prompts, max_news, seeds)
        op = _drain_concurrent(paged, prompts, max_news, seeds)
        for i in range(3):
            assert (of[i] == op[i]).all(), (i, of[i], op[i])
        other = np.concatenate(list(paged.stream(prompts[0], 8, seed=8)))
        assert not (other == op[0]).all()
    finally:
        flat.shutdown()
        paged.shutdown()


def test_paged_prefix_hit_and_cow(nano, nano_params):
    """Shared system prompt: a page-aligned hit maps cached pages
    directly, an exact-repeat hit ends mid-page and forks the partial
    page copy-on-write. Freeing / abandoning one sharer must not
    corrupt the others, and a post-free rerun still hits the cache."""
    rng = np.random.default_rng(2)
    sysp = rng.integers(0, nano.vocab_size, (16,)).astype(np.int32)
    a = np.concatenate([sysp, rng.integers(0, nano.vocab_size,
                                           (4,)).astype(np.int32)])
    b = np.concatenate([sysp, rng.integers(0, nano.vocab_size,
                                           (4,)).astype(np.int32)])
    buckets = (8, 16, 32)
    ref = _make(nano, nano_params, prompt_buckets=buckets, paged=True,
                page_size=8, prefix_cache=False)
    try:
        ra = np.concatenate(list(ref.stream(a, 8)))
        rb = np.concatenate(list(ref.stream(b, 8)))
    finally:
        ref.shutdown()

    eng = _make(nano, nano_params, slots=3, prompt_buckets=buckets,
                paged=True, page_size=8, prefix_cache=True)
    try:
        # Cold run seeds the cache (entries at page bounds 8/16 + n=20).
        oa = np.concatenate(list(eng.stream(a, 8)))
        assert (oa == ra).all()
        assert eng.stats()["prefix_hits"] == 0
        # b: page-aligned hit on sysp (16 tokens, 2 full pages).
        # a again: exact-length hit (20 tokens) -> COW fork of the
        # partial page. Concurrent, so they also share live.
        outs = _drain_concurrent(eng, [b, a], [8, 8])
        assert (outs[0] == rb).all(), (outs[0], rb)
        assert (outs[1] == ra).all(), (outs[1], ra)
        st = eng.stats()
        assert st["prefix_hits"] >= 2
        assert st["cow_copies"] >= 1
        assert st["prefix_tokens_reused"] >= 16 + 19
        # Abandon a sharer mid-stream: its pages free at the boundary;
        # the cached prefix must stay intact for the next hit.
        it = eng.stream(b, 40)
        next(it)
        it.close()
        deadline = time.time() + 2
        while eng.stats()["active_slots"] and time.time() < deadline:
            time.sleep(0.01)
        ob = np.concatenate(list(eng.stream(b, 8)))
        assert (ob == rb).all(), (ob, rb)
        assert eng.stats()["pages_free"] > 0
    finally:
        eng.shutdown()


def test_paged_admission_defers_on_page_exhaustion(nano, nano_params):
    """A pool holding exactly ONE max-length sequence: the second
    admission must defer (FIFO kept) until the first lane frees its
    pages — and both streams stay correct, proving no lane ever read or
    wrote another lane's pages."""
    ref = _make(nano, nano_params, prompt_buckets=(16,), paged=True,
                page_size=8, prefix_cache=False)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, nano.vocab_size, (16,)).astype(np.int32)
               for _ in range(2)]
    try:
        refs = [np.concatenate(list(ref.stream(p, 40))) for p in prompts]
    finally:
        ref.shutdown()
    # max_len=64, ps=8 -> max_pages=8 == n_pages: one sequence's worth.
    eng = _make(nano, nano_params, prompt_buckets=(16,), paged=True,
                page_size=8, n_pages=8, prefix_cache=False)
    try:
        outs = _drain_concurrent(eng, prompts, [40, 40])
        st = eng.stats()
        assert st["admissions_deferred"] >= 1, st
        assert st["completed"] == 2
        for i in range(2):
            assert (outs[i] == refs[i]).all(), i
        assert st["pages_free"] == 8
    finally:
        eng.shutdown()


def test_paged_parking_and_recompute_preemption(nano, nano_params):
    """A starved pool under 6 concurrent long generations: lanes park
    when the allocator runs dry and, on full deadlock, the youngest is
    preempted BY RECOMPUTE (requeued, replayed, delivered tokens
    suppressed) — every stream still completes token-identical, at
    temp 0 and seeded temp > 0."""
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, nano.vocab_size, (16,)).astype(np.int32)
               for _ in range(6)]
    mns = [24, 20, 28, 16, 24, 20]
    seeds = list(range(6))
    for temp in (0.0, 0.9):
        ref = _make(nano, nano_params, slots=4, prompt_buckets=(16,),
                    temperature=temp, paged=True, page_size=8,
                    prefix_cache=False)
        try:
            refs = [np.concatenate(list(ref.stream(p, m, seed=s)))
                    for p, m, s in zip(prompts, mns, seeds)]
        finally:
            ref.shutdown()
        eng = _make(nano, nano_params, slots=4, prompt_buckets=(16,),
                    temperature=temp, paged=True, page_size=8,
                    n_pages=11, prefix_cache=False)
        try:
            outs = _drain_concurrent(eng, prompts, mns, seeds)
            st = eng.stats()
            assert st["completed"] == 6 and st["admitted"] == 6
            assert st["lane_parks"] > 0 or \
                st["admissions_deferred"] > 0, st
            for i in range(6):
                assert (outs[i] == refs[i]).all(), (temp, i)
            assert st["pages_free"] == 11    # everything recycled
        finally:
            eng.shutdown()


def test_paged_dead_parked_lane_is_culled(nano, nano_params):
    """A parked lane whose consumer walks away must be culled at the
    next chunk boundary — pages freed while it sits OUT of the dispatch
    mask (the post-dispatch closed/deadline checks never see it) — and
    must never pin its pages or force recompute-preemption of the
    healthy lane."""
    p = (np.arange(1, 17, dtype=np.int32) * 2) % nano.vocab_size
    q = (np.arange(1, 17, dtype=np.int32) * 3) % nano.vocab_size
    ref = _make(nano, nano_params, max_len=128)
    try:
        want = np.concatenate(list(ref.stream(p, 100)))
    finally:
        ref.shutdown()
    # ps=64: one page covers pos 0..63, so when the lanes cross pos 64
    # the 3-page pool runs dry — the lane that grabs the third page
    # runs on for ~25 boundaries while the other stays parked.
    eng = _make(nano, nano_params, max_len=128, paged=True,
                page_size=64, n_pages=3, prefix_cache=False)
    try:
        s0 = eng.stream(p, 100)
        s1 = eng.stream(q, 100)
        out0 = {}

        def consume():
            out0["t"] = np.concatenate(list(s0))

        t = threading.Thread(target=consume)
        t.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            if eng.stats()["parked_slots"] >= 1:
                break
            time.sleep(0.001)
        else:
            pytest.fail("no lane ever parked")
        s1.close()
        # The dead lane's page must come back while the healthy lane is
        # still mid-generation — waiting for lane 0 to finish first
        # would also free pages, which is exactly the bug.
        while time.time() < deadline:
            st = eng.stats()
            if st["pages_free"] >= 1 and st["completed"] == 0:
                break
            assert st["completed"] == 0, \
                "healthy lane finished before the dead parked lane " \
                "was culled"
            time.sleep(0.001)
        t.join(60)
        st = eng.stats()
        assert (out0["t"] == want).all()
        assert st["abandoned"] >= 1 and st["preempted"] == 0, st
        assert st["pages_free"] == 3, st
    finally:
        eng.shutdown()


def test_paged_recompile_guard_with_prefix_hits(nano, nano_params):
    """The paged compiled-program set is exactly
    ``len(prompt_buckets) + 1`` — prefix-hit admissions (traced
    hist_len, COW, arbitrary page tables) and page-pressure replays add
    ZERO programs across a mixed-shape storm. page_size=16 is unique to
    this test, so the (process-wide, lru-shared) jit wrappers count
    ONLY this pool configuration's programs."""
    from ray_tpu.models.gpt_decode import (jit_decode_chunk_slots_paged,
                                           jit_prefill_into_slot_paged)

    eng = _make(nano, nano_params, slots=3, max_len=48,
                prompt_buckets=(8, 16, 32), paged=True, page_size=16,
                prefix_cache=True)
    try:
        rng = np.random.default_rng(5)
        sysp = rng.integers(0, nano.vocab_size, (16,)).astype(np.int32)
        fixed_tail = rng.integers(0, nano.vocab_size,
                                  (4,)).astype(np.int32)

        def storm(n, lens, shared_every=3):
            threads = []
            for i in range(n):
                if i % shared_every == 0:
                    # Alternate an exact-repeat prompt (COW fork) with
                    # fresh tails (page-aligned hit on the 16-token
                    # system-prompt boundary).
                    tail = fixed_tail if i % (2 * shared_every) == 0 \
                        else rng.integers(0, nano.vocab_size,
                                          (4,)).astype(np.int32)
                    p = np.concatenate([sysp, tail])
                else:
                    p = rng.integers(0, nano.vocab_size,
                                     (int(lens[i % len(lens)]),)
                                     ).astype(np.int32)
                mn = int(rng.integers(1, 12))
                t = threading.Thread(
                    target=lambda p=p, mn=mn: list(eng.stream(p, mn)))
                t.start()
                threads.append(t)
                if i % 3 == 0:
                    time.sleep(0.01)  # stagger: mid-stream admissions
            for t in threads:
                t.join()

        # Warm: cold 20-token shared prompt (bucket 32), plain 5/16
        # (buckets 8/16), then shared repeats (suffix bucket 8).
        storm(7, [5, 16])
        pre_prefill = eng._prefill._cache_size()
        pre_step = eng._step._cache_size()
        assert pre_prefill == len(eng.prompt_buckets)
        assert pre_step == 1
        storm(14, [1, 3, 7, 8, 9, 12, 15, 16])
        assert eng._prefill._cache_size() == pre_prefill
        assert eng._step._cache_size() == pre_step
        st = eng.stats()
        assert st["prefix_hits"] >= 2 and st["cow_copies"] >= 1
        # lru wrappers shared per static-knob tuple across engines
        assert jit_prefill_into_slot_paged(nano, 16, 0.0, "fp") \
            is eng._prefill
        assert jit_decode_chunk_slots_paged(
            nano, 4, 16, 0.0, -1, "fp", "gather") is eng._step
    finally:
        eng.shutdown()


def test_engine_shutdown_fails_queued_lanes(nano, nano_params):
    """Satellite: shutdown() must fail queued/in-flight lanes with
    EngineShutdownError even when the driver never started
    (auto_start=False) or died before processing them — previously
    those streams hung forever."""
    from ray_tpu.serve.batching import _drain_stream
    from ray_tpu.serve.engine import EngineShutdownError

    prompt = np.arange(8, dtype=np.int32) % nano.vocab_size
    # Never-started driver: submissions queue for start()...
    eng = _make(nano, nano_params, auto_start=False)
    lanes = [eng.submit(prompt, 8) for _ in range(3)]
    # ...but shutdown() without start() must drain and fail them all.
    eng.shutdown()
    for lane in lanes:
        with pytest.raises(EngineShutdownError):
            list(_drain_stream(lane))
    with pytest.raises(EngineShutdownError):
        eng.submit(prompt, 8)

    # start() after submit works (the queued-before-start contract).
    eng2 = _make(nano, nano_params, auto_start=False)
    lane = eng2.submit(prompt, 4)
    eng2.start()
    try:
        from ray_tpu.models import gpt_decode

        ref = np.concatenate([s[0] for s in gpt_decode.generate_chunked(
            nano_params, np.asarray(prompt)[None], nano, 4, chunk=4,
            max_len=64)])
        out = np.concatenate(list(_drain_stream(lane)))
        assert (out == ref).all()
    finally:
        eng2.shutdown()


def test_ensure_paging_and_decorator_knobs(nano, nano_params):
    """Config plumbing: ensure_paging repages an idle flat engine (and
    validates instead of repaging a used one); the decorator rejects
    paged knobs without continuous=True."""
    from ray_tpu import serve

    eng = _make(nano, nano_params)
    try:
        assert not eng.paged
        eng.ensure_paging(page_size=8, prefix_cache=True)
        assert eng.paged and eng.page_size == 8
        assert eng._prefix is not None
        eng.ensure_paging(page_size=8)          # idempotent no-op
        eng.ensure_paging(prefix_cache=False)   # host-side toggle
        assert eng._prefix is None
        prompt = np.arange(8, dtype=np.int32) % nano.vocab_size
        ref = _make(nano, nano_params)
        try:
            want = np.concatenate(list(ref.stream(prompt, 6)))
        finally:
            ref.shutdown()
        got = np.concatenate(list(eng.stream(prompt, 6)))
        assert (got == want).all()
        with pytest.raises(ValueError, match="live engine"):
            eng.ensure_paging(page_size=16)
    finally:
        eng.shutdown()

    with pytest.raises(ValueError, match="continuous=True"):
        @serve.batch(page_size=8)
        def bad(items):
            return items


def test_deployment_schema_engine_block():
    """Schema plumbing: the ``engine:`` block parses, rejects unknown
    keys, and lands on DeploymentConfig.engine_config via overrides."""
    from ray_tpu.serve.config import DeploymentConfig
    from ray_tpu.serve.schema import DeploymentSchema, apply_overrides

    s = DeploymentSchema.from_dict(
        {"name": "d", "engine": {"page_size": 8, "prefix_cache": True}})
    assert s.engine == {"page_size": 8, "prefix_cache": True}
    with pytest.raises(ValueError, match="unknown engine config"):
        DeploymentSchema.from_dict(
            {"name": "d", "engine": {"pagesize": 8}})
    spec = {"deployments": [{"name": "d", "config": DeploymentConfig()}]}
    out = apply_overrides(spec, [s])
    assert out["deployments"][0]["config"].engine_config == \
        {"page_size": 8, "prefix_cache": True}


def test_paged_smoke_benchmark():
    """Satellite CI hook: the benchmark's --paged --smoke A/B (flat vs
    paged pool at the SAME KV-byte budget + shared-prefix TTFT probe)
    runs end to end and emits the summary line with the slot
    multiplier. ISSUE 16 rides the same subprocess: --kv-dtype int8
    and --attn-kernel pallas append their own A/B arms (fp-vs-int8
    lane capacity at equal KV bytes; gather-vs-pallas TPOT with a
    token-identity check), so one smoke run covers all three."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks", "serve_gpt.py"),
         "--paged", "--smoke", "--kv-dtype", "int8",
         "--attn-kernel", "pallas"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    ab = [r for r in rows if r["metric"].endswith("paged_ab")]
    assert ab, rows
    # Same KV bytes, >= 1.5x the concurrent slots (acceptance floor).
    assert ab[0]["smoke"] is True and ab[0]["value"] >= 1.5
    modes = {r["metric"]: r for r in rows}
    assert any("paged_flat_mode" in m for m in modes)
    assert any("paged_paged_mode" in m for m in modes)
    paged_row = next(r for m, r in modes.items() if "paged_paged_mode" in m)
    assert paged_row["prefix_hits"] > 0     # the probe actually hit

    # ISSUE 16 arm: int8 KV admits >= 1.5x lanes at equal KV bytes.
    kv_ab = [r for r in rows if r["metric"].endswith("kv_dtype_ab")]
    assert kv_ab, rows
    assert kv_ab[0]["value"] >= 1.5
    assert kv_ab[0]["bytes_per_token_ratio"] > 1.5
    # ISSUE 16 arm: the pallas kernel streams token-identical output.
    kern_ab = [r for r in rows if r["metric"].endswith("attn_kernel_ab")]
    assert kern_ab, rows
    assert kern_ab[0]["token_identical_temp0"] is True
    kern_mode = next(r for m, r in modes.items() if "attn_pallas_mode" in m)
    assert kern_mode["kernel_dispatches"] > 0


def test_prefix_cache_survives_pinned_eviction():
    """Eviction under lane-saturation must NOT wipe the cache: an entry
    whose pages are all pinned by live lanes frees nothing, so it stays
    resident (and keeps serving hits) until a lane lets go."""
    from ray_tpu.serve.engine import _PagePool, _PrefixCache

    pool = _PagePool(4)
    pc = _PrefixCache(pool, 8)
    toks = np.arange(16, dtype=np.int32)
    lane_pages = pool.alloc(2)          # a live lane holds them
    pc.insert(toks, lane_pages)         # cache pins them too
    assert len(pc) == 2                 # page-bound + exact-length
    pool.alloc(2)                       # pool now dry
    # Every cached page is lane-pinned: eviction can free nothing and
    # must refuse (no pointless wipe) — repeatedly.
    assert pc.evict_lru() is False
    assert pc.evict_lru() is False
    assert len(pc) == 2
    pool.unref(lane_pages)              # lane done: cache-only refs
    assert pc.evict_lru() is True       # now an eviction frees a page
    assert pool.available() >= 1
    pc.clear()                          # teardown unpins EVERYTHING
    assert len(pc) == 0 and pool.available() == 2


def test_paged_engine_metrics_observed(nano, nano_params):
    """Page-pool observability: gauges + prefix/COW counters reach the
    serve metric set, and engine.stats() carries the page block."""
    from ray_tpu._private.metrics import serve_metrics

    eng = _make(nano, nano_params, paged=True, page_size=8,
                prefix_cache=True, deployment="paged_probe")
    try:
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, nano.vocab_size, (11,)).astype(np.int32)
        list(eng.stream(prompt, 6))
        list(eng.stream(prompt, 6))    # exact repeat: hit + COW
        sm = serve_metrics()
        key = (("deployment", "paged_probe"),)
        free = dict(sm["engine_pages_free"].collect())
        used = dict(sm["engine_pages_used"].collect())
        hits = dict(sm["engine_prefix_hits"].collect())
        cows = dict(sm["engine_cow_copies"].collect())
        assert key in free and key in used
        assert free[key] + used[key] == eng.n_pages
        assert hits.get(key, 0) >= 1
        assert cows.get(key, 0) >= 1
        st = eng.stats()
        for field in ("pages_free", "pages_used", "prefix_hits",
                      "cow_copies", "page_size", "n_pages"):
            assert field in st
    finally:
        eng.shutdown()
