"""pp x sp composition: ring attention over the sp sub-axis inside each
GPipe pipeline stage (parallel/pipeline.py sp_axis=, models/gpt.py
_block_pp_sp). SURVEY §2.3 PP/SP rows."""
import dataclasses

import jax
import numpy as np
import pytest

from ray_tpu.models import gpt
from ray_tpu.parallel import create_mesh


def _loss(cfg, mesh, params, tokens):
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits = gpt.forward(params, inp, cfg, mesh)
    logp = jax.nn.log_softmax(logits.astype(np.float32), axis=-1)
    ll = np.take_along_axis(np.asarray(logp), np.asarray(tgt)[..., None],
                            axis=-1)
    return -float(ll.mean())


def test_pp_sp_matches_single_device_forward():
    """The pp x sp pipelined forward computes the SAME function as the
    plain single-device stack (same params, same tokens)."""
    cfg = dataclasses.replace(gpt.CONFIGS["nano"], pp_axis="pp",
                              sp_axis="sp", num_microbatches=2)
    base = dataclasses.replace(gpt.CONFIGS["nano"])
    mesh = create_mesh({"dp": 2, "pp": 2, "sp": 2})
    params = gpt.init_params(jax.random.PRNGKey(0), base)
    tokens = jax.numpy.asarray(
        np.random.default_rng(0).integers(
            0, base.vocab_size, (4, 64), np.int64).astype(np.int32))
    ref = gpt.forward(params, tokens, base)
    out = gpt.forward(params, tokens, cfg, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_pp_sp_train_step_runs_and_loss_decreases():
    cfg = dataclasses.replace(gpt.CONFIGS["nano"], pp_axis="pp",
                              sp_axis="sp", num_microbatches=2)
    mesh = create_mesh({"dp": 2, "pp": 2, "sp": 2})
    init, step, _, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    tokens = jax.device_put(
        np.random.default_rng(1).integers(
            0, cfg.vocab_size, (8, 65), np.int64).astype(np.int32),
        batch_sh)
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"tokens": tokens})
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_pp_mesh_without_sp_axis_arg_rejected():
    from ray_tpu.parallel.pipeline import pipeline_apply

    mesh = create_mesh({"pp": 2, "sp": 2, "dp": 2})
    cfg = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    x = jax.numpy.zeros((4, 16, cfg.d_model), cfg.dtype)
    with pytest.raises(ValueError, match="sp-aware"):
        pipeline_apply(lambda a, p: a, params["block"], x, mesh=mesh)


def test_pp_tp_sp_combination_rejected():
    cfg = dataclasses.replace(gpt.CONFIGS["nano"], pp_axis="pp",
                              sp_axis="sp")
    mesh = create_mesh({"pp": 2, "tp": 2, "sp": 2})
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.numpy.zeros((2, 32), jax.numpy.int32)
    with pytest.raises(NotImplementedError, match="pick two"):
        gpt.forward(params, tokens, cfg, mesh)
