"""MARWIL tests (reference: ``rllib/algorithms/marwil/tests`` —
advantage-weighted imitation must beat plain BC when the dataset mixes
good and bad behavior)."""
import numpy as np

from ray_tpu.rllib import MARWILConfig


def _mixed_quality_dataset(n=3000, seed=0):
    """Bandit-style dataset: 4 states, 4 actions; the 'expert' half picks
    action == state (reward 1), the 'random' half picks uniformly
    (reward 1 only when it happens to match). MARWIL's advantage weights
    should upweight the matching transitions; plain BC imitates the
    marginal (noisy) action distribution."""
    rng = np.random.default_rng(seed)
    states = rng.integers(0, 4, n)
    expert = rng.random(n) < 0.5
    actions = np.where(expert, states, rng.integers(0, 4, n))
    rewards = (actions == states).astype(np.float32)
    obs = np.eye(4, dtype=np.float32)[states]
    dones = np.ones(n, np.float32)  # one-step episodes
    return {"obs": obs, "actions": actions.astype(np.int64),
            "rewards": rewards, "dones": dones}


def _accuracy(algo):
    obs = np.eye(4, dtype=np.float32)
    return float(np.mean(algo.compute_actions(obs) == np.arange(4)))


def test_marwil_learns_from_mixed_data():
    data = _mixed_quality_dataset()
    cfg = (MARWILConfig()
           .training(beta=2.0, lr=5e-3, num_epochs=40, minibatch_size=256)
           .debugging(seed=1)
           .offline(data, obs_dim=4, num_actions=4))
    algo = cfg.build()
    m = algo.train()
    assert np.isfinite(m["policy_loss"]) and np.isfinite(m["vf_loss"])
    assert _accuracy(algo) == 1.0, "MARWIL failed to recover the expert"
    # the advantage normalizer must have moved off its init
    assert m["ms_adv"] != 1.0


def test_beta_zero_is_plain_bc():
    data = _mixed_quality_dataset()
    cfg = (MARWILConfig()
           .training(beta=0.0, lr=5e-3, num_epochs=10, minibatch_size=256)
           .debugging(seed=1)
           .offline(data, obs_dim=4, num_actions=4))
    algo = cfg.build()
    m = algo.train()
    assert m["weight_mean"] == 1.0  # uniform weights == BC
