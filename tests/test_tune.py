"""Tune: search DSL, Tuner.fit, ASHA early stopping, PBT, Trainer-on-Tune."""
import os

import numpy as np
import pytest


def test_search_space_expansion():
    from ray_tpu import tune
    from ray_tpu.tune import BasicVariantGenerator

    gen = BasicVariantGenerator(num_samples=2, seed=0)
    gen.set_search_space({
        "lr": tune.loguniform(1e-4, 1e-1),
        "size": tune.grid_search([16, 32, 64]),
        "nested": {"k": tune.choice(["a", "b"])},
    })
    cfgs = []
    while True:
        c = gen.suggest(f"t{len(cfgs)}")
        if c is None:
            break
        cfgs.append(c)
    assert len(cfgs) == 6  # 3 grid × 2 samples
    assert {c["size"] for c in cfgs} == {16, 32, 64}
    for c in cfgs:
        assert 1e-4 <= c["lr"] <= 1e-1
        assert c["nested"]["k"] in ("a", "b")


def test_tuner_grid(rt_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train import RunConfig

    def objective(config):
        tune.report({"score": config["x"] ** 2})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="min",
                                    max_concurrent_trials=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert len(grid) == 4
    assert not grid.errors
    best = grid.get_best_result()
    assert best.config["x"] == 1
    assert grid.get_best_result(mode="max").config["x"] == 4
    # experiment state snapshot written
    assert os.path.exists(os.path.join(grid.experiment_path,
                                       "experiment_state.json"))


def test_tuner_trial_error_isolated(rt_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train import RunConfig

    def objective(config):
        if config["x"] == 2:
            raise ValueError("boom")
        tune.report({"score": config["x"]})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert len(grid.errors) == 1
    assert "boom" in grid.errors[0].error
    assert grid.get_best_result().config["x"] == 3


def test_asha_early_stops(rt_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train import RunConfig

    def objective(config):
        import time

        for i in range(20):
            # bad trials plateau high; good trials descend
            loss = config["base"] - (i * 0.1 if config["base"] < 5 else 0)
            tune.report({"loss": loss, "training_iteration": i + 1})
            time.sleep(0.005)

    grid = tune.Tuner(
        objective,
        param_space={"base": tune.grid_search([1.0, 2.0, 9.0, 10.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=4,
            scheduler=tune.AsyncHyperBandScheduler(
                metric="loss", mode="min", grace_period=2,
                reduction_factor=2, max_t=20)),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    def last_iter(r):
        return r.metrics.get("training_iteration", 0)

    good = [r for r in grid.results if r.config["base"] < 5]
    bad = [r for r in grid.results if r.config["base"] > 5]
    # good trials run to (or near) max_t; at least one bad trial is cut early
    assert max(last_iter(r) for r in good) >= 10
    assert min(last_iter(r) for r in bad) < 10, \
        [(r.config["base"], last_iter(r)) for r in grid.results]
    best = grid.get_best_result()
    assert best.config["base"] < 5


def test_pbt_exploits(rt_cluster, tmp_path):
    from ray_tpu import tune
    from ray_tpu.train import Checkpoint, RunConfig

    sync_dir = tmp_path / "sync"
    sync_dir.mkdir()

    def objective(config):
        import os
        import time

        import numpy as np

        from ray_tpu import train

        # barrier: don't start iterating until BOTH trials are alive, so
        # PBT's ranking sees two trials at every perturbation interval
        open(os.path.join(config["sync"], f"up_{config['lr']}"), "w")
        deadline = time.time() + 20
        while len(os.listdir(config["sync"])) < 2:
            if time.time() > deadline:
                raise TimeoutError("peer trial never started")
            time.sleep(0.01)
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.load_state()[0]) + 1
        for i in range(start, 12):
            score = i * config["lr"]
            tune.report(
                {"score": score, "training_iteration": i + 1},
                checkpoint=Checkpoint.from_state(np.int64(i)))
            time.sleep(0.03)  # pace reports so trials interleave in polls

    sched = tune.PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": tune.uniform(0.5, 2.0)}, seed=0)
    grid = tune.Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.01, 1.5]),
                     "sync": str(sync_dir)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert not grid.errors
    # the weak trial must have been perturbed away from lr=0.01 by exploit
    weak = [r for r in grid.results
            if r.metrics_history
            and r.metrics_history[0].get("score", 1) == 0]
    assert weak and weak[0].config["lr"] != 0.01, \
        [(r.config, len(r.metrics_history)) for r in grid.results]
    best = grid.get_best_result()
    assert best.metrics["score"] > 10 * 0.5  # exploited/continued trial


def test_trainer_on_tune(rt_cluster, tmp_path):
    """Train mounts on Tune exactly like the reference (base_trainer:567)."""
    from ray_tpu import train, tune
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        lr = config.get("lr", 0.1)
        train.report({"final_loss": 1.0 / lr})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "inner")))
    grid = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": tune.grid_search([0.5, 2.0])}},
        tune_config=tune.TuneConfig(metric="final_loss", mode="min",
                                    max_concurrent_trials=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert not grid.errors, grid.errors[0].error if grid.errors else None
    assert grid.get_best_result().metrics["final_loss"] == pytest.approx(0.5)
