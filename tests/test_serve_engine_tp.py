"""Tensor-parallel decode (ISSUE 20): one DecodeEngine spanning a
multi-chip mesh.

- ``DecodeEngine(tp=2)`` on a REAL 2-device host-platform mesh
  (conftest forces 8 virtual CPU devices) is TOKEN-IDENTICAL to the
  single-chip engine at temperature 0 AND seeded temperature > 0,
  flat and paged, with speculative decoding on — the sharded compute
  graph (column/row-parallel weights, head-sharded KV, psum'd
  partials) commits the same tokens the canonical graph does.
- The compiled-program set stays ``len(prompt_buckets) + 3`` PER MESH
  SHAPE: the tp=2 wrappers are distinct cache keys from tp=1, and an
  admission storm adds zero programs to either.
- The KV handoff plane is a resharding boundary: an N-way exporter
  gathers to the canonical host layout, an M-way importer scatters
  into its own mesh, the digest rides the layout-independent bytes —
  and a non-canonical layout stamp degrades to the counted local
  re-prefill, never a wrongly-scattered cache.
- Crash-resume works unchanged on sharded state: a mid-stream driver
  kill on a tp=2 engine resumes token-identically via the replay
  token.
"""
import threading
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _make_engine(nano, nano_params, **kw):
    from ray_tpu.serve.engine import DecodeEngine

    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    return DecodeEngine(nano_params, nano, **kw)


def _drain(lane):
    from ray_tpu.serve.batching import _EngineStream

    return np.concatenate(list(_EngineStream(lane)))


def _mk_prompt(rid: int, vocab: int, n: int = 7):
    return np.random.default_rng(2000 + rid).integers(
        0, vocab, (n,)).astype(np.int32)


# ------------------------------------------------------- token identity
@pytest.mark.parametrize("paged,temperature",
                         [(False, 0.0), (True, 0.0),
                          (False, 1.0), (True, 1.0)])
def test_tp2_token_identity(nano, nano_params, paged, temperature):
    """tp=2 output == tp=1 output, stream for stream, at temp 0 and
    seeded temp>0, flat and paged — concurrent mixed-length requests
    through both pools."""
    prompts = [_mk_prompt(i, nano.vocab_size, n)
               for i, n in enumerate((5, 8, 11, 16))]
    max_news = [10, 7, 12, 3]

    def run(tp):
        eng = _make_engine(nano, nano_params, paged=paged, page_size=8,
                           temperature=temperature, tp=tp)
        try:
            outs = {}

            def consume(i):
                outs[i] = np.concatenate(list(eng.stream(
                    prompts[i], max_news[i], seed=100 + i)))

            threads = [threading.Thread(target=consume, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert eng.stats()["tp"] == tp
            return outs
        finally:
            eng.shutdown()

    ref, got = run(1), run(2)
    for i in range(4):
        assert (got[i] == ref[i]).all(), (i, got[i], ref[i])


def test_tp2_spec_decode_identity(nano, nano_params):
    """Speculative decoding on a sharded pool: the tp=2 verify program
    commits exactly what tp=1 commits (draft, verify, and the
    correction token all replicate through the mesh)."""
    prompt = np.tile(np.arange(4, dtype=np.int32) % nano.vocab_size, 2)

    def run(tp):
        eng = _make_engine(nano, nano_params, paged=True, page_size=8,
                           spec_decode="ngram", draft_k=4, tp=tp)
        try:
            out = np.concatenate(list(eng.stream(prompt, 16, seed=1)))
            st = eng.stats()
            assert st["spec"]["rounds"] >= 1
            return out
        finally:
            eng.shutdown()

    ref, got = run(1), run(2)
    assert (got == ref).all(), (got, ref)


# --------------------------------------------------- program budget
def test_tp_recompile_guard(nano, nano_params):
    """The per-mesh compiled-program budget: a tp=2 engine compiles one
    prefill per prompt bucket + 1 chunk + 2 handoff programs on ITS OWN
    wrappers (distinct lru keys from tp=1), and an admission storm adds
    zero programs."""
    from ray_tpu.models.gpt_decode import (jit_decode_chunk_slots,
                                           jit_prefill_into_slot)

    eng = _make_engine(nano, nano_params, slots=3, max_len=48,
                       prompt_buckets=(8, 16), tp=2)
    try:
        rng = np.random.default_rng(7)

        def storm(n, lens):
            threads = []
            for i in range(n):
                p = rng.integers(0, nano.vocab_size,
                                 (int(lens[i % len(lens)]),)
                                 ).astype(np.int32)
                mn = int(rng.integers(1, 12))
                t = threading.Thread(
                    target=lambda p=p, mn=mn: list(eng.stream(p, mn)))
                t.start()
                threads.append(t)
            for t in threads:
                t.join()

        storm(4, [5, 16])             # warm pass: touch both buckets
        pre_prefill = eng._prefill._cache_size()
        pre_step = eng._step._cache_size()
        assert pre_prefill >= 2       # one program per prompt bucket
        storm(12, [1, 3, 7, 8, 9, 12, 15, 16])
        assert eng._prefill._cache_size() == pre_prefill
        assert eng._step._cache_size() == pre_step
        # Mesh shape is part of the wrapper key: the tp=2 engine shares
        # the tp=2 wrapper, never the tp=1 one.
        assert jit_prefill_into_slot(nano, 0.0, 2) is eng._prefill
        assert jit_prefill_into_slot(nano, 0.0) is not eng._prefill
        assert jit_decode_chunk_slots(nano, 4, 0.0, -1, 2) is eng._step
    finally:
        eng.shutdown()


def test_tp_validation_and_config_plane(nano, nano_params):
    """Bad meshes fail at construction; ensure_tp is idempotent,
    rebuilds an unused engine, and refuses a live one."""
    with pytest.raises(ValueError, match="tp"):
        _make_engine(nano, nano_params, tp=3)   # 3 does not divide 2 heads
    eng = _make_engine(nano, nano_params, auto_start=False)
    assert eng.tp == 1
    eng.ensure_tp(2)
    assert eng.tp == 2 and eng.stats()["tp"] == 2
    eng.ensure_tp(2)                            # idempotent no-op
    eng.apply_config(tp=1)                      # config-plane routing
    assert eng.tp == 1
    eng.start()
    try:
        list(eng.stream(_mk_prompt(9, nano.vocab_size), 4))
        with pytest.raises(ValueError, match="live"):
            eng.ensure_tp(2)
    finally:
        eng.shutdown()


# ------------------------------------------------ resharding handoff
@pytest.mark.parametrize("src_tp,dst_tp,src_paged,dst_paged",
                         [(2, 1, False, False), (1, 2, True, True),
                          (2, 4, True, False)])
def test_handoff_resharding_roundtrip(nano, nano_params, src_tp, dst_tp,
                                      src_paged, dst_paged):
    """N-way prefill -> M-way decode: the exporter gathers to the
    canonical host layout, the importer scatters into its own mesh, the
    digest verifies the layout-independent bytes, and the continued
    stream is token-identical to an uninterrupted tp=1 run."""
    import dataclasses

    import jax

    from ray_tpu.models import gpt

    params = nano_params
    if max(src_tp, dst_tp) > nano.n_head:
        # nano has 2 heads; the 2-way -> 4-way leg needs a mesh axis
        # that divides the head count, so widen the model for it.
        nano = dataclasses.replace(nano, n_head=4)
        params = gpt.init_params(jax.random.PRNGKey(0), nano)
    pre = _make_engine(nano, params, role="prefill", tp=src_tp,
                       paged=src_paged, page_size=8)
    dec = _make_engine(nano, params, role="decode", tp=dst_tp,
                       paged=dst_paged, page_size=8)
    ref_eng = _make_engine(nano, params)
    try:
        prompt = _mk_prompt(3, nano.vocab_size)
        ref = np.concatenate(list(ref_eng.stream(prompt, 12, seed=9)))
        desc = pre.handoff(prompt, 12, seed=9)
        assert desc["digest"]
        out = _drain(dec.admit_prefilled(desc))
        assert (out == ref).all(), (out, ref)
        assert pre.stats()["handoff"]["exported"] == 1
        hd = dec.stats()["handoff"]
        assert hd["imported"] == 1 and hd["import_fallbacks"] == 0
    finally:
        pre.shutdown()
        dec.shutdown()
        ref_eng.shutdown()


def test_handoff_layout_mismatch_counted_fallback(nano, nano_params):
    """A payload stamped with a non-canonical KV layout is REJECTED
    (its bytes would scatter wrong into the importer's mesh) and
    degrades to the counted local re-prefill — token-identical, zero
    broken streams, visible in serve_prefill_fallbacks_total."""
    from ray_tpu._private.metrics import serve_metrics
    from ray_tpu.serve.handoff import payload_digest

    pre = _make_engine(nano, nano_params, role="prefill", tp=2)
    dec = _make_engine(nano, nano_params, role="decode", tp=2,
                       deployment="tp_layout_probe")
    ref_eng = _make_engine(nano, nano_params)
    try:
        prompt = _mk_prompt(4, nano.vocab_size)
        ref = np.concatenate(list(ref_eng.stream(prompt, 10, seed=5)))
        desc = pre.handoff(prompt, 10, seed=5)
        # A foreign exporter shipping mesh-local bytes: internally
        # consistent (digest covers the stamp), wrong for this plane.
        desc["payload"]["layout"] = "tp2-local"
        desc["payload"]["digest"] = payload_digest(desc["payload"])
        desc["digest"] = desc["payload"]["digest"]
        out = _drain(dec.admit_prefilled(desc))
        assert (out == ref).all(), (out, ref)
        hd = dec.stats()["handoff"]
        assert hd["imported"] == 0 and hd["import_fallbacks"] == 1
        fb = dict(serve_metrics()["prefill_fallbacks"].collect())
        key = (("deployment", "tp_layout_probe"), ("where", "engine"))
        assert fb.get(key, 0) >= 1
    finally:
        pre.shutdown()
        dec.shutdown()
        ref_eng.shutdown()


def test_handoff_digest_canonical_across_meshes(nano, nano_params):
    """The digest is a function of the canonical bytes, not the
    exporter's mesh: the same (prompt, seed) exported from a tp=1 and
    a tp=2 engine hashes identically."""
    one = _make_engine(nano, nano_params, role="prefill", tp=1)
    two = _make_engine(nano, nano_params, role="prefill", tp=2)
    try:
        prompt = _mk_prompt(6, nano.vocab_size)
        d1 = one.handoff(prompt, 8, seed=2)
        d2 = two.handoff(prompt, 8, seed=2)
        assert d1["digest"] == d2["digest"]
        assert "layout" not in d2["payload"]   # canonical ships unstamped
    finally:
        one.shutdown()
        two.shutdown()


# ------------------------------------------------------- crash resume
def test_tp_driver_kill_resume_identity(nano, nano_params):
    """Mid-stream driver death on a sharded pool: the supervisor
    rebuilds the tp=2 pool (sharded params, sharded cache, same
    compiled programs), and the replay token resumes the stream
    bit-exactly against an uninterrupted tp=1 reference."""
    from ray_tpu.serve.engine import EngineRestartError

    ref_eng = _make_engine(nano, nano_params, temperature=1.0)
    eng = _make_engine(nano, nano_params, temperature=1.0, tp=2,
                       wedge_timeout_s=2.0)
    try:
        prompt = _mk_prompt(8, nano.vocab_size)
        ref = np.concatenate(list(ref_eng.stream(prompt, 24, seed=11)))
        eng.inject_fault("driver_die", at_tokens=8)
        toks = []
        try:
            for c in eng.stream(prompt, 24, seed=11):
                toks.extend(int(t) for t in np.asarray(c).ravel())
        except EngineRestartError:
            pass
        assert 0 < len(toks) < 24, toks
        # The replica's health probe path: keep probing until the
        # supervisor observes the death and restarts (the lanes fail
        # before the old thread finishes dying, so an early probe can
        # still see it alive and not restart yet).
        deadline = time.monotonic() + 10.0
        while eng.stats()["driver_restarts"] == 0:
            assert eng.supervise()
            assert time.monotonic() < deadline, "supervisor never restarted"
            time.sleep(0.05)
        tail = list(eng.stream(prompt, 24, seed=11,
                               resume_from=len(toks)))
        toks.extend(int(t) for t in np.concatenate(tail))
        assert toks == [int(t) for t in ref], (toks, ref)
        assert eng.stats()["driver_restarts"] == 1
        assert eng.stats()["tp"] == 2
    finally:
        ref_eng.shutdown()
        eng.shutdown()


# ------------------------------------------------------- benchmark CI
def test_tp_smoke_benchmark():
    """Satellite CI hook: the benchmark's --tp 2 --smoke A/B runs end
    to end (tp=1 and sharded arms under the same saturating burst) and
    the summary line certifies temp-0 token identity and equal
    dispatch accounting on the forced host mesh."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks", "serve_gpt.py"),
         "--tp", "2", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    ab = [r for r in rows if r["metric"].endswith("tp_ab")]
    assert ab, rows
    assert ab[0]["smoke"] is True and ab[0]["value"] > 0
    assert ab[0]["token_identical_temp0"] is True
    assert ab[0]["dispatches_equal"] is True
    modes = {r["metric"]: r for r in rows}
    assert any(m.endswith("tp1_mode") for m in modes)
    assert any(m.endswith("tp2_mode") for m in modes)


# --------------------------------------------------- flight recorder
def test_shard_dispatch_event_and_stats(nano, nano_params, tmp_path):
    """The sharded dispatch path leaves a post-mortem breadcrumb: one
    ``shard.dispatch`` event (mesh shape + program key) per chunk
    boundary, next to the ``engine.dispatch`` it annotates."""
    from ray_tpu._private import events as ev

    ev._reset_for_tests()
    try:
        ev.init(str(tmp_path), proc="tp-test")
        eng = _make_engine(nano, nano_params, tp=2, paged=True,
                           page_size=8)
        try:
            list(eng.stream(_mk_prompt(10, nano.vocab_size), 8))
        finally:
            eng.shutdown()
        rec = ev.recorder()
        rec.flush()
        ring = ev.read_ring(rec.path)
        shard = [e for e in ring["events"]
                 if e["kind"] == "shard.dispatch"]
        assert shard, [e["kind"] for e in ring["events"]]
        assert [list(ax) for ax in shard[0]["attrs"]["mesh"]] \
            == [["tp", 2]]
        assert shard[0]["attrs"]["program"] == "chunk_paged"
    finally:
        ev._reset_for_tests()
