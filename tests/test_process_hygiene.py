"""Orphan reaping: no cluster process survives a SIGKILL'd spawner
(reference capability: ``src/ray/util/subreaper.h`` — workers must not
outlive their raylet)."""
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DRIVER = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
import ray_tpu as rt

rt.init(num_cpus=2, num_tpus=0)

@rt.remote
def pid():
    return os.getpid()

pids = rt.get([pid.remote() for _ in range(4)])
with open({out!r}, "w") as f:
    json.dump(sorted(set(pids)), f)
time.sleep(600)   # hold the cluster open until we are SIGKILLed
"""


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def test_workers_die_with_sigkilled_driver(tmp_path):
    out = str(tmp_path / "pids.json")
    proc = subprocess.Popen(
        [sys.executable, "-c", DRIVER.format(repo=REPO, out=out)],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(out):
        if proc.poll() is not None:
            raise AssertionError("driver died before spawning workers")
        time.sleep(0.2)
    assert os.path.exists(out), "driver never reported worker pids"
    import json

    with open(out) as f:
        worker_pids = json.load(f)
    assert worker_pids and all(_alive(p) for p in worker_pids)

    proc.send_signal(signal.SIGKILL)   # no graceful shutdown hook runs
    proc.wait(timeout=10)

    deadline = time.time() + 15
    while time.time() < deadline and any(_alive(p) for p in worker_pids):
        time.sleep(0.5)
    leaked = [p for p in worker_pids if _alive(p)]
    for p in leaked:   # clean up before failing loudly
        os.kill(p, signal.SIGKILL)
    assert not leaked, f"workers leaked after driver SIGKILL: {leaked}"


def test_node_daemon_dies_with_parent(tmp_path):
    """A --die-with-parent node daemon (and its workers) follows a
    SIGKILL'd standalone head's test harness down."""
    session_dir = tempfile.mkdtemp(prefix="rt_hyg_")
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "1", "--num-tpus", "0",
         "--session-dir", session_dir, "--die-with-parent"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        path = os.path.join(session_dir, "session.json")
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(path):
            assert head.poll() is None, "head died during startup"
            time.sleep(0.1)
        assert os.path.exists(path)
    finally:
        head.send_signal(signal.SIGKILL)
        head.wait(timeout=10)
    # The head was SIGKILLed → pdeathsig must reap any worker it
    # prestarted; give the kernel + watchdog a moment, then scan.
    time.sleep(3)
    r = subprocess.run(["pgrep", "-f", session_dir],
                       capture_output=True, text=True)
    leaked = [int(p) for p in r.stdout.split()]
    for p in leaked:
        os.kill(p, signal.SIGKILL)
    assert not leaked, f"processes leaked after head SIGKILL: {leaked}"


def test_head_startup_reclaims_dead_session_segments():
    """A SIGKILLed session never runs its clean-stop sweep; the NEXT
    head to start on this machine reclaims its shm segments (dead pid
    in session.json proves the session is over)."""
    import json as _json

    import ray_tpu as rt
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import SharedMemoryStore
    from ray_tpu._private.utils import session_shm_domain

    if rt.is_initialized():
        rt.shutdown()
    # Fabricate a dead session in the discovery root: a session.json
    # with a certainly-dead pid and one orphaned segment in its domain.
    root = os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu")
    dead_dir = os.path.join(root, f"session_deadtest_{os.getpid()}")
    os.makedirs(dead_dir, exist_ok=True)
    dead_pid = 2 ** 22 - 3  # beyond pid_max defaults: never running
    with open(os.path.join(dead_dir, "session.json"), "w") as f:
        _json.dump({"pid": dead_pid, "head_sock": "x"}, f)
    store = SharedMemoryStore(1 << 20,
                              domain=session_shm_domain(dead_dir))
    oid = ObjectID.from_random()
    store.create(oid, [b"h", b"orphan"])
    seg = f"/dev/shm/{store._name(oid)}"
    assert os.path.exists(seg)

    rt.init(num_cpus=1)  # embedded head start runs the sweep
    try:
        assert not os.path.exists(seg), "dead session segment survived"
    finally:
        rt.shutdown()
        import shutil

        shutil.rmtree(dead_dir, ignore_errors=True)
