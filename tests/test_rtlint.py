"""rtlint (tools/rtlint): the repo-native static analyzer.

Three layers of coverage:

- fixture files under ``tests/rtlint_fixtures/`` assert every rule
  RT101-RT107 both FIRES (lines tagged ``# FIRES RTxxx``, or
  ``# FIRES-BELOW RTxxx`` when a same-line comment would read as a
  justification) and respects inline suppressions — the expectation set
  is derived from the tags, so the fixtures are self-describing;
- the baseline mechanism is proven on a real finding (grandfathered
  entries filtered, stale entries reported);
- the CI gate: ``python -m tools.rtlint ray_tpu/ --check`` must exit 0
  against the checked-in baseline (this is the tier-1 hook — a new
  finding in ray_tpu/ fails this test), and two runs must be
  byte-identical (determinism).
"""
import json
import os
import re
import subprocess
import sys

import pytest

from tools.rtlint import (DEFAULT_BASELINE, RULE_TABLE, lint_metric_name,
                          run_paths, write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "rtlint_fixtures")

_MARKER = re.compile(r"#\s*FIRES(-BELOW)?\s+(RT\d{3})")


def _expected_from_markers(path):
    """(line, rule) pairs a fixture file declares it must produce."""
    out = set()
    with open(path) as f:
        lines = f.readlines()
    for i, text in enumerate(lines, 1):
        m = _MARKER.search(text)
        if not m:
            continue
        line = i
        if m.group(1):  # FIRES-BELOW: next non-blank, non-comment line
            j = i
            while j < len(lines) and (
                    not lines[j].strip()
                    or lines[j].lstrip().startswith("#")):
                j += 1
            line = j + 1
        out.add((line, m.group(2)))
    return out


def _fixture_findings():
    report = run_paths([FIXTURES])
    return report, {(f.line, f.rule) for f in report.findings
                    if f.rule != "RT999"}


def test_fixtures_fire_exactly_as_marked():
    """Every tagged line fires its rule; nothing else fires — which
    proves, per rule, the positive, the negative, AND the suppressed
    cases in one comparison."""
    report, got = _fixture_findings()
    expected = set()
    by_file = {}
    for root, _dirs, files in os.walk(FIXTURES):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, REPO).replace(os.sep, "/")
            marks = _expected_from_markers(p)
            by_file[rel] = marks
            expected |= marks
    # Findings are repo-relative only when cwd == repo root; compare on
    # (line, rule) per file to stay cwd-independent.
    got_pairs = {(f.path.split("rtlint_fixtures/")[-1], f.line, f.rule)
                 for f in report.findings}
    exp_pairs = {(rel.split("rtlint_fixtures/")[-1], line, rule)
                 for rel, marks in by_file.items()
                 for (line, rule) in marks}
    assert got_pairs == exp_pairs, (
        f"unexpected: {sorted(got_pairs - exp_pairs)}\n"
        f"missing: {sorted(exp_pairs - got_pairs)}")


def test_every_rule_has_fire_and_suppression_coverage():
    """The fixture set exercises each rule's fire path (a tagged line)
    and its suppression path (a ``# rtlint: disable=`` for the same
    rule somewhere in the fixtures)."""
    tagged, suppressed = set(), set()
    for root, _dirs, files in os.walk(FIXTURES):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(root, fn)).read()
            tagged |= {m.group(2) for m in _MARKER.finditer(src)}
            suppressed |= set(
                re.findall(r"rtlint:\s*disable=(RT\d{3})", src))
    rules = set(RULE_TABLE)
    assert tagged == rules, f"no fire fixture for {rules - tagged}"
    assert suppressed == rules, \
        f"no suppression fixture for {rules - suppressed}"


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    report, _ = _fixture_findings()
    assert report.findings, "fixtures must produce findings"
    # One grandfathered finding PER RULE: the baseline must silence
    # each rule's findings individually, not just wholesale.
    grandfathered = {}
    for f in report.findings:
        grandfathered.setdefault(f.rule, f)
    assert set(grandfathered) == set(RULE_TABLE)
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), list(grandfathered.values()))
    data = json.loads(baseline.read_text())
    assert sorted(data["findings"]) == sorted(
        f.key for f in grandfathered.values())

    again = run_paths([FIXTURES], baseline_path=str(baseline))
    assert {f.key for f in again.baselined} == \
        {f.key for f in grandfathered.values()}
    assert not {f.key for f in again.new} & set(data["findings"])
    assert len(again.new) == len(report.findings) - len(grandfathered)
    grandfather = report.findings[0]

    # A stale entry (finding since fixed) is surfaced, not silently kept.
    baseline.write_text(json.dumps(
        {"findings": [grandfather.key, "RT101:gone.py:Gone.fixed.attr"]}))
    stale = run_paths([FIXTURES], baseline_path=str(baseline))
    assert stale.stale_baseline == ["RT101:gone.py:Gone.fixed.attr"]


def test_baseline_keys_are_line_number_free(tmp_path):
    """Inserting lines above a finding must not churn its baseline key
    (the whole point of symbol-keyed entries)."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def b(self):\n"
        "        self._n = 2\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    key1 = run_paths([str(p)]).findings[0].key
    p.write_text("# a new header comment\n# another\n" + src)
    moved = run_paths([str(p)]).findings[0]
    assert moved.key == key1 and moved.line > 10 - 1


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    report = run_paths([str(p)])
    assert [f.rule for f in report.findings] == ["RT999"]
    assert report.new, "a broken file must fail the gate"


def test_parse_errors_are_never_grandfatherable(tmp_path):
    """A baseline must not greenlight a file that escapes every rule:
    write_baseline drops RT999 keys, and even a hand-edited baseline
    carrying one still fails the gate."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    report = run_paths([str(p)])
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), report.findings)
    assert json.loads(baseline.read_text())["findings"] == []
    baseline.write_text(json.dumps(
        {"findings": [report.findings[0].key]}))  # hand-edited in
    again = run_paths([str(p)], baseline_path=str(baseline))
    assert again.new and not again.baselined


def test_rt106_shares_the_runtime_implementation():
    """The satellite contract: MetricsRegistry.register and the static
    RT106 rule run ONE source of truth, so they cannot drift. The
    runtime loads metrics_names.py by FILE PATH (a package import
    would drag the whole analyzer into every ray_tpu process), so the
    pin is source-file identity, not function-object identity."""
    from ray_tpu._private import metrics
    from tools.rtlint import metrics_names

    assert os.path.samefile(
        metrics.lint_metric_name.__code__.co_filename,
        metrics_names.__file__)
    # And ray_tpu's import must NOT pull the analyzer package in.
    proc = subprocess.run(
        [sys.executable, "-c",
         "import ray_tpu._private.metrics as m, sys; "
         "assert not any(k.startswith('tools') for k in sys.modules), "
         "sorted(k for k in sys.modules if k.startswith('tools')); "
         "assert m.lint_metric_name('x', 'counter')"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    # RT_METRICS_STRICT semantics unchanged: strict registries raise on
    # the same problems the static rule reports.
    reg = metrics.MetricsRegistry(strict=True)
    with pytest.raises(ValueError, match="_total"):
        metrics.Counter("requests_shed", registry=reg)
    reg_warn = metrics.MetricsRegistry(strict=False)
    with pytest.warns(UserWarning, match="_seconds"):
        metrics.Histogram("decode_latency", registry=reg_warn)


def test_rtlint_is_clean_on_itself():
    report = run_paths([os.path.join(REPO, "tools")])
    assert not report.findings, [f.render() for f in report.findings]


def test_determinism_two_runs_byte_identical():
    """Two analyses of ray_tpu/ must render byte-identical JSON (no
    timestamps, no dict-order leakage, stable sort)."""
    target = os.path.join(REPO, "ray_tpu")
    a = run_paths([target]).to_json()
    b = run_paths([target]).to_json()
    assert a == b


def test_ci_gate_ray_tpu_is_clean():
    """THE tier-1 hook: the analyzer over ray_tpu/ must exit 0 against
    the checked-in baseline — a new finding fails this test, which
    fails the suite, which fails the existing verify command. Runs the
    real CLI so the exit-code contract is what's pinned."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint", "ray_tpu/", "--check"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"rtlint found new findings (fix them or, if genuinely "
        f"grandfathered, add them to {DEFAULT_BASELINE}):\n"
        f"{proc.stdout}\n{proc.stderr}")


def test_ci_gate_fails_on_new_findings(tmp_path):
    """--check exits non-zero on a non-baselined finding."""
    p = tmp_path / "serve"
    p.mkdir()
    bad = p / "controller.py"
    bad.write_text(
        "def loop(work):\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        pass\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint", str(bad), "--check",
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "RT107" in proc.stdout


def test_cli_json_output_and_rule_filter(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint",
         "tests/rtlint_fixtures/rt104_async.py", "--json",
         "--no-baseline", "--rules", "RT104"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    rules = {f["rule"] for f in data["findings"]}
    assert rules == {"RT104"}
    assert data["files_checked"] == 1


def test_shared_lint_rules_agree_with_register():
    """Spot-check the shared function directly (the same strings the
    runtime warns/raises about are what RT106 reports)."""
    assert lint_metric_name("x_total", "counter") == []
    assert any("_total" in p
               for p in lint_metric_name("x", "counter"))
    assert any("_seconds" in p
               for p in lint_metric_name("wait_ms", "histogram"))
    assert any("regex" in p
               for p in lint_metric_name("1bad", "gauge"))
