"""rtlint (tools/rtlint): the repo-native static analyzer.

Three layers of coverage:

- fixture files under ``tests/rtlint_fixtures/`` assert every rule
  RT101-RT107 both FIRES (lines tagged ``# FIRES RTxxx``, or
  ``# FIRES-BELOW RTxxx`` when a same-line comment would read as a
  justification) and respects inline suppressions — the expectation set
  is derived from the tags, so the fixtures are self-describing;
- the baseline mechanism is proven on a real finding (grandfathered
  entries filtered, stale entries reported);
- the CI gate: ``python -m tools.rtlint ray_tpu/ --check`` must exit 0
  against the checked-in baseline (this is the tier-1 hook — a new
  finding in ray_tpu/ fails this test), and two runs must be
  byte-identical (determinism).
"""
import json
import os
import re
import subprocess
import sys

import pytest

from tools.rtlint import (DEFAULT_BASELINE, RULE_TABLE, lint_metric_name,
                          run_paths, write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "rtlint_fixtures")

_MARKER = re.compile(r"#\s*FIRES(-BELOW)?\s+(RT\d{3})")


def _expected_from_markers(path):
    """(line, rule) pairs a fixture file declares it must produce."""
    out = set()
    with open(path) as f:
        lines = f.readlines()
    for i, text in enumerate(lines, 1):
        m = _MARKER.search(text)
        if not m:
            continue
        line = i
        if m.group(1):  # FIRES-BELOW: next non-blank, non-comment line
            j = i
            while j < len(lines) and (
                    not lines[j].strip()
                    or lines[j].lstrip().startswith("#")):
                j += 1
            line = j + 1
        out.add((line, m.group(2)))
    return out


def _fixture_findings():
    report = run_paths([FIXTURES])
    return report, {(f.line, f.rule) for f in report.findings
                    if f.rule != "RT999"}


def test_fixtures_fire_exactly_as_marked():
    """Every tagged line fires its rule; nothing else fires — which
    proves, per rule, the positive, the negative, AND the suppressed
    cases in one comparison."""
    report, got = _fixture_findings()
    expected = set()
    by_file = {}
    for root, _dirs, files in os.walk(FIXTURES):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, REPO).replace(os.sep, "/")
            marks = _expected_from_markers(p)
            by_file[rel] = marks
            expected |= marks
    # Findings are repo-relative only when cwd == repo root; compare on
    # (line, rule) per file to stay cwd-independent.
    got_pairs = {(f.path.split("rtlint_fixtures/")[-1], f.line, f.rule)
                 for f in report.findings}
    exp_pairs = {(rel.split("rtlint_fixtures/")[-1], line, rule)
                 for rel, marks in by_file.items()
                 for (line, rule) in marks}
    assert got_pairs == exp_pairs, (
        f"unexpected: {sorted(got_pairs - exp_pairs)}\n"
        f"missing: {sorted(exp_pairs - got_pairs)}")


def test_every_rule_has_fire_and_suppression_coverage():
    """The fixture set exercises each rule's fire path (a tagged line)
    and its suppression path (a ``# rtlint: disable=`` for the same
    rule somewhere in the fixtures)."""
    tagged, suppressed = set(), set()
    for root, _dirs, files in os.walk(FIXTURES):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(root, fn)).read()
            tagged |= {m.group(2) for m in _MARKER.finditer(src)}
            suppressed |= set(
                re.findall(r"rtlint:\s*disable=(RT\d{3})", src))
    rules = set(RULE_TABLE)
    assert tagged == rules, f"no fire fixture for {rules - tagged}"
    assert suppressed == rules, \
        f"no suppression fixture for {rules - suppressed}"


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    report, _ = _fixture_findings()
    assert report.findings, "fixtures must produce findings"
    # One grandfathered finding PER RULE: the baseline must silence
    # each rule's findings individually, not just wholesale.
    grandfathered = {}
    for f in report.findings:
        grandfathered.setdefault(f.rule, f)
    assert set(grandfathered) == set(RULE_TABLE)
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), list(grandfathered.values()))
    data = json.loads(baseline.read_text())
    assert sorted(data["findings"]) == sorted(
        f.key for f in grandfathered.values())

    again = run_paths([FIXTURES], baseline_path=str(baseline))
    assert {f.key for f in again.baselined} == \
        {f.key for f in grandfathered.values()}
    assert not {f.key for f in again.new} & set(data["findings"])
    assert len(again.new) == len(report.findings) - len(grandfathered)
    grandfather = report.findings[0]

    # A stale entry (finding since fixed) is surfaced, not silently kept.
    baseline.write_text(json.dumps(
        {"findings": [grandfather.key, "RT101:gone.py:Gone.fixed.attr"]}))
    stale = run_paths([FIXTURES], baseline_path=str(baseline))
    assert stale.stale_baseline == ["RT101:gone.py:Gone.fixed.attr"]


def test_baseline_keys_are_line_number_free(tmp_path):
    """Inserting lines above a finding must not churn its baseline key
    (the whole point of symbol-keyed entries)."""
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self._n = 1\n"
        "    def b(self):\n"
        "        self._n = 2\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    key1 = run_paths([str(p)]).findings[0].key
    p.write_text("# a new header comment\n# another\n" + src)
    moved = run_paths([str(p)]).findings[0]
    assert moved.key == key1 and moved.line > 10 - 1


def test_parse_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    report = run_paths([str(p)])
    assert [f.rule for f in report.findings] == ["RT999"]
    assert report.new, "a broken file must fail the gate"


def test_parse_errors_are_never_grandfatherable(tmp_path):
    """A baseline must not greenlight a file that escapes every rule:
    write_baseline drops RT999 keys, and even a hand-edited baseline
    carrying one still fails the gate."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    report = run_paths([str(p)])
    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), report.findings)
    assert json.loads(baseline.read_text())["findings"] == []
    baseline.write_text(json.dumps(
        {"findings": [report.findings[0].key]}))  # hand-edited in
    again = run_paths([str(p)], baseline_path=str(baseline))
    assert again.new and not again.baselined


def test_rt106_shares_the_runtime_implementation():
    """The satellite contract: MetricsRegistry.register and the static
    RT106 rule run ONE source of truth, so they cannot drift. The
    runtime loads metrics_names.py by FILE PATH (a package import
    would drag the whole analyzer into every ray_tpu process), so the
    pin is source-file identity, not function-object identity."""
    from ray_tpu._private import metrics
    from tools.rtlint import metrics_names

    assert os.path.samefile(
        metrics.lint_metric_name.__code__.co_filename,
        metrics_names.__file__)
    # And ray_tpu's import must NOT pull the analyzer package in.
    proc = subprocess.run(
        [sys.executable, "-c",
         "import ray_tpu._private.metrics as m, sys; "
         "assert not any(k.startswith('tools') for k in sys.modules), "
         "sorted(k for k in sys.modules if k.startswith('tools')); "
         "assert m.lint_metric_name('x', 'counter')"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    # RT_METRICS_STRICT semantics unchanged: strict registries raise on
    # the same problems the static rule reports.
    reg = metrics.MetricsRegistry(strict=True)
    with pytest.raises(ValueError, match="_total"):
        metrics.Counter("requests_shed", registry=reg)
    reg_warn = metrics.MetricsRegistry(strict=False)
    with pytest.warns(UserWarning, match="_seconds"):
        metrics.Histogram("decode_latency", registry=reg_warn)


def test_rtlint_is_clean_on_itself():
    report = run_paths([os.path.join(REPO, "tools")])
    assert not report.findings, [f.render() for f in report.findings]


def test_determinism_two_runs_byte_identical():
    """Two analyses of ray_tpu/ must render byte-identical JSON (no
    timestamps, no dict-order leakage, stable sort)."""
    target = os.path.join(REPO, "ray_tpu")
    a = run_paths([target]).to_json()
    b = run_paths([target]).to_json()
    assert a == b


def test_ci_gate_ray_tpu_is_clean():
    """THE tier-1 hook: the analyzer over ray_tpu/ must exit 0 against
    the checked-in baseline — a new finding fails this test, which
    fails the suite, which fails the existing verify command. Runs the
    real CLI so the exit-code contract is what's pinned."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint", "ray_tpu/", "--check"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"rtlint found new findings (fix them or, if genuinely "
        f"grandfathered, add them to {DEFAULT_BASELINE}):\n"
        f"{proc.stdout}\n{proc.stderr}")


def test_ci_gate_fails_on_new_findings(tmp_path):
    """--check exits non-zero on a non-baselined finding."""
    p = tmp_path / "serve"
    p.mkdir()
    bad = p / "controller.py"
    bad.write_text(
        "def loop(work):\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        pass\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint", str(bad), "--check",
         "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "RT107" in proc.stdout


def test_cli_json_output_and_rule_filter(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint",
         "tests/rtlint_fixtures/rt104_async.py", "--json",
         "--no-baseline", "--rules", "RT104"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    rules = {f["rule"] for f in data["findings"]}
    assert rules == {"RT104"}
    assert data["files_checked"] == 1


def test_shared_lint_rules_agree_with_register():
    """Spot-check the shared function directly (the same strings the
    runtime warns/raises about are what RT106 reports)."""
    assert lint_metric_name("x_total", "counter") == []
    assert any("_total" in p
               for p in lint_metric_name("x", "counter"))
    assert any("_seconds" in p
               for p in lint_metric_name("wait_ms", "histogram"))
    assert any("regex" in p
               for p in lint_metric_name("1bad", "gauge"))


# ---------------------------------------------------------------- rtflow
# ISSUE 15: interprocedural dataflow (tools/rtlint/flow.py +
# callgraph.py) and the RT109/RT110/RT111 rules built on it.

def test_new_rules_registered():
    assert {"RT109", "RT110", "RT111"} <= set(RULE_TABLE)


def test_determinism_covers_rtflow_rules():
    """Two analyses of the fixture tree — where RT109-RT111 actually
    produce findings — must render byte-identical JSON, extending the
    determinism pin to the interprocedural rules (their fixpoint and
    call-graph iteration order must not leak)."""
    a = run_paths([FIXTURES]).to_json()
    b = run_paths([FIXTURES]).to_json()
    assert a == b
    rules = {f["rule"] for f in json.loads(a)["findings"]}
    assert {"RT109", "RT110", "RT111"} <= rules


def test_parse_budget_grammar():
    from tools.rtlint import parse_budget

    c = parse_budget("len(prompt_buckets) + 3")
    assert c.evaluate({"len(prompt_buckets)": 2}) == 5
    assert parse_budget("1").evaluate({}) == 1
    assert parse_budget("2 * len(buckets) + 1").evaluate(
        {"len(buckets)": 4}) == 9
    for bad in ("len(prompt_buckets) - 1", "foo", "1.5", "len(a, b)"):
        with pytest.raises(ValueError):
            parse_budget(bad)


def test_card_leq_assumes_atoms_at_least_one():
    from tools.rtlint import Card, parse_budget

    atom = parse_budget("len(prompt_buckets)")
    assert Card.const(1).leq(atom)           # len >= 1 covers a const
    assert atom.leq(parse_budget("len(prompt_buckets) + 2"))
    assert not parse_budget("len(prompt_buckets) + 1").leq(atom)
    assert not Card.unbounded().leq(parse_budget("len(prompt_buckets)"))
    assert Card.unbounded().leq(Card.unbounded())


def _run_engine_scoped(tmp_path, src):
    """Analyze ``src`` under a path RT109's budget scope matches."""
    p = tmp_path / "serve"
    p.mkdir(exist_ok=True)
    f = p / "engine.py"
    f.write_text(src)
    return run_paths([str(f)])


def test_rt109_unbounded_fails_then_bounded_passes(tmp_path):
    """THE acceptance-criteria pin: a request-varying value laundered
    through a helper reaches a trace key -> RT109 fires (RT103 stays
    blind: no len() at the flagged site); re-bounding it through the
    bucket discipline makes the same code clean."""
    unbounded = (
        "import numpy as np\n"
        "# rtlint: program-budget: 1\n"
        "def jit_step(cfg):\n"
        "    return lambda *a: a\n"
        "class Eng:\n"
        "    # rtlint: program-budget: 1\n"
        "    def _build(self, cfg):\n"
        "        self._prog = jit_step(cfg)\n"
        "    def _width(self, prompt):\n"
        "        return len(prompt)\n"
        "    def admit(self, prompt):\n"
        "        n = self._width(prompt)\n"
        "        padded = np.zeros((1, n), np.int32)\n"
        "        return self._prog(padded)\n")
    report = _run_engine_scoped(tmp_path, unbounded)
    assert [f.rule for f in report.findings] == ["RT109"]
    assert "request-varying" in report.findings[0].message
    assert report.new, "an unbounded trace key must fail the gate"

    bounded = unbounded.replace(
        "        n = self._width(prompt)\n"
        "        padded = np.zeros((1, n), np.int32)\n",
        "        n = self._width(prompt)\n"
        "        b = next(x for x in self.prompt_buckets if x >= n)\n"
        "        padded = np.zeros((1, b), np.int32)\n").replace(
        "    # rtlint: program-budget: 1\n"
        "    def _build",
        "    # rtlint: program-budget: len(prompt_buckets)\n"
        "    def _build").replace(
        "# rtlint: program-budget: 1\n"
        "def jit_step",
        "# rtlint: program-budget: len(prompt_buckets)\n"
        "def jit_step")
    report = _run_engine_scoped(tmp_path, bounded)
    assert not report.findings, [f.render() for f in report.findings]


def test_rt109_budget_exceeded_then_raised(tmp_path):
    over = (
        "# rtlint: program-budget: 1\n"
        "def jit_p(cfg, k=0):\n"
        "    return lambda *a: a\n"
        "class Eng:\n"
        "    # rtlint: program-budget: 1\n"
        "    def _build(self, cfg):\n"
        "        self._a = jit_p(cfg)\n"
        "        self._b = jit_p(cfg, 1)\n")
    report = _run_engine_scoped(tmp_path, over)
    assert [f.rule for f in report.findings] == ["RT109"]
    assert "budget_exceeded" in report.findings[0].key
    fixed = over.replace("    # rtlint: program-budget: 1\n",
                         "    # rtlint: program-budget: 2\n")
    assert not _run_engine_scoped(tmp_path, fixed).findings


def test_rt110_holds_checked_at_edges(tmp_path):
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "    def _bump(self):  # rtlint: holds=_lock\n"
        "        self._n += 1\n"
        "    def good(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n"
        "    def bad(self):\n"
        "        self._bump()\n")
    p = tmp_path / "m.py"
    p.write_text(src)
    report = run_paths([str(p)])
    assert [f.rule for f in report.findings] == ["RT110"]
    assert "C.bad->C._bump" in report.findings[0].key


def test_callgraph_resolves_repo_idioms(tmp_path):
    """Self methods, base-class methods, thread registration, nested
    with-lock context, and manual-acquire credit all resolve."""
    from tools.rtlint.callgraph import CallGraph
    from tools.rtlint.core import Module

    src = (
        "import threading\n"
        "class Base:\n"
        "    def shared(self):\n"
        "        return 1\n"
        "class C(Base):\n"
        "    def _run(self):\n"
        "        self.helper()\n"
        "    def helper(self):\n"
        "        return self.shared()\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._run)\n"
        "        return t\n"
        "    def locked_call(self):\n"
        "        with self._big_lock:\n"
        "            with self._small_lock:\n"
        "                self.helper()\n")
    p = tmp_path / "cg.py"
    p.write_text(src)
    mod = Module(str(p), str(p), src)
    g = CallGraph.build([mod])
    edges = {(e.caller or "<mod>", e.callee, e.kind): e for e in g.edges}
    rel = mod.relpath
    assert (f"{rel}::C._run", f"{rel}::C.helper", "call") in edges
    assert (f"{rel}::C.helper", f"{rel}::Base.shared", "call") in edges
    assert (f"{rel}::C.start", f"{rel}::C._run", "thread") in edges
    nested = edges[(f"{rel}::C.locked_call", f"{rel}::C.helper", "call")]
    assert nested.locks == frozenset({"_big_lock", "_small_lock"})


def test_decorator_line_directives_attach(tmp_path):
    """The shared loader attaches directives on ANY decorator line of a
    def (and the line above the stack) — the rtlint suppression and the
    rtsan contract read the same placement (fixture coverage lives in
    rt101_locks.py; this pins the loader directly, multi-line decorator
    included)."""
    from tools.rtlint.annotations import directive_map, func_directives

    src = (
        "import functools\n"
        "# rtlint: owner=driver\n"
        "@functools.lru_cache(\n"
        "    maxsize=64)\n"
        "@staticmethod  # rtlint: holds=_lock\n"
        "def f():\n"
        "    pass\n")
    import ast as _ast
    fn = _ast.parse(src).body[1]
    d = func_directives(directive_map(src), fn)
    assert d == {"owner": "driver", "holds": "_lock"}


def test_update_baseline_refuses_growth(tmp_path):
    """--update-baseline is a burn-down tool: shrinking is free, adding
    entries needs --allow-growth (ISSUE 15 satellite)."""
    bad = tmp_path / "serve"
    bad.mkdir()
    f = bad / "controller.py"
    one = ("def loop(work):\n"
           "    try:\n"
           "        work()\n"
           "    except Exception:\n"
           "        pass\n")
    two = one + ("def loop2(work):\n"
                 "    try:\n"
                 "        work()\n"
                 "    except Exception:\n"
                 "        pass\n")
    baseline = tmp_path / "baseline.json"

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "tools.rtlint", *args],
            cwd=REPO, capture_output=True, text=True, timeout=120)

    f.write_text(one)
    proc = cli(str(f), "--update-baseline", "--baseline", str(baseline))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "refusing to grow" in proc.stderr
    assert not baseline.exists()

    proc = cli(str(f), "--update-baseline", "--baseline", str(baseline),
               "--allow-growth")
    assert proc.returncode == 0, proc.stderr
    assert len(json.loads(baseline.read_text())["findings"]) == 1

    # Growing an EXISTING baseline refuses the same way...
    f.write_text(two)
    proc = cli(str(f), "--update-baseline", "--baseline", str(baseline))
    assert proc.returncode == 2 and "refusing" in proc.stderr
    assert len(json.loads(baseline.read_text())["findings"]) == 1
    # ...while shrinking (the burn-down direction) never needs a flag.
    f.write_text("def loop(work):\n    return work()\n")
    proc = cli(str(f), "--update-baseline", "--baseline", str(baseline))
    assert proc.returncode == 0, proc.stderr
    assert json.loads(baseline.read_text())["findings"] == []


def test_ci_gate_rtflow_rules_clean_on_ray_tpu():
    """The tier-1 budget/contract gate, rule-filtered: even under
    --rules RT109,RT110,RT111 the engine tree must be clean — every
    factory entrypoint declares its budget, every contract edge holds,
    every sync point is justified."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.rtlint", "ray_tpu/", "--check",
         "--rules", "RT109,RT110,RT111"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"


def test_engine_declared_budget_matches_actual_nano():
    """The declared budgets in serve/engine.py are the engine's REAL
    compiled-program count (ISSUE 15 satellite): exercise every prompt
    bucket plus a full handoff round-trip on nano CPU and compare the
    jit cache growth against the parsed program-budget declarations."""
    import jax
    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.models import gpt_decode as gd
    from ray_tpu.serve.engine import DecodeEngine
    from tools.rtlint import declared_budgets, parse_budget
    from tools.rtlint.core import Module

    cfg = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)
    buckets = (8, 16)
    eng = DecodeEngine(params, cfg, slots=3, chunk=4, max_len=40,
                       prompt_buckets=buckets, eos_token=-1)
    try:
        wrappers = {"_prefill": eng._prefill, "_step": eng._step,
                    "_export": eng._export, "_import": eng._import}
        pre = {k: w._cache_size() for k, w in wrappers.items()}
        rng = np.random.default_rng(3)
        # Every bucket decodes...
        for n in (5, 8, 11, 16):
            prompt = rng.integers(0, cfg.vocab_size, (n,)).astype(
                np.int32)
            assert len(list(eng.stream(prompt, 6))) >= 1
        # ...and the handoff path exports AND imports.
        prompt = rng.integers(0, cfg.vocab_size, (9,)).astype(np.int32)
        desc = eng.handoff(prompt, max_new=5)
        out = np.concatenate(list(eng.stream(prompt, 5)))
        resumed = eng.admit_prefilled(desc)
        from ray_tpu.serve.batching import _EngineStream
        got = np.concatenate(list(_EngineStream(resumed)))
        assert np.array_equal(out, got)
        actual = sum(w._cache_size() - pre[k]
                     for k, w in wrappers.items())

        src = open(os.path.join(REPO, "ray_tpu", "serve",
                                "engine.py")).read()
        mod = Module("engine.py", "serve/engine.py", src)
        decls = declared_budgets(mod)
        declared = parse_budget(decls["DecodeEngine._build_pool"][1])
        env = {"len(prompt_buckets)": len(buckets)}
        assert actual == declared.evaluate(env) == len(buckets) + 3
        # The verify budget is declared separately (spec engines).
        assert parse_budget(
            decls["DecodeEngine._bind_verify"][1]).evaluate(env) == 1
        # And the factory-level declarations in gpt_decode parse and
        # cover the flat factories' per-site bounds.
        gsrc = open(os.path.join(REPO, "ray_tpu", "models",
                                 "gpt_decode.py")).read()
        gdecls = declared_budgets(
            Module("gpt_decode.py", "models/gpt_decode.py", gsrc))
        assert parse_budget(gdecls["jit_prefill_into_slot"][1]
                            ).evaluate(env) == len(buckets)
        assert parse_budget(gdecls["jit_decode_chunk_slots"][1]
                            ).evaluate(env) == 1
    finally:
        eng.shutdown()
