"""Continuous-batching decode engine (ISSUE 5): slot-pool streams must
be token-identical to ``generate_chunked``, admission must happen at
chunk boundaries with per-slot freeing (EOS / max_new / deadline /
abandonment), the compiled-program set must stay bounded across ANY
admission pattern, and the ``@serve.batch(continuous=True)`` path must
carry it through a live deployment."""
import sys
import threading
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _ref_chunked(params, prompt, cfg, max_new, **kw):
    from ray_tpu.models import gpt_decode

    return np.concatenate([s[0] for s in gpt_decode.generate_chunked(
        params, np.asarray(prompt)[None], cfg, max_new, **kw)])


def _make_engine(nano, nano_params, **kw):
    from ray_tpu.serve.engine import DecodeEngine

    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    return DecodeEngine(nano_params, nano, **kw)


def test_engine_greedy_token_identity(nano, nano_params):
    """Four concurrent requests of mixed prompt/output lengths through a
    2-slot pool: every stream is token-identical to generate_chunked,
    the first slice is the lone prefill token (TTFT), and the engine's
    accounting sees all four admissions complete."""
    eng = _make_engine(nano, nano_params)
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, nano.vocab_size, (n,)).astype(np.int32)
                   for n in (5, 8, 11, 16)]
        max_news = [10, 7, 12, 3]
        refs = [_ref_chunked(nano_params, p, nano, mn, chunk=4, max_len=64)
                for p, mn in zip(prompts, max_news)]
        outs = {}

        def consume(i):
            chunks = list(eng.stream(prompts[i], max_news[i]))
            assert chunks[0].shape == (1,)
            assert all(c.shape[0] <= eng.chunk for c in chunks[1:])
            outs[i] = np.concatenate(chunks)

        threads = [threading.Thread(target=consume, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            assert (outs[i] == refs[i]).all(), (i, outs[i], refs[i])
        st = eng.stats()
        assert st["admitted"] == 4 and st["completed"] == 4
        assert st["tokens"] == sum(max_news)
        assert st["active_slots"] == 0
        assert 0.0 < st["avg_occupancy"] <= 1.0
        # Fused amortization: far fewer dispatches than tokens.
        assert st["dispatches_per_token"] < 0.5
    finally:
        eng.shutdown()


def test_engine_metrics_observed(nano, nano_params):
    """The engine driver observes slot occupancy / admission wait /
    dispatch counters into the serve metric set."""
    from ray_tpu._private.metrics import serve_metrics

    eng = _make_engine(nano, nano_params, deployment="metrics_probe")
    try:
        prompt = np.arange(8, dtype=np.int32) % nano.vocab_size
        list(eng.stream(prompt, 6))
        sm = serve_metrics()
        occ = dict(sm["engine_slot_occupancy"].collect())
        waits = dict(sm["engine_admission_wait"].collect())
        disp = dict(sm["engine_dispatches"].collect())
        key = (("deployment", "metrics_probe"),)
        assert key in occ and occ[key][-1] > 0      # n observations
        assert key in waits and waits[key][-1] > 0
        assert key in disp and disp[key] >= 1
    finally:
        eng.shutdown()


def test_engine_temperature_per_slot_rng(nano, nano_params):
    """Sampling threads one PRNG lane per slot: same seed reproduces the
    stream (and matches generate_chunked's chain exactly); a different
    seed diverges. Admission order of other slots must not perturb it."""
    import jax

    eng = _make_engine(nano, nano_params, temperature=1.0)
    try:
        prompt = np.random.default_rng(1).integers(
            0, nano.vocab_size, (8,)).astype(np.int32)
        a = np.concatenate(list(eng.stream(prompt, 8, seed=7)))
        # occupy slot 0 so the retry lands in a different slot
        noise = eng.submit(prompt, 24, seed=3)
        b = np.concatenate(list(eng.stream(prompt, 8, seed=7)))
        c = np.concatenate(list(eng.stream(prompt, 8, seed=8)))
        from ray_tpu.serve.batching import _drain_stream

        list(_drain_stream(noise))
        ref = _ref_chunked(nano_params, prompt, nano, 8, chunk=4,
                           max_len=64, temperature=1.0,
                           rng=jax.random.PRNGKey(7))
        assert (a == b).all()
        assert (a == ref).all(), (a, ref)
        assert not (a == c).all()
    finally:
        eng.shutdown()


def test_engine_eos_frees_slot(nano, nano_params):
    """A lane sampling EOS mid-chunk ends AT the EOS (trimmed slice, no
    trailing tokens) and its slot frees for the queued request instead
    of riding out the batch."""
    prompt = np.random.default_rng(2).integers(
        0, nano.vocab_size, (8,)).astype(np.int32)
    ref = _ref_chunked(nano_params, prompt, nano, 16, chunk=4, max_len=64)
    eos = int(ref[5])
    stop = int(np.argmax(ref == eos))
    eng = _make_engine(nano, nano_params, slots=1, eos_token=eos)
    try:
        # Second request queued behind the 1-slot pool: only an EOS free
        # can admit it.
        p2 = np.random.default_rng(3).integers(
            0, nano.vocab_size, (8,)).astype(np.int32)
        ref2 = _ref_chunked(nano_params, p2, nano, 6, chunk=4, max_len=64,
                            eos_token=eos)
        out = {}

        def consume(key, p, mn):
            out[key] = np.concatenate(list(eng.stream(p, mn)))

        t1 = threading.Thread(target=consume, args=("a", prompt, 16))
        t2 = threading.Thread(target=consume, args=("b", p2, 6))
        t1.start()
        time.sleep(0.05)
        t2.start()
        t1.join()
        t2.join()
        assert out["a"].shape[0] == stop + 1
        assert int(out["a"][-1]) == eos
        assert (out["a"] == ref[:stop + 1]).all()
        assert (out["b"] == ref2).all()
        assert eng.stats()["completed"] == 2
    finally:
        eng.shutdown()


def test_engine_deadline_handling(nano, nano_params):
    """Expired-while-queued requests fail without spending a prefill;
    a deadline passing mid-generation frees the slot at the next chunk
    boundary with RequestDeadlineExceeded on the lane."""
    from ray_tpu.serve import RequestDeadlineExceeded
    from ray_tpu.serve.batching import _drain_stream

    eng = _make_engine(nano, nano_params, slots=1)
    try:
        prompt = np.random.default_rng(4).integers(
            0, nano.vocab_size, (8,)).astype(np.int32)
        # already expired: dropped at admission, no prefill spent
        before = eng.stats()["prefills"]
        lane = eng.submit(prompt, 8, deadline_s=time.time() - 1)
        with pytest.raises(RequestDeadlineExceeded):
            list(_drain_stream(lane))
        assert eng.stats()["prefills"] == before
        assert eng.stats()["expired"] == 1

        # expires mid-generation: partial stream, then the typed error
        it = eng.stream(prompt, 40, deadline_s=time.time() + 0.03)
        got = []
        with pytest.raises(RequestDeadlineExceeded):
            for s in it:
                got.append(s)
                time.sleep(0.01)
        assert got, "deadline fired before the TTFT token"
        deadline = time.time() + 2
        while eng.stats()["active_slots"] and time.time() < deadline:
            time.sleep(0.01)
        assert eng.stats()["active_slots"] == 0
        # the freed slot still serves new work
        ref = _ref_chunked(nano_params, prompt, nano, 5, chunk=4,
                           max_len=64)
        assert (np.concatenate(list(eng.stream(prompt, 5))) == ref).all()
    finally:
        eng.shutdown()


def test_engine_abandoned_consumer_frees_slot(nano, nano_params):
    """A consumer walking away mid-stream closes its lane; the driver
    frees the slot at the next boundary instead of decoding for nobody."""
    eng = _make_engine(nano, nano_params, slots=1)
    try:
        prompt = np.random.default_rng(5).integers(
            0, nano.vocab_size, (8,)).astype(np.int32)
        it = eng.stream(prompt, 40)
        next(it)
        it.close()
        deadline = time.time() + 2
        while eng.stats()["active_slots"] and time.time() < deadline:
            time.sleep(0.01)
        st = eng.stats()
        assert st["active_slots"] == 0 and st["abandoned"] == 1
        ref = _ref_chunked(nano_params, prompt, nano, 4, chunk=4,
                           max_len=64)
        assert (np.concatenate(list(eng.stream(prompt, 4))) == ref).all()
        # close BEFORE the first pull (consumer gone while still queued
        # for admission): dropped at the boundary, no prefill spent
        pre = eng.stats()["prefills"]
        it2 = eng.stream(prompt, 40)
        it2.close()
        deadline = time.time() + 2
        while eng.stats()["abandoned"] < 2 and time.time() < deadline:
            time.sleep(0.01)
        st = eng.stats()
        assert st["abandoned"] == 2 and st["prefills"] == pre
        assert st["active_slots"] == 0
    finally:
        eng.shutdown()


def test_engine_recompile_guard(nano, nano_params):
    """The compiled-program set is bounded by the bucket config, NOT the
    admission pattern: after one warm pass over the buckets, a storm of
    varied prompts/output lengths/arrival orders adds ZERO XLA programs
    — no retrace per admitted request."""
    from ray_tpu.models.gpt_decode import (jit_decode_chunk_slots,
                                           jit_prefill_into_slot)

    eng = _make_engine(nano, nano_params, slots=3, max_len=48,
                       prompt_buckets=(8, 16))
    try:
        rng = np.random.default_rng(6)

        def storm(n, lens):
            threads = []
            for i in range(n):
                p = rng.integers(0, nano.vocab_size,
                                 (int(lens[i % len(lens)]),)
                                 ).astype(np.int32)
                mn = int(rng.integers(1, 12))
                t = threading.Thread(
                    target=lambda p=p, mn=mn: list(eng.stream(p, mn)))
                t.start()
                threads.append(t)
                if i % 3 == 0:
                    time.sleep(0.01)  # stagger: mid-stream admissions
            for t in threads:
                t.join()

        storm(4, [5, 16])             # warm pass: touch both buckets
        pre_prefill = eng._prefill._cache_size()
        pre_step = eng._step._cache_size()
        assert pre_prefill >= 2       # one program per prompt bucket
        storm(12, [1, 3, 7, 8, 9, 12, 15, 16])
        assert eng._prefill._cache_size() == pre_prefill
        assert eng._step._cache_size() == pre_step
        # the lru wrappers are shared per static-knob tuple, so repeated
        # engine construction reuses (not duplicates) the programs
        assert jit_prefill_into_slot.cache_info().currsize <= 64
        assert jit_decode_chunk_slots.cache_info().currsize <= 64
        assert jit_prefill_into_slot(nano, 0.0) is eng._prefill
    finally:
        eng.shutdown()


def test_engine_submit_validation(nano, nano_params):
    from ray_tpu.serve.engine import EngineShutdownError

    eng = _make_engine(nano, nano_params, max_len=32,
                       prompt_buckets=(8, 16))
    try:
        with pytest.raises(ValueError, match="exceeds largest prompt"):
            eng.submit(np.zeros(17, np.int32), 4)
        with pytest.raises(ValueError, match="exceeds cache length"):
            eng.submit(np.zeros(16, np.int32), 17)
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(np.zeros(0, np.int32), 4)
        # max_new=0: an instantly-finished stream, no slot spent
        assert list(eng.stream(np.zeros(4, np.int32), 0)) == []
    finally:
        eng.shutdown()
    with pytest.raises(EngineShutdownError):
        eng.submit(np.zeros(4, np.int32), 4)


def test_batch_buckets_must_cover_max_batch_size():
    """Satellite: custom buckets that cannot hold a full batch are a
    decorate-time ValueError, not a silent negative-count 'pad'."""
    from ray_tpu import serve

    with pytest.raises(ValueError, match="do not cover"):
        @serve.batch(max_batch_size=8, pad_to_bucket=True, buckets=(2, 4))
        def bad(items):
            return items

    with pytest.raises(ValueError, match="positive"):
        @serve.batch(max_batch_size=4, buckets=(0, 4))
        def worse(items):
            return items

    @serve.batch(max_batch_size=8, pad_to_bucket=True, buckets=(2, 4, 8))
    def good(items):
        return items

    with pytest.raises(ValueError, match="continuous=True"):
        @serve.batch(continuous=True, stream=True)
        def conflicted(item):
            return item


def test_continuous_serve_deployment(rt_cluster, nano, nano_params):
    """Live data plane: @serve.batch(continuous=True) feeds the engine's
    admission queue from concurrent handle callers and streams per-slot
    slices back through the replica — token-identical to the library
    reference, with the engine's accounting visible via the handle."""
    from ray_tpu import serve

    rng = np.random.default_rng(8)
    prompts = [rng.integers(0, 512, (8,)).astype(np.int32)
               for _ in range(3)]
    max_news = [9, 5, 12]
    refs = [_ref_chunked(nano_params, p, nano, mn, chunk=4, max_len=64)
            for p, mn in zip(prompts, max_news)]

    serve.start(proxy=False)
    try:
        @serve.deployment(max_ongoing_requests=8)
        class ContinuousGPT:
            def __init__(self):
                import jax

                from ray_tpu.models import gpt
                from ray_tpu.serve.engine import DecodeEngine

                cfg = gpt.CONFIGS["nano"]
                params = gpt.init_params(jax.random.PRNGKey(0), cfg)
                self.engine = DecodeEngine(
                    params, cfg, slots=2, chunk=4, max_len=64,
                    prompt_buckets=(8,), deployment="cont_test")

            @serve.batch(continuous=True)
            def decode(self, request):
                return self.engine, {
                    "prompt": np.asarray(request["prompt"], np.int32),
                    "max_new": int(request["max_new"])}

            def stats(self):
                return self.engine.stats()

            def __call__(self, request):
                return self.decode(request)

        h = serve.run(ContinuousGPT.bind(), name="cont",
                      route_prefix=None)
        out = {}

        def call(i):
            items = list(h.options(stream=True).remote(
                {"prompt": prompts[i].tolist(),
                 "max_new": max_news[i]}))
            assert len(items[0]) == 1          # TTFT token alone
            out[i] = np.concatenate([np.asarray(x) for x in items])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(3):
            assert (out[i] == refs[i]).all(), (i, out[i], refs[i])
        st = h.options(method_name="stats").remote().result(timeout=30)
        assert st["admitted"] == 3 and st["completed"] == 3
        # flatten_chunks still flattens engine slices to tokens
        toks = list(h.options(stream=True, flatten_chunks=True).remote(
            {"prompt": prompts[0].tolist(), "max_new": max_news[0]}))
        assert toks == [int(t) for t in refs[0]]
        serve.delete("cont")
    finally:
        serve.shutdown()


def test_continuous_smoke_benchmark():
    """Satellite CI hook: the benchmark's --continuous --smoke A/B runs
    end to end (static gang AND engine under the same Poisson schedule)
    and emits the A/B summary line."""
    import json
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks", "serve_gpt.py"),
         "--continuous", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    ab = [r for r in rows if r["metric"].endswith("continuous_ab")]
    assert ab, rows
    assert ab[0]["smoke"] is True and ab[0]["value"] > 0
    modes = {r["metric"]: r for r in rows}
    assert any("continuous_mode" in m for m in modes)
    assert any("static_mode" in m for m in modes)
