"""Kill-under-load chaos tests (reference: ``_private/test_utils.py``
``ResourceKillerActor``/``WorkerKillerActor`` + ``tests/chaos/`` — every
fault-tolerance invariant gets a version that holds while processes are
actively being killed, not just after a single orchestrated death)."""
import time

import pytest


def _actor_worker_pid(rt, actor_id_hex: str):
    for w in rt.state("workers"):
        if actor_id_hex[:8] in str(w["assignment"]):
            return w["pid"]
    return None


def test_tasks_complete_under_worker_chaos(rt_fresh):
    """Retryable tasks must all produce correct results while a chaos
    thread SIGKILLs random workers throughout the run."""
    rt = rt_fresh
    from ray_tpu.testing import WorkerKiller

    @rt.remote
    def work(i):
        time.sleep(0.05)
        return i * 2

    n = 80
    with WorkerKiller(interval_s=0.25) as killer:
        refs = [work.options(max_retries=8).remote(i) for i in range(n)]
        out = rt.get(refs, timeout=120)
    assert out == [i * 2 for i in range(n)]
    assert killer.kills >= 1, "chaos thread never killed anything"


def test_actor_restart_while_calls_in_flight(rt_fresh):
    """An actor with max_restarts must come back and serve new calls
    after its worker is killed mid-stream — repeatedly."""
    rt = rt_fresh

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            time.sleep(0.01)
            return self.n

    c = Counter.options(max_restarts=10).remote()
    assert rt.get(c.inc.remote()) == 1
    aid = c._actor_id.hex()

    import os
    import signal

    survived_rounds = 0
    for _ in range(3):
        # calls in flight...
        refs = [c.inc.remote() for _ in range(20)]
        pid = _actor_worker_pid(rt, aid)
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        # in-flight calls may fail (restart loses in-memory state); the
        # invariant is that the actor RECOVERS and serves new calls.
        for r in refs:
            try:
                rt.get(r, timeout=60)
            except Exception:  # noqa: BLE001 - expected for killed batch
                pass
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                rt.get(c.inc.remote(), timeout=30)
                survived_rounds += 1
                break
            except Exception:  # noqa: BLE001 - still restarting
                time.sleep(0.2)
    assert survived_rounds == 3, (
        f"actor only recovered {survived_rounds}/3 times")


def test_data_pipeline_under_chaos(rt_fresh):
    """A Dataset map over many blocks completes correctly under worker
    kills (stage tasks ride the task-retry path)."""
    rt = rt_fresh
    from ray_tpu import data as rtd
    from ray_tpu.data.executor import task_pool_stage
    from ray_tpu.testing import WorkerKiller

    blocks = [rt.put([i, i + 1]) for i in range(30)]

    def slow_double(b):
        import time as _t

        _t.sleep(0.05)
        return [x * 2 for x in b]

    with WorkerKiller(interval_s=0.3) as killer:
        fn = rt.remote(slow_double).options(max_retries=8)
        out_refs = list(task_pool_stage(iter(blocks), fn))
        out = rt.get(out_refs, timeout=120)
    assert out == [[2 * i, 2 * (i + 1)] for i in range(30)]


def test_named_actor_reacquire_after_chaos(rt_fresh):
    """get_actor on a named, restartable actor keeps working across a
    kill (reference named-actor FT semantics)."""
    rt = rt_fresh

    @rt.remote
    class KV:
        def put(self, k, v):
            setattr(self, f"_{k}", v)
            return True

        def get(self, k):
            return getattr(self, f"_{k}", None)

    kv = KV.options(name="chaos-kv", max_restarts=5).remote()
    assert rt.get(kv.put.remote("a", 1))
    import os
    import signal

    pid = _actor_worker_pid(rt, kv._actor_id.hex())
    if pid:
        os.kill(pid, signal.SIGKILL)
    h = rt.get_actor("chaos-kv")
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            rt.get(h.put.remote("b", 2), timeout=30)
            ok = True
            break
        except Exception:  # noqa: BLE001 - restarting
            time.sleep(0.2)
    assert ok, "named actor never recovered"
    assert rt.get(h.get.remote("b")) == 2
