"""Request-lifecycle hardening under overload: deadline propagation,
budgeted retries, and load shedding (ISSUE 2; reference: the reference's
``test_request_timeout.py`` / backpressure tests, rebuilt for this
runtime's proxy + router + replica admission stack).

The fault-injection hook (``Replica.set_fault_injection`` via
``ray_tpu.testing``) replaces real slowness: latency saturates
``max_ongoing_requests`` on demand and the invocation log proves no
request ever STARTED after its deadline."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve import (BackPressureError, RequestDeadlineExceeded)
from ray_tpu.testing import (ReplicaKiller, clear_replica_fault_injection,
                             get_replica_invocation_logs,
                             set_replica_fault_injection)


@pytest.fixture
def serve_instance(rt_cluster):
    serve.start(http_options={"host": "127.0.0.1", "port": 0})
    yield serve
    serve.shutdown()


@serve.deployment
class Echo:
    def __call__(self, x):
        if hasattr(x, "json"):  # HTTP ingress
            x = x.json()
        return {"y": x}


def test_shed_503_with_retry_after(serve_instance):
    """Offered load >> capacity: the proxy sheds with 503 + Retry-After
    while accepted requests still answer correctly."""
    app = Echo.options(num_replicas=1, max_ongoing_requests=2,
                       max_queued_requests=2).bind()
    serve.run(app, name="shed", route_prefix="/shed")
    assert set_replica_fault_injection("shed", "Echo", latency_s=0.8) == 1
    port = serve.status()["http"]["port"]

    results = []
    lock = threading.Lock()

    def call(i):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/shed", data=json.dumps(i).encode())
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = (resp.status, json.loads(resp.read()), None)
        except urllib.error.HTTPError as e:
            out = (e.code, None, e.headers.get("Retry-After"))
        with lock:
            results.append((i, out))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    ok = [(i, r) for i, r in results if r[0] == 200]
    shed = [(i, r) for i, r in results if r[0] == 503]
    assert shed, f"nothing shed: {[r[0] for _, r in results]}"
    assert ok, "everything shed; accepted requests must still answer"
    for i, r in ok:
        assert r[1] == {"y": i}
    for _, r in shed:
        assert r[2] is not None and int(r[2]) >= 1, \
            f"503 without a Retry-After contract: {r!r}"

    # Shed totals reach the controller's status dict via the proxy
    # health pass (period 5 s).
    deadline = time.time() + 20
    while time.time() < deadline:
        life = serve.status().get("lifecycle", {})
        if life.get("proxy_shed_total", 0) >= len(shed):
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"status never surfaced shed counters: "
                    f"{serve.status().get('lifecycle')}")
    clear_replica_fault_injection("shed", "Echo")
    serve.delete("shed")


def test_handle_backpressure_typed_error(serve_instance):
    """Handle callers get the typed BackPressureError (the gRPC/handle
    equivalent of the proxy's 503), raised at submission time.

    ``handle.remote()`` BLOCKS in router admission while a slot is
    unavailable, so saturation needs background threads: one occupies
    the single in-flight slot, one occupies the single queue slot, and
    then the main thread's submission must shed immediately."""
    app = Echo.options(num_replicas=1, max_ongoing_requests=1,
                       max_queued_requests=1).bind()
    h = serve.run(app, name="bp", route_prefix=None)
    assert h.remote(1).result(timeout=10) == {"y": 1}  # warm the router
    set_replica_fault_injection("bp", "Echo", latency_s=1.5)

    def occupy():
        try:
            h.options(timeout_s=5.0).remote(0).result()
        except Exception:  # noqa: BLE001 - only saturation matters here
            pass

    threads = [threading.Thread(target=occupy) for _ in range(2)]
    threads[0].start()
    time.sleep(0.3)  # thread 0 holds the in-flight slot (1.5 s latency)
    threads[1].start()
    time.sleep(0.3)  # thread 1 is parked in the admission queue
    with pytest.raises(BackPressureError):
        h.remote(99)
    for t in threads:
        t.join()
    clear_replica_fault_injection("bp", "Echo")
    serve.delete("bp")


def test_expired_request_dropped_at_replica(serve_instance):
    """A request whose deadline already passed is rejected before user
    code runs — the invocation log records zero starts for it."""
    app = Echo.options(num_replicas=1).bind()
    h = serve.run(app, name="expired", route_prefix=None)
    set_replica_fault_injection("expired", "Echo")  # arm logging only

    with pytest.raises(RequestDeadlineExceeded):
        h.options(timeout_s=0.0).remote(1).result()
    assert get_replica_invocation_logs("expired", "Echo") == []

    # A sane deadline still flows through to completion.
    assert h.options(timeout_s=30.0).remote(2).result() == {"y": 2}
    log = get_replica_invocation_logs("expired", "Echo")
    assert len(log) == 1 and log[0]["deadline"] is not None
    clear_replica_fault_injection("expired", "Echo")
    serve.delete("expired")


def test_expired_entry_dropped_at_batcher(serve_instance):
    """The batcher drops entries whose deadline passed while queued; live
    entries in the same flush still execute."""

    @serve.deployment(max_ongoing_requests=8)
    class Batched:
        def __init__(self):
            self.sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.4)
        def predict(self, xs):
            self.sizes.append(len(xs))
            return [x * 2 for x in xs]

        def __call__(self, x):
            return self.predict(x)

        def seen(self, _):
            return self.sizes

    h = serve.run(Batched.bind(), name="batchdl", route_prefix=None)
    errors = {}
    results = {}

    def call(i, timeout_s):
        try:
            results[i] = h.options(timeout_s=timeout_s).remote(i).result()
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    # Entry 0's 0.05 s deadline expires during the 0.4 s batch wait;
    # entry 1 has plenty of budget and must survive the same flush.
    t0 = threading.Thread(target=call, args=(0, 0.05))
    t1 = threading.Thread(target=call, args=(1, 30.0))
    t0.start()
    t1.start()
    t0.join()
    t1.join()
    assert results.get(1) == 2
    assert isinstance(errors.get(0), RequestDeadlineExceeded), errors
    sizes = h.seen.remote(None).result(timeout=10)
    assert sizes and max(sizes) == 1, \
        f"expired entry reached the batch handler: {sizes}"
    serve.delete("batchdl")


def test_nested_call_inherits_outer_deadline(serve_instance):
    """A composed deployment's nested handle call inherits the OUTER
    request's remaining deadline instead of minting a fresh 60 s window
    — the whole call tree shares one budget."""

    @serve.deployment
    class Inner:
        def __call__(self, x):
            return x

    @serve.deployment
    class Outer:
        def __init__(self, inner):
            self.inner = inner

        def __call__(self, x):
            # No explicit timeout: without inheritance this would wait
            # the full 60 s default against the saturated Inner.
            return self.inner.remote(x).result()

    app = Outer.bind(Inner.options(num_replicas=1,
                                   max_ongoing_requests=16).bind())
    h = serve.run(app, name="nested", route_prefix=None)
    assert h.remote(5).result(timeout=10) == 5
    set_replica_fault_injection("nested", "Inner", latency_s=3.0)
    t0 = time.time()
    with pytest.raises((RequestDeadlineExceeded, TimeoutError)):
        h.options(timeout_s=0.5).remote(1).result()
    assert time.time() - t0 < 5, \
        "nested call did not inherit the outer 0.5 s deadline"
    clear_replica_fault_injection("nested", "Inner")
    serve.delete("nested")


def test_budgeted_retry_exhaustion_raises_original(serve_instance):
    """With the retry budget drained, a replica failure surfaces as the
    ORIGINAL error instead of silently resubmitting forever."""

    @serve.deployment(num_replicas=1, health_check_period_s=30.0)
    class Fragile:
        def __call__(self, x):
            return x

        def die(self, _):
            import os

            os._exit(1)

    h = serve.run(Fragile.bind(), name="exhaust", route_prefix=None)
    assert h.remote(1).result(timeout=10) == 1

    from ray_tpu.serve.handle import get_router

    router = get_router("exhaust", "Fragile")
    router.budget.reserve_per_s = 0.0  # no trickle back
    with router.budget._lock:
        router.budget._tokens = 0.0
    t0 = time.time()
    with pytest.raises(Exception) as ei:
        h.die.remote(None).result(timeout=30)
    # The original replica-death error, not a timeout and not a
    # backpressure/deadline mapping.
    assert not isinstance(ei.value, (BackPressureError,
                                     RequestDeadlineExceeded, TimeoutError))
    assert time.time() - t0 < 25, "exhausted budget should fail fast"
    serve.delete("exhaust")


def test_streaming_retry_before_first_item(serve_instance):
    """Stream setup against a dead replica transparently re-routes as
    long as no item was delivered (the router's membership view is up to
    1 s stale after a kill — streams opened in that window land on the
    corpse and must re-pick)."""

    @serve.deployment(num_replicas=2, health_check_period_s=30.0)
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield i * 10

    h = serve.run(Streamer.bind(), name="sretry", route_prefix=None)
    # Warm the router's membership view, then kill one replica behind
    # its back.
    assert list(h.options(stream=True).remote(3)) == [0, 10, 20]
    from ray_tpu.testing import _serve_replica_handles

    handles = _serve_replica_handles("sretry", "Streamer")
    assert len(handles) == 2
    rt.kill(next(iter(handles.values())))
    deadline = time.time() + 2
    ok = 0
    while time.time() < deadline:
        out = list(h.options(stream=True).remote(4))
        assert out == [0, 10, 20, 30], out
        ok += 1
    assert ok > 4  # several streams ran inside the stale-view window
    serve.delete("sretry")


def test_overload_no_invocation_after_deadline(serve_instance):
    """Acceptance: under offered load >= 3x capacity, zero replica
    invocations start after their request deadline has passed, and
    accepted-request latency stays bounded by the deadline window."""
    app = Echo.options(num_replicas=1, max_ongoing_requests=2,
                       max_queued_requests=4).bind()
    h = serve.run(app, name="satur", route_prefix=None)
    set_replica_fault_injection("satur", "Echo", latency_s=0.25)

    outcomes = {"ok": 0, "shed": 0, "expired": 0, "other": 0}
    durations = []
    lock = threading.Lock()
    timeout_s = 2.0

    def call(i):
        t0 = time.time()
        try:
            h.options(timeout_s=timeout_s).remote(i).result()
            key = "ok"
        except BackPressureError:
            key = "shed"
        except (RequestDeadlineExceeded, TimeoutError):
            key = "expired"
        except Exception:  # noqa: BLE001
            key = "other"
        with lock:
            outcomes[key] += 1
            durations.append(time.time() - t0)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert outcomes["ok"] > 0, outcomes
    assert outcomes["shed"] > 0, f"3x overload never shed: {outcomes}"
    assert outcomes["other"] == 0, outcomes
    # Bounded latency: nobody waited meaningfully past the deadline
    # window (no unbounded queue growth).
    assert max(durations) < timeout_s + 1.0, max(durations)
    log = get_replica_invocation_logs("satur", "Echo")
    assert log, "fault-injection log empty"
    late = [e for e in log
            if e["deadline"] is not None and e["start"] > e["deadline"]]
    assert not late, f"{len(late)} invocations started past their deadline"
    clear_replica_fault_injection("satur", "Echo")
    serve.delete("satur")


def test_kill_under_load_with_replica_killer(serve_instance):
    """Kill-under-load (test_chaos.py pattern, serve edition): traffic
    keeps making progress while a ReplicaKiller snipes replicas, and the
    controller heals the deployment afterwards."""

    @serve.deployment(num_replicas=2, health_check_period_s=0.2)
    class Svc:
        def __call__(self, x):
            time.sleep(0.05)
            return x + 1

    h = serve.run(Svc.bind(), name="chaos", route_prefix=None)
    ok = [0]
    lock = threading.Lock()

    def client(base):
        for i in range(15):
            try:
                if h.remote(base + i).result(timeout=30) == base + i + 1:
                    with lock:
                        ok[0] += 1
            except Exception:  # noqa: BLE001 - budget may run dry
                pass

    with ReplicaKiller("chaos", "Svc", interval_s=0.3) as killer:
        threads = [threading.Thread(target=client, args=(100 * c,))
                   for c in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert killer.kills >= 1, "killer never fired"
    assert ok[0] >= 45, f"only {ok[0]}/60 requests survived the chaos"
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()["applications"]["chaos"]["deployments"]["Svc"]
        if st["replicas"] == 2:
            break
        time.sleep(0.2)
    else:
        pytest.fail("controller never healed back to 2 replicas")
    serve.delete("chaos")


@pytest.mark.slow
def test_long_chaos_streams_and_unary_mixed(serve_instance):
    """Long chaos soak (slow tier): mixed unary + streaming traffic under
    sustained replica kills keeps a high goodput and ends healthy."""

    @serve.deployment(num_replicas=3, health_check_period_s=0.2)
    class Mixed:
        def __call__(self, x):
            time.sleep(0.01)
            return x * 2

        def stream(self, n):
            for i in range(n):
                yield i

    h = serve.run(Mixed.bind(), name="soak", route_prefix=None)
    ok = [0]
    total = [0]
    lock = threading.Lock()

    def client(c):
        for i in range(30):
            with lock:
                total[0] += 1
            try:
                if i % 3 == 0:
                    out = list(h.options(
                        stream=True, method_name="stream").remote(4))
                    good = out == [0, 1, 2, 3]
                else:
                    good = h.remote(i).result(timeout=30) == i * 2
                if good:
                    with lock:
                        ok[0] += 1
            except Exception:  # noqa: BLE001
                pass

    with ReplicaKiller("soak", "Mixed", interval_s=0.5) as killer:
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert killer.kills >= 3
    assert ok[0] / total[0] >= 0.8, f"goodput {ok[0]}/{total[0]}"
    serve.delete("soak")
