"""HTTP GCE connector conformance (reference:
``python/ray/autoscaler/_private/gcp/node_provider.py`` — REST
transport for the TPU queued-resources API). The strict
``FakeGCEConnector`` is served over a real localhost socket by
``LocalGCEAPIServer``; ``HTTPGCEConnector`` must drive the full slice
lifecycle through actual HTTP with correct auth, error mapping, and
retry behavior."""
import threading

import pytest

from ray_tpu.autoscaler import (FakeGCEConnector, GCESliceBackend,
                                HTTPGCEConnector, LocalGCEAPIServer)

PARENT = "projects/p1/locations/us-central2-b"
BODY = {"tpu": {"node_spec": [{
    "parent": PARENT, "node_id": "qr-a",
    "node": {"accelerator_type": "v5litepod-16",
             "runtime_version": "tpu-ubuntu2204-base"}}]}}


@pytest.fixture()
def served_fake():
    fake = FakeGCEConnector(polls_per_state=1)
    with LocalGCEAPIServer(fake) as srv:
        yield fake, HTTPGCEConnector(srv.endpoint, retry_base_s=0.01)


def test_http_lifecycle_states(served_fake):
    fake, conn = served_fake
    op = conn.create_queued_resource(PARENT, "qr-a", BODY)
    assert op["name"].endswith("op-qr-a") and op["done"] is False
    name = f"{PARENT}/queuedResources/qr-a"
    states = [conn.get_queued_resource(name)["state"]["state"]
              for _ in range(5)]
    assert states[:4] == ["CREATING", "WAITING_FOR_RESOURCES",
                         "PROVISIONING", "ACTIVE"]
    assert conn.delete_queued_resource(name)["done"] is True
    # the fake's audit log proves every verb crossed the wire
    assert [r[0] for r in fake.requests] == \
        ["create"] + ["get"] * 5 + ["delete"]


def test_http_error_mapping(served_fake):
    _, conn = served_fake
    with pytest.raises(KeyError, match="not found"):
        conn.get_queued_resource(f"{PARENT}/queuedResources/ghost")
    with pytest.raises(ValueError, match="node_spec"):
        conn.create_queued_resource(PARENT, "bad", {"tpu": {}})
    with pytest.raises(ValueError, match="queuedResourceId"):
        conn._request("POST", f"/v2/{PARENT}/queuedResources", {})


def test_http_bearer_auth():
    fake = FakeGCEConnector()
    with LocalGCEAPIServer(fake, require_token="s3cret") as srv:
        noauth = HTTPGCEConnector(srv.endpoint, retry_base_s=0.01)
        with pytest.raises(PermissionError, match="bearer"):
            noauth.get_queued_resource(f"{PARENT}/queuedResources/x")
        authed = HTTPGCEConnector(srv.endpoint, retry_base_s=0.01,
                                  token_provider=lambda: "s3cret")
        authed.create_queued_resource(PARENT, "qr-a", BODY)
        assert fake.requests[-1][0] == "create"


def test_http_retries_transient_503():
    """First two GETs 503 at the HTTP layer; the connector retries
    through to the fake's real answer."""
    fake = FakeGCEConnector()
    fail_left = [2]

    class Flaky(FakeGCEConnector.__bases__[0]):  # GCEConnector
        def create_queued_resource(self, parent, qr_id, body):
            return fake.create_queued_resource(parent, qr_id, body)

        def get_queued_resource(self, name):
            if fail_left[0] > 0:
                fail_left[0] -= 1
                raise RuntimeError("upstream hiccup")  # -> 500
            return fake.get_queued_resource(name)

        def delete_queued_resource(self, name):
            return fake.delete_queued_resource(name)

    with LocalGCEAPIServer(Flaky()) as srv:
        conn = HTTPGCEConnector(srv.endpoint, retry_base_s=0.01)
        conn.create_queued_resource(PARENT, "qr-a", BODY)
        doc = conn.get_queued_resource(f"{PARENT}/queuedResources/qr-a")
        assert doc["state"]["state"] == "CREATING" and fail_left[0] == 0


def test_create_replay_is_idempotent(served_fake):
    """A retried create whose first attempt committed (response lost on
    the wire) replays into 'already exists' — the connector confirms
    via GET and reports success rather than failing a live slice."""
    fake, conn = served_fake
    op1 = conn.create_queued_resource(PARENT, "qr-a", BODY)
    op2 = conn.create_queued_resource(PARENT, "qr-a", BODY)  # replay
    assert op2["name"] == op1["name"] and op2["done"] is False
    assert len(fake.resources) == 1


def test_http_unreachable_raises_connection_error():
    conn = HTTPGCEConnector("http://127.0.0.1:1", max_retries=1,
                            retry_base_s=0.01)
    with pytest.raises(ConnectionError, match="unreachable"):
        conn.get_queued_resource(f"{PARENT}/queuedResources/x")


def test_slice_backend_over_http():
    """GCESliceBackend end-to-end through the HTTP transport: launch a
    4-host slice (one queued resource), finalize polls to ACTIVE over
    the wire, terminate deletes exactly once."""
    fake = FakeGCEConnector(polls_per_state=1)
    with LocalGCEAPIServer(fake, require_token="tok") as srv:
        conn = HTTPGCEConnector(srv.endpoint, retry_base_s=0.01,
                                token_provider=lambda: "tok")
        backend = GCESliceBackend(conn, "v5e-16", project="p1",
                                  poll_interval_s=0.01)
        handles = [backend.launch("slice-0", w, {}, 4, 4)
                   for w in range(4)]
        backend.finalize("slice-0", handles)
        for h in handles:
            backend.terminate(h)
    verbs = [r[0] for r in fake.requests]
    assert verbs.count("create") == 1 and verbs.count("delete") == 1
    assert fake.requests[0][3]["tpu"]["node_spec"][0]["node"][
        "accelerator_type"] == "v5litepod-16"


def test_concurrent_http_clients():
    """ThreadingHTTPServer + per-request connections: 8 threads create
    and poll distinct queued resources without cross-talk."""
    fake = FakeGCEConnector(polls_per_state=1)
    errors = []
    with LocalGCEAPIServer(fake) as srv:
        def worker(i):
            try:
                conn = HTTPGCEConnector(srv.endpoint, retry_base_s=0.01)
                body = {"tpu": {"node_spec": [{
                    "parent": PARENT, "node_id": f"qr-{i}",
                    "node": {"accelerator_type": "v5litepod-16",
                             "runtime_version": "v2"}}]}}
                conn.create_queued_resource(PARENT, f"qr-{i}", body)
                name = f"{PARENT}/queuedResources/qr-{i}"
                for _ in range(4):
                    conn.get_queued_resource(name)
                assert conn.get_queued_resource(
                    name)["state"]["state"] == "ACTIVE"
            except Exception as e:
                errors.append((i, e))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert len(fake.resources) == 8
