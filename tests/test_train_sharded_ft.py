"""Automatic sharded-checkpoint fault tolerance: orbax saves derive a
deterministic shared path from the session (no hand-agreed path), and a
gang restart resumes from the latest one (reference:
train/_internal/storage.py:289 derived checkpoint dirs)."""
import os

import numpy as np


def test_session_derives_deterministic_sharded_path(tmp_path):
    """Two lockstep sessions (same storage_dir/incarnation) derive the
    SAME path sequence — the multi-process agreement property."""
    from ray_tpu.train.session import _TrainSession

    from unittest import mock

    a = _TrainSession(world_rank=0, world_size=2,
                      storage_dir=str(tmp_path), incarnation=1)
    b = _TrainSession(world_rank=1, world_size=2,
                      storage_dir=str(tmp_path), incarnation=1)
    # Multi-controller (jax.distributed): rank-INDEPENDENT shared path.
    with mock.patch("jax.process_count", return_value=2):
        p0a, p1a = (a.next_sharded_checkpoint_path(),
                    a.next_sharded_checkpoint_path())
        p0b, p1b = (b.next_sharded_checkpoint_path(),
                    b.next_sharded_checkpoint_path())
    assert p0a == p0b and p1a == p1b and p0a != p1a
    assert p0a.startswith(str(tmp_path))
    # Single-controller gang: independent writers get per-rank paths.
    a2 = _TrainSession(world_rank=0, world_size=2,
                       storage_dir=str(tmp_path), incarnation=1)
    b2 = _TrainSession(world_rank=1, world_size=2,
                       storage_dir=str(tmp_path), incarnation=1)
    assert a2.next_sharded_checkpoint_path() != \
        b2.next_sharded_checkpoint_path()


def test_sharded_save_without_path_inside_session(tmp_path):
    """from_sharded_state() with NO path lands in the session-derived
    dir and restores through get_checkpoint(). Single-controller ranks
    keep the normal move + bounded GC (their dirs are full per-rank
    checkpoints); a genuinely COLLECTIVE dir (multi-controller) stays
    in place — moving it to a rank-suffixed name would split one
    checkpoint's shards across names."""
    from unittest import mock

    import jax

    from ray_tpu.train import session as sess
    from ray_tpu.train.checkpoint import Checkpoint

    s = sess.init_session(world_rank=0, world_size=1,
                          storage_dir=str(tmp_path), incarnation=0)
    try:
        state = {"w": jax.numpy.arange(8.0), "step": jax.numpy.int32(3)}
        ckpt = Checkpoint.from_sharded_state(state)
        assert ckpt.path.startswith(str(tmp_path)), ckpt.path
        s.report({"loss": 1.0}, checkpoint=ckpt)
        like = {"w": jax.numpy.zeros(8), "step": jax.numpy.int32(0)}
        out = s.get_checkpoint().load_sharded_state(like)
        np.testing.assert_allclose(np.asarray(out["w"]), np.arange(8.0))
        assert int(out["step"]) == 3

        # Collective save (multi-controller): the shared dir is NOT
        # moved or GC'd by any rank.
        ckpt2 = Checkpoint.from_sharded_state(
            {"w": jax.numpy.arange(4.0), "step": jax.numpy.int32(9)})
        with mock.patch("jax.process_count", return_value=2):
            s.report({"loss": 0.5}, checkpoint=ckpt2)
        assert s.get_checkpoint().path == ckpt2.path  # left in place
        out2 = s.get_checkpoint().load_sharded_state(
            {"w": jax.numpy.zeros(4), "step": jax.numpy.int32(0)})
        assert int(out2["step"]) == 9
    finally:
        sess.shutdown_session()


def test_gang_restart_resumes_from_sharded_checkpoint(rt_fresh, tmp_path):
    """Kill a worker process mid-run of a sharded-checkpointing job: the
    gang restarts and resumes from the latest SHARDED checkpoint with no
    explicit path anywhere in user code."""
    from ray_tpu import train
    from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                               RunConfig, ScalingConfig)

    marker = tmp_path / "killed_once"

    def loop(config):
        import jax

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            like = {"w": jax.numpy.zeros(4), "step": jax.numpy.int32(0)}
            state = ckpt.load_sharded_state(like)
            start = int(state["step"]) + 1
        for step in range(start, 5):
            state = {"w": jax.numpy.full((4,), float(step)),
                     "step": jax.numpy.int32(step)}
            c = Checkpoint.from_sharded_state(state)  # NO path anywhere
            train.report({"step": step, "resumed_from": start},
                         checkpoint=c)
            if step == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").write("x")
                os.kill(os.getpid(), 9)  # hard kill, not an exception

    r = JaxTrainer(
        loop,
        train_loop_config={"marker": str(marker)},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "store"),
                             failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert r.error is None
    assert marker.exists()  # the kill actually happened
    assert r.metrics_history[-1]["step"] == 4
    # The restarted gang resumed from a sharded checkpoint, not scratch.
    assert r.metrics_history[-1]["resumed_from"] >= 1
    import jax

    like = {"w": jax.numpy.zeros(4), "step": jax.numpy.int32(0)}
    out = r.checkpoint.load_sharded_state(like)
    assert int(out["step"]) == 4
    np.testing.assert_allclose(np.asarray(out["w"]), np.full((4,), 4.0))
