"""Runtime environments + job submission.

Mirrors the reference's coverage (``python/ray/tests/test_runtime_env*``,
``dashboard/modules/job/tests``): env_vars isolate per-task workers,
working_dir ships code through the KV, pip is validated import-only, and
submitted jobs run driver scripts against the live cluster.
"""
import os
import time

import pytest

import ray_tpu as rt_mod
from ray_tpu._private import runtime_env as renv


def test_zip_roundtrip(tmp_path):
    d = tmp_path / "pkg"
    (d / "sub").mkdir(parents=True)
    (d / "mod.py").write_text("VALUE = 41\n")
    (d / "sub" / "__init__.py").write_text("")
    blob = renv.zip_directory(str(d))
    assert renv.package_key(blob) == renv.package_key(
        renv.zip_directory(str(d)))  # deterministic

    import io
    import zipfile

    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        assert sorted(zf.namelist()) == ["mod.py", "sub/__init__.py"]


def test_validate_rejects_unknown():
    with pytest.raises(ValueError, match="unsupported"):
        renv.validate({"container": {"image": "x"}})
    # conda is a supported PLUGIN now (packed/prefix forms); the
    # reference's yaml-file form needs a conda binary and stays invalid
    # in this zero-egress runtime.
    with pytest.raises(ValueError, match="conda"):
        renv.validate({"conda": "env.yml"})


def test_env_vars_isolated_per_env(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def read_env(name):
        return os.environ.get(name)

    a = read_env.options(
        runtime_env={"env_vars": {"RT_TEST_FLAG": "alpha"}}).remote(
            "RT_TEST_FLAG")
    b = read_env.options(
        runtime_env={"env_vars": {"RT_TEST_FLAG": "beta"}}).remote(
            "RT_TEST_FLAG")
    plain = read_env.remote("RT_TEST_FLAG")
    assert rt.get(a, timeout=60) == "alpha"
    assert rt.get(b, timeout=60) == "beta"
    assert rt.get(plain, timeout=60) is None  # untainted shared worker


def test_working_dir_ships_code(rt_cluster, tmp_path):
    rt = rt_cluster
    (tmp_path / "shipped_mod.py").write_text("ANSWER = 1234\n")

    @rt.remote
    def use_shipped():
        import shipped_mod

        return shipped_mod.ANSWER

    ref = use_shipped.options(
        runtime_env={"working_dir": str(tmp_path)}).remote()
    assert rt.get(ref, timeout=60) == 1234


def test_pip_gate(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def use_numpy():
        import numpy

        return numpy.__name__

    ok = use_numpy.options(runtime_env={"pip": ["numpy"]}).remote()
    assert rt.get(ok, timeout=60) == "numpy"

    @rt.remote
    def nope():
        return 1

    bad = nope.options(
        runtime_env={"pip": ["definitely-not-a-package-xyz"]}).remote()
    with pytest.raises(Exception, match="zero-egress"):
        rt.get(bad, timeout=60)


def test_actor_runtime_env(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class EnvActor:
        def flag(self):
            return os.environ.get("RT_ACTOR_FLAG")

    a = EnvActor.options(
        runtime_env={"env_vars": {"RT_ACTOR_FLAG": "set"}}).remote()
    assert rt.get(a.flag.remote(), timeout=60) == "set"
    rt.kill(a)


def test_job_submission_end_to_end(rt_cluster, tmp_path):
    rt = rt_cluster
    from ray_tpu.core.worker import CoreWorker
    from ray_tpu.job_submission import JobSubmissionClient, JobStatus

    head_sock = CoreWorker.current().head_sock
    client = JobSubmissionClient(head_sock)

    script = tmp_path / "driver.py"
    script.write_text(
        "import os\n"
        "import ray_tpu as rt\n"
        "rt.init(address=os.environ['RT_ADDRESS'])\n"
        "@rt.remote\n"
        "def f(x):\n"
        "    return x * 3\n"
        "print('job result:', rt.get(f.remote(7)))\n"
        "rt.shutdown()\n")
    job_id = client.submit_job(
        entrypoint=f"python {script}",
        runtime_env={"env_vars": {"RT_JOB_MARK": "yes"}})
    status = client.wait_until_finished(job_id, timeout=120)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job result: 21" in logs

    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)

    # failing job surfaces FAILED
    bad_id = client.submit_job(entrypoint="python -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(bad_id, timeout=60) == \
        JobStatus.FAILED
    assert client.get_job_info(bad_id)["returncode"] == 3
