"""Train library: session, checkpoint manager, end-to-end fit, FT restart.

Mirrors the reference's ``python/ray/train/tests/`` strategy: unit tests on
the manager/session pieces plus real mini-cluster integration runs.
"""
import os

import numpy as np
import pytest


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from ray_tpu.train import Checkpoint

    state = {"w": jnp.arange(6.0).reshape(2, 3), "step": jnp.int32(7)}
    ckpt = Checkpoint.from_state(state, base_dir=str(tmp_path))
    restored = ckpt.load_state(like=state)
    assert np.allclose(np.asarray(restored["w"]), np.arange(6.0).reshape(2, 3))
    assert int(restored["step"]) == 7


def test_checkpoint_manager_topk(tmp_path):
    from ray_tpu.train import Checkpoint, CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "store"), num_to_keep=2,
                            score_attribute="acc", score_order="max")
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.3]):
        d = tmp_path / f"c{i}"
        d.mkdir()
        (d / "x").write_text(str(i))
        mgr.register(Checkpoint(str(d)), {"acc": acc})
    assert len(mgr.checkpoints) == 2
    best = mgr.best_checkpoint
    assert (os.path.join(best.path, "x")) and \
        open(os.path.join(best.path, "x")).read() == "1"  # acc=0.9
    # latest always kept
    assert open(os.path.join(mgr.latest_checkpoint.path, "x")).read() == "3"


def test_fit_single_worker(rt_cluster, tmp_path):
    from ray_tpu import train
    from ray_tpu.train import (JaxTrainer, RunConfig, ScalingConfig)

    def loop(config):
        for step in range(3):
            train.report({"step": step, "loss": 1.0 / (step + 1)})

    r = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert r.error is None
    assert [m["step"] for m in r.metrics_history] == [0, 1, 2]
    assert r.metrics["loss"] == pytest.approx(1 / 3)


def test_fit_multi_worker_with_checkpoint(rt_cluster, tmp_path):
    from ray_tpu import train
    from ray_tpu.train import (Checkpoint, JaxTrainer, RunConfig,
                               ScalingConfig)

    def loop(config):
        ctx = train.get_context()
        rank, world = ctx.get_world_rank(), ctx.get_world_size()
        assert world == 2
        for step in range(2):
            ckpt = None
            if rank == 0:
                ckpt = Checkpoint.from_state({"step": np.int64(step)})
            train.report({"step": step, "rank": rank}, checkpoint=ckpt)

    r = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert r.error is None
    assert len(r.metrics_history) == 2
    assert r.checkpoint is not None
    got = r.checkpoint.load_state()
    assert int(got[0]) == 1


def test_fit_gpt_end_to_end(rt_cluster, tmp_path):
    """The §7-step-6 minimum slice: trainer → worker → jitted sharded step."""
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        import jax
        import numpy as np

        from ray_tpu.models import gpt
        from ray_tpu.parallel import create_mesh

        cfg = gpt.CONFIGS["nano"]
        mesh = create_mesh({"dp": -1})
        init, step_fn, state_sh, batch_sh = gpt.make_train_step(cfg, mesh)
        state = init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        batch = {"tokens": jax.device_put(
            rng.integers(0, cfg.vocab_size, (8, 33)).astype(np.int32),
            batch_sh)}
        for i in range(3):
            state, m = step_fn(state, batch)
            train.report({"loss": float(m["loss"]), "step": i})

    r = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert r.error is None
    losses = [m["loss"] for m in r.metrics_history]
    assert losses[-1] < losses[0]


def test_fit_failure_then_restart(rt_fresh, tmp_path):
    """Worker raises once; group restarts and resumes from checkpoint."""
    from ray_tpu import train
    from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer,
                               RunConfig, ScalingConfig)

    marker = tmp_path / "crashed_once"

    def loop(config):
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.load_state()[0]) + 1
        for step in range(start, 4):
            train.report(
                {"step": step},
                checkpoint=Checkpoint.from_state(np.int64(step)))
            if step == 1 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").write("x")
                raise RuntimeError("injected failure")

    r = JaxTrainer(
        loop,
        train_loop_config={"marker": str(marker)},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=2)),
    ).fit()
    assert r.error is None
    # resumed from step-1 checkpoint → steps 2 and 3 after restart
    steps = [m["step"] for m in r.metrics_history]
    assert steps[-1] == 3
    assert marker.exists()


def test_fit_failure_exhausted(rt_fresh, tmp_path):
    from ray_tpu import train
    from ray_tpu.train import (FailureConfig, JaxTrainer, RunConfig,
                               ScalingConfig)

    def loop(config):
        train.report({"step": 0})
        raise RuntimeError("always fails")

    r = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)),
    ).fit()
    assert r.error is not None
    assert "always fails" in str(r.error)
