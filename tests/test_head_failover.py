"""Head crash-restart with worker reconnect (reference: GCS failover —
``gcs_server.cc:566-577`` restart against durable state,
``ray_config_def.h:60`` worker reconnect grace): kill -9 the head under
load, restart it on the same session, and the cluster resumes — node
daemons and actor workers reattach, named-actor state survives."""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import pytest

import ray_tpu as rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _start_head(session_dir):
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "0", "--num-tpus", "0",
         "--session-dir", session_dir, "--die-with-parent"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    path = os.path.join(session_dir, "session.json")
    deadline = time.time() + 30
    while time.time() < deadline:
        if os.path.exists(path):
            # The restarted head rewrites session.json last; wait for a
            # fresh pid to avoid reading the predecessor's file.
            with open(path) as f:
                try:
                    info = json.load(f)
                except json.JSONDecodeError:
                    time.sleep(0.1)
                    continue
            if info.get("pid") == proc.pid:
                return proc, info
        assert proc.poll() is None, "head died during startup"
        time.sleep(0.1)
    raise AssertionError("head never wrote session.json")


@pytest.fixture
def failover_cluster(monkeypatch):
    # Generous windows: on a loaded single-core CI box the restart +
    # reconnect sequence can stretch well past the production defaults.
    monkeypatch.setenv("RT_HEAD_RECONNECT_TIMEOUT_S", "180")
    monkeypatch.setenv("RT_HEAD_RECONNECT_GRACE_S", "60")
    if rt.is_initialized():
        rt.shutdown()
    session_dir = tempfile.mkdtemp(prefix="rt_failover_")
    head, info = _start_head(session_dir)
    host, port = info["tcp_address"]
    node = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_main",
         "--head", f"{host}:{port}",
         "--session-dir", session_dir,
         "--num-cpus", "4", "--die-with-parent"],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    state = {"head": head, "info": info, "session_dir": session_dir}
    yield state
    for p in (state["head"], node):
        try:
            p.kill()
            p.wait(timeout=10)
        except Exception:
            pass
    try:
        rt.shutdown()
    except Exception:
        pass


def test_head_crash_restart_cluster_resumes(failover_cluster):
    st = failover_cluster
    rt.init(address=st["info"]["head_sock"])

    @rt.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def inc(self):
            self.v += 1
            return self.v

    c = Counter.options(name="survivor", lifetime="detached").remote()
    assert rt.get(c.inc.remote()) == 1

    # kill -9: no graceful persist beyond the periodic auto-snapshot.
    # Force one snapshot first so the actor's registration is durable
    # (the auto-snapshot cadence is 10s).
    from ray_tpu.core.worker import CoreWorker

    core = CoreWorker._current
    core.run_sync(core._head.call_simple("persist_state", {}), 30)
    st["head"].send_signal(signal.SIGKILL)
    st["head"].wait(timeout=10)

    # The head is DOWN: direct actor calls must still work (the head is
    # not on the actor data path).
    assert rt.get(c.inc.remote(), timeout=30) == 2

    # Restart the head on the same session dir; node daemon + actor
    # worker + driver all reconnect.
    st["head"], info2 = _start_head(st["session_dir"])
    assert info2["head_sock"] == st["info"]["head_sock"]

    deadline = time.time() + 60
    last_err = None
    while time.time() < deadline:
        try:
            c2 = rt.get_actor("survivor", timeout=5)
            # State preserved => the SAME actor process answered.
            assert rt.get(c2.inc.remote(), timeout=10) >= 3
            break
        except Exception as e:  # noqa: BLE001 - still reconciling
            last_err = e
            time.sleep(1)
    else:
        raise AssertionError(
            f"cluster did not resume after head restart: {last_err}")

    # New work schedules too (leases flow through the restarted head).
    @rt.remote
    def ping():
        return "ok"

    assert rt.get(ping.remote(), timeout=60) == "ok"
