"""Per-node dashboard agents (reference ``dashboard/agent.py:28``):
each node daemon serves node-local stats/logs over HTTP, and the head
proxies any node's stats + logs through one URL."""
import json
import os
import urllib.request

import pytest

from ray_tpu._private.node_agent import collect_node_stats
from ray_tpu.cluster_utils import Cluster


def _fetch(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_collect_node_stats_shape():
    stats = collect_node_stats({"ab" * 14: os.getpid()})
    assert stats["mem_total_bytes"] > 0
    assert stats["cpu_count"] >= 1
    assert stats["num_workers"] == 1
    (w,) = stats["workers"]
    assert w["pid"] == os.getpid()
    assert w["rss_bytes"] > 0


def test_agents_through_head_and_direct(monkeypatch):
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    # Direct agent access is OPT-IN (the default loopback bind
    # advertises no cluster-wide URL; the head proxy covers that path).
    # Deliberate exposure = bind the routable interface.
    monkeypatch.setenv("RT_AGENT_BIND", "0.0.0.0")
    c = Cluster(head_resources={"CPU": 0})
    c.add_node(num_cpus=2)
    rt = c.connect()
    try:
        # run a task so the remote node has a worker + a log file
        @rt.remote
        def hello():
            print("agent-test-marker")
            return "hi"

        assert rt.get(hello.remote(), timeout=60) == "hi"

        dash = rt.dashboard_url()
        nodes = _fetch(f"{dash}/api/state?kind=nodes")
        remote = [n for n in nodes if not n["is_head"]]
        assert len(remote) == 1
        node = remote[0]
        # daemons advertise their agent endpoint
        assert node["agent_url"] and node["agent_url"].startswith("http")

        # 1) the head proxies the REMOTE node's stats over its daemon
        #    RPC connection — one URL serves the whole cluster
        stats = _fetch(f"{dash}/api/node?node_id={node['node_id']}")
        assert stats["node_id"] == node["node_id"]
        assert stats["mem_total_bytes"] > 0
        assert stats["num_workers"] >= 1
        assert any(w.get("rss_bytes", 0) > 0 for w in stats["workers"])

        # 2) the head's own node answers too
        head_node = [n for n in nodes if n["is_head"]][0]
        hstats = _fetch(f"{dash}/api/node?node_id={head_node['node_id']}")
        assert hstats["node_id"] == head_node["node_id"]

        # 3) direct agent access (multi-host debugging path)
        astats = _fetch(f"{node['agent_url']}/api/stats")
        assert astats["node_id"] == node["node_id"]
        workers = _fetch(f"{node['agent_url']}/api/workers")
        assert len(workers) >= 1
        files = _fetch(f"{node['agent_url']}/api/logs")["files"]
        assert any(f.startswith("worker-") for f in files)
        wid = workers[0]["worker_id"]
        tail = _fetch(f"{node['agent_url']}/api/logs?worker_id={wid}")
        assert "data" in tail

        # 4) the remote worker's LOG reaches the driver through the
        #    head URL as well (fan-out through the daemon)
        log = _fetch(f"{dash}/api/logs?worker_id={wid}")
        assert "agent-test-marker" in log["data"]

        # unknown node → clean 404
        with pytest.raises(urllib.error.HTTPError):
            _fetch(f"{dash}/api/node?node_id=deadbeef")
    finally:
        c.shutdown()
