"""Fused multi-token decode (``decode_chunk``/``decode_until``): the
chunked path must be token-for-token identical to the per-token
``decode_step`` loop at temperature 0, stop at EOS inside a chunk
without emitting trailing tokens, and stream per-chunk slices through a
live serve deployment (including the batched streaming mode)."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _per_token(params, prompt, cfg, max_new, **kw):
    import jax.numpy as jnp

    from ray_tpu.models import gpt_decode

    return np.stack([np.asarray(t) for t in gpt_decode.generate(
        params, jnp.asarray(prompt), cfg, max_new, **kw)], axis=1)


def _chunked(params, prompt, cfg, max_new, **kw):
    import jax.numpy as jnp

    from ray_tpu.models import gpt_decode

    slices = list(gpt_decode.generate_chunked(
        params, jnp.asarray(prompt), cfg, max_new, **kw))
    return slices, np.concatenate(slices, axis=1)


@pytest.mark.parametrize("chunk", [4, 5, 16])
def test_chunk_matches_per_token_greedy(nano, nano_params, chunk):
    """Temperature 0: the fused scan emits exactly the per-token loop's
    tokens — dividing, non-dividing, and larger-than-max_new chunks."""
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, nano.vocab_size, (2, 8)).astype(np.int32)
    max_new = 12
    want = _per_token(nano_params, prompt, nano, max_new, max_len=32)
    slices, got = _chunked(nano_params, prompt, nano, max_new,
                           chunk=chunk, max_len=32)
    assert got.shape == (2, max_new)
    assert (got == want).all(), (got, want)
    # Streaming granularity: prefill token first, then <=chunk slices.
    assert slices[0].shape[1] == 1
    assert all(s.shape[1] <= chunk for s in slices[1:])


def test_eos_inside_chunk_stops_early(nano, nano_params):
    """Pick the greedy token at step 5 as EOS: the chunked stream must
    end AT that token — no trailing tokens from the rest of the chunk —
    and never restart."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, nano.vocab_size, (1, 8)).astype(np.int32)
    ref = _per_token(nano_params, prompt, nano, 16, max_len=32)[0]
    eos = int(ref[5])
    stop = int(np.argmax(ref == eos))  # first occurrence (may be < 5)
    _, got = _chunked(nano_params, prompt, nano, 16, chunk=4, max_len=32,
                      eos_token=eos)
    assert got.shape[1] == stop + 1, (got, ref, eos)
    assert int(got[0, -1]) == eos
    assert (got[0] == ref[:stop + 1]).all()


def test_eos_masks_finished_stream_in_batch(nano, nano_params):
    """B=2 with one stream finishing first: the finished lane is
    masked-and-carried (keeps emitting eos) while the other decodes on,
    and the batch stops when BOTH are done or max_new is hit."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, nano.vocab_size, (2, 8)).astype(np.int32)
    ref = _per_token(nano_params, prompt, nano, 12, max_len=32)
    # EOS = stream 0's token at position 3; ensure stream 1 doesn't
    # emit it at/before that position (else pick another seed offset).
    eos = int(ref[0, 3])
    first0 = int(np.argmax(ref[0] == eos))
    hits1 = np.nonzero(ref[1] == eos)[0]
    assume_ok = not len(hits1) or hits1[0] > first0
    assert assume_ok, "seed produced overlapping EOS; adjust test seed"
    _, got = _chunked(nano_params, prompt, nano, 12, chunk=4, max_len=32,
                      eos_token=eos)
    n = got.shape[1]
    assert n == 12 if not len(hits1) else n == hits1[0] + 1
    # Stream 0: real tokens up to its EOS, eos-padding after.
    assert (got[0, :first0 + 1] == ref[0, :first0 + 1]).all()
    assert (got[0, first0:] == eos).all()
    # Stream 1: untouched by stream 0's stopping.
    assert (got[1] == ref[1, :n]).all()


def test_eos_on_prefill_token_ends_stream(nano, nano_params):
    """EOS sampled as the very FIRST (prefill-derived) token: the stream
    is exactly one [B, 1] slice holding the eos — no decode chunk ever
    dispatches. Pins the prefill-edge semantics the engine's
    admit-then-free-immediately path mirrors."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, nano.vocab_size, (1, 8)).astype(np.int32)
    ref = _per_token(nano_params, prompt, nano, 4, max_len=32)
    eos = int(ref[0, 0])
    slices, got = _chunked(nano_params, prompt, nano, 8, chunk=4,
                           max_len=32, eos_token=eos)
    assert len(slices) == 1 and slices[0].shape == (1, 1)
    assert got.tolist() == [[eos]]


def test_decode_until_lane_done_at_entry(nano, nano_params):
    """decode_until's two-layer EOS contract, pinned per lane: a lane
    whose ENTRY token is already eos stays masked (emits eos padding
    only, its done flag honored from the first chunk) while the other
    lane decodes its full reference stream; trimming cuts at the first
    position where ALL lanes are done — never earlier. The engine's
    per-slot freeing must preserve exactly these stream contents."""
    import jax.numpy as jnp

    from ray_tpu.models import gpt_decode

    rng = np.random.default_rng(0)
    prompt = rng.integers(0, nano.vocab_size, (2, 8)).astype(np.int32)
    ref = _per_token(nano_params, prompt, nano, 10, max_len=32)
    # EOS = lane 0's prefill-derived token; require lane 1 to avoid it
    # through its window so only max_new ends the batch.
    eos = int(ref[0, 0])
    assert not (ref[1] == eos).any(), \
        "seed produced overlapping EOS; adjust the test seed"
    cache = gpt_decode.init_cache(nano, 2, 32)
    logits, cache = gpt_decode._jitted_prefill()(
        nano_params, jnp.asarray(prompt), nano, cache)
    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    assert int(token[0]) == eos  # lane 0 enters decode_until done
    step = gpt_decode.jit_decode_chunk(nano, 4, 0.0, eos)
    slices = list(gpt_decode.decode_until(
        step, nano_params, cache, token, 9, eos_token=eos))
    got = np.concatenate(slices, axis=1)
    # ALL-lanes trimming: lane 1 alive => full 9 tokens stream.
    assert got.shape == (2, 9)
    assert (got[0] == eos).all()               # masked lane: eos padding
    assert (got[1] == ref[1, 1:]).all()        # live lane: untouched
    # and when BOTH lanes enter done, not a single chunk is emitted
    token_done = jnp.asarray([eos, eos], jnp.int32)
    assert list(gpt_decode.decode_until(
        step, nano_params, cache, token_done, 9, eos_token=eos)) == []


def test_temperature_sampling_deterministic(nano, nano_params):
    """temperature>0 threads the PRNG key through the scan carry: same
    seed → same tokens, different seed → (almost surely) different."""
    import jax

    rng = np.random.default_rng(3)
    prompt = rng.integers(0, nano.vocab_size, (1, 8)).astype(np.int32)
    kw = dict(chunk=4, max_len=32, temperature=1.0)
    _, a = _chunked(nano_params, prompt, nano, 12,
                    rng=jax.random.PRNGKey(7), **kw)
    _, b = _chunked(nano_params, prompt, nano, 12,
                    rng=jax.random.PRNGKey(7), **kw)
    _, c = _chunked(nano_params, prompt, nano, 12,
                    rng=jax.random.PRNGKey(8), **kw)
    assert (a == b).all()
    assert a.shape == c.shape == (1, 12)
    assert not (a == c).all()


def test_serve_streams_chunk_slices(rt_cluster):
    """Live serve deployment on the fused path: per-chunk token slices
    arrive as individual stream items (incremental, not buffered), and
    flatten_chunks re-yields them per token — both matching the
    per-token reference decode."""
    import jax

    from ray_tpu import serve
    from ray_tpu.models import gpt

    nano = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, nano.vocab_size, (1, 8)).astype(np.int32)
    want = _per_token(params, prompt, nano, 9, max_len=32)[0].tolist()

    serve.start(proxy=False)
    try:
        @serve.deployment
        class ChunkDecoder:
            def __init__(self, prompt):
                from ray_tpu.models import gpt as _gpt

                self.cfg = _gpt.CONFIGS["nano"]
                self.params = _gpt.init_params(jax.random.PRNGKey(0),
                                               self.cfg)
                self.prompt = np.asarray(prompt)

            def __call__(self, request):
                from ray_tpu.models import gpt_decode

                max_new, mode = request
                for slice_ in gpt_decode.generate_chunked(
                        self.params, self.prompt, self.cfg, max_new,
                        chunk=4, max_len=32):
                    # Both producer shapes must stream/flatten: raw
                    # [j] ndarray rows and plain int lists.
                    yield (slice_[0] if mode == "array"
                           else [int(t) for t in slice_[0]])

        h = serve.run(ChunkDecoder.bind(prompt), name="chunkdec",
                      route_prefix=None)
        for mode in ("list", "array"):
            items = list(h.options(stream=True).remote((9, mode)))
            # Chunk granularity: first item is the prefill token alone,
            # later items are whole chunk slices.
            assert [len(i) for i in items] == [1, 4, 4]
            assert [int(t) for i in items for t in i] == want
            # flatten_chunks: same stream, token granularity.
            toks = list(h.options(stream=True,
                                  flatten_chunks=True).remote((9, mode)))
            assert toks == want
        serve.delete("chunkdec")
    finally:
        serve.shutdown()


def test_batched_streaming_decode(rt_cluster):
    """@serve.batch(stream=True): concurrent callers are fused into ONE
    batched handler invocation whose yielded per-batch slices fan out to
    each caller's own stream."""
    import threading

    from ray_tpu import serve

    calls = []

    class Fanout:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2,
                     stream=True)
        def decode_batch(self, starts):
            calls.append(len(starts))
            for step in range(3):  # 3 "chunks" per stream
                yield [[s + step * 10, s + step * 10 + 1]
                       for s in starts]

        def run(self, start):
            return list(self.decode_batch(start))

    f = Fanout()
    out = {}

    def worker(s):
        out[s] = f.run(s)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in (100, 200, 300)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for s in (100, 200, 300):
        assert out[s] == [[s, s + 1], [s + 10, s + 11], [s + 20, s + 21]]
    # All three callers rode one (or at most two, if the flusher raced
    # the submits) batched invocations — not three.
    assert sum(calls) >= 3 and len(calls) <= 2, calls


def test_batched_streaming_error_fans_out():
    """A handler raising mid-stream fails every batched caller, after
    delivering the chunks that preceded the error."""
    from ray_tpu import serve

    class Bad:
        @serve.batch(max_batch_size=2, batch_wait_timeout_s=0.01,
                     stream=True)
        def decode_batch(self, items):
            yield [i * 2 for i in items]
            raise RuntimeError("device fell over")

        def run(self, x):
            return self.decode_batch(x)

    b = Bad()
    gen = b.run(21)
    assert next(gen) == 42
    with pytest.raises(RuntimeError, match="fell over"):
        list(gen)
