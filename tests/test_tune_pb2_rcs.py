"""PB2 (GP-bandit explore), ResourceChangingScheduler, and the GCE
queued-resource backend conformance (reference:
``tune/schedulers/pb2.py``, ``resource_changing_scheduler.py``,
``autoscaler/_private/gcp/node_provider.py``)."""
import numpy as np
import pytest

from ray_tpu import tune
from ray_tpu.autoscaler import (
    FakeGCEConnector,
    GCESliceBackend,
    TPUSliceProvider,
    gce_accelerator_type,
)


class _T:
    def __init__(self, tid, config):
        self.trial_id = tid
        self.config = config


def test_pb2_explore_prefers_observed_winners():
    """GP-bandit selection: with clear evidence that high lr improves
    reward, explore() proposes lr well above the uniform midpoint."""
    sched = tune.PB2(metric="score", mode="max",
                     perturbation_interval=1,
                     hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    rng = np.random.default_rng(0)
    # feed the population history: improvement grows with lr
    for step in range(1, 14):
        for i, lr in enumerate((0.1, 0.5, 0.9)):
            t = _T(f"t{i}", {"lr": lr})
            sched.on_trial_result(
                t, {"training_iteration": step,
                    "score": step * lr + rng.normal(0, 0.01)})
    picks = [sched.explore({"lr": 0.2}, donor_id="t2")["lr"]
             for _ in range(8)]
    assert np.mean(picks) > 0.6, picks  # pulled toward observed winners
    assert all(0.0 <= p <= 1.0 for p in picks)


def test_pb2_cold_start_uniform():
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"lr": [2.0, 4.0]}, seed=1)
    cfg = sched.explore({"lr": 3.0})
    assert 2.0 <= cfg["lr"] <= 4.0


def test_pb2_requires_bounds():
    with pytest.raises(ValueError, match="hyperparam_bounds"):
        tune.PB2(metric="m", mode="max")


def test_pb2_end_to_end(rt_cluster, tmp_path):
    """PB2 drives a two-trial population: the weak trial's lr is
    re-selected by the GP instead of random perturbation and lands in
    bounds; the experiment finishes clean."""
    from ray_tpu.train import Checkpoint, RunConfig

    sync_dir = tmp_path / "sync"
    sync_dir.mkdir()

    def objective(config):
        import os
        import time

        from ray_tpu import train

        open(os.path.join(config["sync"], f"up_{config['lr']}"), "w")
        deadline = time.time() + 20
        while len(os.listdir(config["sync"])) < 2:
            if time.time() > deadline:
                raise TimeoutError("peer trial never started")
            time.sleep(0.01)
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.load_state()[0]) + 1
        for i in range(start, 12):
            tune.report(
                {"score": i * config["lr"],
                 "training_iteration": i + 1},
                checkpoint=Checkpoint.from_state(np.int64(i)))
            time.sleep(0.03)

    sched = tune.PB2(metric="score", mode="max",
                     perturbation_interval=3,
                     hyperparam_bounds={"lr": [0.5, 2.0]}, seed=0)
    grid = tune.Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.01, 1.5]),
                     "sync": str(sync_dir)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert not grid.errors
    weak = [r for r in grid.results
            if r.metrics_history
            and r.metrics_history[0].get("score", 1) == 0]
    # exploited config came from the GP selection, inside the bounds
    assert weak and 0.5 <= weak[0].config["lr"] <= 2.0, \
        [(r.config, len(r.metrics_history)) for r in grid.results]


def test_resource_changing_scheduler(rt_cluster, tmp_path):
    """Trials restart from checkpoint with the reallocated shape: with
    4 cluster CPUs and one live trial, DistributeResources grows the
    trial from 1 CPU to the whole machine."""
    from ray_tpu.train import Checkpoint, RunConfig

    def objective(config):
        import time

        from ray_tpu import train

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = int(ckpt.load_state()[0]) + 1
        for i in range(start, 8):
            tune.report({"score": float(i), "training_iteration": i + 1},
                        checkpoint=Checkpoint.from_state(np.int64(i)))
            time.sleep(0.03)

    sched = tune.ResourceChangingScheduler(
        resources_allocation_function=tune.DistributeResources(
            base_cpus=1))
    res = tune.Tuner(
        objective,
        param_space={},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    scheduler=sched,
                                    max_concurrent_trials=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert not res.errors
    (r,) = res.results
    # the trial finished all 8 iterations across the resize restart
    assert r.metrics["training_iteration"] == 8
    # and ended with an upsized allocation recorded on the trial
    assert r.metrics["score"] == 7.0


def test_rcs_wrapping_pbt_exploit_path():
    """ResourceChangingScheduler(base=PBT): the controller resolves
    explore() through the wrapper instead of asserting on it."""
    from ray_tpu.tune.controller import TuneController
    from ray_tpu.tune.schedulers import PopulationBasedTraining

    pbt = PopulationBasedTraining(
        metric="score", mode="max",
        hyperparam_mutations={"lr": tune.uniform(0.1, 1.0)}, seed=0)
    rcs = tune.ResourceChangingScheduler(base_scheduler=pbt)
    sched = rcs
    if not isinstance(sched, PopulationBasedTraining):
        sched = sched.base
    assert isinstance(sched, PopulationBasedTraining)
    cfg = sched.explore({"lr": 0.5}, donor_id="d", trial_id="t")
    assert "lr" in cfg
    del TuneController  # imported to prove the resolution mirrors it


def test_pb2_exploit_resets_prev_record():
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    t = _T("a", {"lr": 0.1})
    sched.on_trial_result(t, {"training_iteration": 1, "score": 0.0})
    assert "a" in sched._prev
    sched.explore({"lr": 0.1}, donor_id="d", trial_id="a")
    # pre-exploit record dropped: donor-level reward jump can't be
    # credited to the old hyperparameters
    assert "a" not in sched._prev


# --------------------------------------------------------- GCE conformance


def test_gce_accelerator_naming():
    assert gce_accelerator_type("v5e-16") == "v5litepod-16"
    assert gce_accelerator_type("v4-32") == "v4-32"
    assert gce_accelerator_type("v5p-128") == "v5p-128"


def test_gce_backend_conformance():
    """The provider's slice lifecycle maps onto well-formed GCE queued
    resource calls: one create per slice with the real body shape,
    polls until ACTIVE, one delete per slice."""
    fake = FakeGCEConnector(polls_per_state=2)
    backend = GCESliceBackend(fake, pod_type="v5e-16",
                              project="proj-x", zone="us-east5-a")
    provider = TPUSliceProvider(None, pod_type="v5e-16",
                                backend=backend)
    sid = provider.create_node({"TPU": 16})
    creates = [r for r in fake.requests if r[0] == "create"]
    assert len(creates) == 1  # 4 hosts, ONE queued resource
    _, parent, qr_id, body = creates[0]
    assert parent == "projects/proj-x/locations/us-east5-a"
    assert qr_id == sid
    spec = body["tpu"]["node_spec"][0]
    assert spec["node"]["accelerator_type"] == "v5litepod-16"
    assert spec["node"]["runtime_version"]
    assert spec["node_id"] == sid
    # finalize polled through the provisioning states to ACTIVE
    states_seen = len([r for r in fake.requests if r[0] == "get"])
    assert states_seen >= 4
    assert provider.non_terminated_nodes() == [sid]

    provider.terminate_node(sid)
    deletes = [r for r in fake.requests if r[0] == "delete"]
    assert len(deletes) == 1
    assert fake.resources == {}  # gone server-side
    assert provider.non_terminated_nodes() == []


def test_gce_node_id_resolution_via_labels():
    """With a cluster node lister, GCE handles resolve to node ids by
    their slice labels — the autoscaler's idle accounting (scale-down)
    depends on this."""
    fake = FakeGCEConnector()
    nodes = [
        {"node_id": "n-abc", "labels": {"rt.io/tpu-slice": "s1",
                                        "rt.io/tpu-worker-id": "0"}},
        {"node_id": "n-def", "labels": {"rt.io/tpu-slice": "s1",
                                        "rt.io/tpu-worker-id": "1"}},
    ]
    backend = GCESliceBackend(fake, pod_type="v5e-8",
                              list_nodes=lambda: nodes)
    h0 = backend.launch("s1", 0, {}, 4, 4)
    h1 = backend.launch("s1", 1, {}, 4, 4)
    backend.finalize("s1", [h0, h1])
    assert backend.node_id(h0) == "n-abc"
    assert backend.node_id(h1) == "n-def"
    # cached on the handle afterwards
    assert h0.node_id == "n-abc"


def test_gce_backend_stockout_tears_down():
    fake = FakeGCEConnector(fail_with="no capacity in zone")
    backend = GCESliceBackend(fake, pod_type="v5e-8")
    provider = TPUSliceProvider(None, pod_type="v5e-8", backend=backend)
    with pytest.raises(RuntimeError, match="no capacity"):
        provider.create_node({"TPU": 8})
    # failed create cleaned up its queued resource
    assert fake.resources == {}
    assert provider.non_terminated_nodes() == []