"""SAC / CQL / offline-data tests (reference:
``rllib/tuned_examples/sac/pendulum_sac.py`` — Pendulum is the standard
continuous-control learning gate; ``rllib/algorithms/cql/tests``)."""
import numpy as np
import pytest

from ray_tpu.rllib import (CQLConfig, OfflineData, SAC, SACConfig,
                           to_columns)


def _pendulum_config():
    return (SACConfig()
            .environment("Pendulum-v1")
            .env_runners(num_env_runners=0, num_envs_per_env_runner=4,
                         rollout_fragment_length=64)
            .training(train_batch_size=256, lr=1e-3,
                      num_steps_sampled_before_learning=1000,
                      updates_per_iteration=256)
            .debugging(seed=7))


def test_sac_module_logp_matches_jax():
    """Numpy rollout path and jitted learner path must agree on log π."""
    import jax.numpy as jnp

    from ray_tpu.rllib.rl_module import RLModuleSpec
    from ray_tpu.rllib.sac import (SquashedGaussianModule, actor_forward,
                                   squash_logp)

    spec = RLModuleSpec(obs_dim=3, num_actions=2, hidden=(16,),
                        continuous=True,
                        action_low=np.array([-2.0, -1.0], np.float32),
                        action_high=np.array([2.0, 1.0], np.float32))
    mod = SquashedGaussianModule(spec, seed=0)
    obs = np.random.default_rng(0).standard_normal((5, 3)).astype(np.float32)
    mean, log_std = actor_forward(mod.params, obs, np)
    u = mean  # deterministic point
    lp_np = squash_logp(u, log_std, mean, np)
    lp_jax = np.asarray(squash_logp(jnp.asarray(u), jnp.asarray(log_std),
                                    jnp.asarray(mean), jnp))
    np.testing.assert_allclose(lp_np, lp_jax, rtol=1e-4)


def test_sac_learns_pendulum():
    """Pendulum returns start ≈ -1400; solved ≈ -200. Gate: clear
    improvement within a bounded iteration budget (reference tuned
    example stops at -250; the CI-sized gate here is looser but must
    show real learning, not noise)."""
    algo = _pendulum_config().build()
    first = None
    best = -1e9
    for i in range(70):
        algo.train()
        m = algo.env_runner_group.get_metrics()
        if m.get("num_episodes", 0) >= 5:
            r = m["episode_return_mean"]
            if first is None:
                first = r
            best = max(best, r)
            if best > -400:
                break
    algo.stop()
    assert first is not None, "no episodes completed"
    assert best > -600, (
        f"SAC failed to learn Pendulum: first={first:.1f} best={best:.1f}")
    assert best > first + 300, (
        f"no improvement: first={first:.1f} best={best:.1f}")


def test_offline_data_columns_roundtrip():
    rows = [{"obs": [0.1, 0.2], "actions": [0.5], "rewards": 1.0,
             "next_obs": [0.2, 0.3], "dones": 0.0} for _ in range(10)]
    cols = to_columns(rows)
    assert set(cols) == {"obs", "actions", "rewards", "next_obs", "dones"}
    assert cols["obs"].shape == (10, 2)

    od = OfflineData({"obs": np.zeros((7, 2)), "actions": np.zeros((7, 1)),
                      "rewards": np.zeros(7), "next_obs": np.zeros((7, 2)),
                      "dones": np.zeros(7)})
    assert len(od) == 7
    assert od.sample(3)["obs"].shape == (3, 2)
    assert sum(len(b["obs"]) for b in od.epoch(2)) == 7

    with pytest.raises(ValueError):
        to_columns({"obs": np.zeros((3, 2)), "actions": np.zeros((4, 1))})


def test_offline_data_from_dataset(rt_cluster):
    from ray_tpu import data as rtd

    rows = [{"obs": [float(i), 0.0], "actions": [0.1],
             "rewards": float(i), "next_obs": [float(i + 1), 0.0],
             "dones": 0.0} for i in range(20)]
    ds = rtd.from_items(rows)
    od = OfflineData(ds)
    assert len(od) == 20
    assert od.cols["rewards"].sum() == sum(range(20))


def _make_offline_pendulum(n=3000, seed=0):
    """Log transitions from a scripted stabilizing controller so the
    dataset contains good behavior for CQL to distill."""
    import gymnasium

    rng = np.random.default_rng(seed)
    env = gymnasium.make("Pendulum-v1")
    obs, _ = env.reset(seed=seed)
    cols = {k: [] for k in ("obs", "actions", "rewards", "next_obs",
                            "dones")}
    for _ in range(n):
        cos_th, sin_th, thdot = obs
        # energy-shaping-ish controller + exploration noise
        a = np.clip(-(2.0 * sin_th + 0.5 * thdot)
                    + rng.normal(0, 0.3), -2, 2)
        nobs, r, term, trunc, _ = env.step(np.array([a], np.float32))
        cols["obs"].append(obs)
        cols["actions"].append([a])
        cols["rewards"].append(r)
        cols["next_obs"].append(nobs)
        cols["dones"].append(float(term))
        obs = nobs
        if term or trunc:
            obs, _ = env.reset()
    return {k: np.asarray(v, np.float32) for k, v in cols.items()}


def test_cql_trains_offline():
    data = _make_offline_pendulum()
    cfg = (CQLConfig()
           .training(train_batch_size=128, updates_per_iteration=50,
                     cql_weight=1.0, cql_num_actions=4)
           .debugging(seed=3)
           .offline(data, obs_dim=3, action_dim=1,
                    action_low=[-2.0], action_high=[2.0]))
    algo = cfg.build()
    m1 = algo.train()
    m2 = algo.train()
    assert m2["training_iteration"] == 2
    assert np.isfinite(m2["critic_loss"])
    assert np.isfinite(m2["cql_loss"])
    # The conservative penalty must actually be wired in.
    assert m2["cql_loss"] != 0.0
    # Policy should output bounded actions of the right shape.
    acts = algo.compute_actions(data["obs"][:16])
    assert acts.shape == (16, 1)
    assert np.all(acts >= -2.0) and np.all(acts <= 2.0)
    # checkpoint roundtrip
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        algo.save_to_path(d)
        before = algo.compute_actions(data["obs"][:4])
        algo2 = cfg.build()
        algo2.restore_from_path(d)
        after = algo2.compute_actions(data["obs"][:4])
        np.testing.assert_allclose(before, after, rtol=1e-5)


def test_cql_penalizes_ood_actions():
    """Train two learners on the same narrow-action dataset — with and
    without the conservative penalty — and check CQL assigns lower Q to
    out-of-distribution actions relative to its in-distribution Q."""
    from ray_tpu.rllib.sac import q_forward

    data = _make_offline_pendulum(n=1500)
    base = dict(obs_dim=3, action_dim=1, action_low=[-2.0],
                action_high=[2.0])

    def train(cql_weight):
        cfg = (CQLConfig()
               .training(train_batch_size=128, updates_per_iteration=150,
                         cql_weight=cql_weight, cql_num_actions=4)
               .debugging(seed=5)
               .offline(data, **base))
        algo = cfg.build()
        algo.train()
        return algo

    algo_cql = train(5.0)
    obs = data["obs"][:256]
    a_data = data["actions"][:256]
    import jax

    params = jax.tree.map(np.asarray, algo_cql.learner.params)
    q_data = q_forward(params["q1"], obs, a_data, np).mean()
    rng = np.random.default_rng(0)
    a_ood = rng.uniform(-2, 2, size=a_data.shape).astype(np.float32)
    q_ood = q_forward(params["q1"], obs, a_ood, np).mean()
    assert q_data >= q_ood - 1.0, (
        f"CQL did not keep OOD Q below data Q: data={q_data:.2f} "
        f"ood={q_ood:.2f}")
