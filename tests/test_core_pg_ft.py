"""Placement groups + fault tolerance
(reference: python/ray/tests/test_placement_group*.py, test_reconstruction*.py)."""
import time

import pytest


def test_pg_create_ready(rt_cluster):
    rt = rt_cluster
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=15)
    rt.remove_placement_group(pg)


def test_pg_schedule_into_bundle(rt_cluster):
    rt = rt_cluster
    pg = rt.placement_group([{"CPU": 2}], strategy="PACK")
    pg.ready(timeout=15)

    @rt.remote
    def f():
        return "in-bundle"

    s = rt.PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    assert rt.get(f.options(scheduling_strategy=s).remote()) == "in-bundle"
    rt.remove_placement_group(pg)


def test_pg_actor_in_bundle(rt_cluster):
    rt = rt_cluster
    pg = rt.placement_group([{"CPU": 1}], strategy="PACK")
    pg.ready(timeout=15)

    @rt.remote
    class A:
        def ping(self):
            return 1

    s = rt.PlacementGroupSchedulingStrategy(pg)
    a = A.options(scheduling_strategy=s).remote()
    assert rt.get(a.ping.remote()) == 1
    rt.kill(a)
    rt.remove_placement_group(pg)


def test_pg_infeasible(rt_cluster):
    rt = rt_cluster
    pg = rt.placement_group([{"CPU": 1000}], strategy="PACK")
    with pytest.raises(Exception):
        pg.ready(timeout=1.0)


def test_pg_resources_returned_after_remove(rt_cluster):
    rt = rt_cluster
    before = rt.available_resources()["CPU"]
    pg = rt.placement_group([{"CPU": 4}])
    pg.ready(timeout=15)
    during = rt.available_resources()["CPU"]
    assert during <= before - 4
    rt.remove_placement_group(pg)
    time.sleep(0.2)
    after = rt.available_resources()["CPU"]
    assert after >= before - 0.01


def test_task_retry_on_worker_death(rt_fresh):
    rt = rt_fresh

    @rt.remote(max_retries=3)
    def flaky(marker_path):
        import os

        # Die the first time, succeed on retry (marker file persists).
        if not os.path.exists(marker_path):
            open(marker_path, "w").close()
            os._exit(1)
        return "recovered"

    import tempfile

    marker = tempfile.mktemp()
    assert rt.get(flaky.remote(marker), timeout=60) == "recovered"


def test_worker_crash_no_retry(rt_fresh):
    rt = rt_fresh

    @rt.remote(max_retries=0)
    def die():
        import os

        os._exit(1)

    with pytest.raises(Exception):
        rt.get(die.remote(), timeout=60)


def test_remove_racing_pending_create_wins(rt_cluster):
    """remove_placement_group on a still-PENDING (infeasible-for-now)
    create must win: the create loop aborts instead of committing a
    reservation nobody holds a handle to (leak)."""
    import time

    rt = rt_cluster
    # Infeasible for the 4-CPU fixture cluster: stays PENDING.
    pg = rt.placement_group([{"CPU": 64.0}], strategy="PACK")
    with pytest.raises(Exception):
        pg.ready(timeout=1.5)
    rt.remove_placement_group(pg)
    # Free capacity never lets the raced create come back to life.
    time.sleep(1.0)
    from ray_tpu.core.worker import CoreWorker

    st = CoreWorker.current().head_call("pg_state", {"pg_id": pg._id.hex()})
    assert st["state"] == "REMOVED"
    listed = rt.state("placement_groups")
    assert all(p["pg_id"] != pg._id.hex() for p in listed)


def test_pg_state_unknown_id_grace_then_removed(rt_cluster):
    """pg_state answers PENDING only inside a short grace window for an
    id with no entry; a permanently-dead id then reads REMOVED so stale
    handles fail fast instead of burning their whole timeout."""
    import time

    from ray_tpu._private.ids import PlacementGroupID
    from ray_tpu.core.worker import CoreWorker

    ghost = PlacementGroupID.from_random().hex()
    core = CoreWorker.current()
    assert core.head_call("pg_state", {"pg_id": ghost})["state"] == "PENDING"
    deadline = time.time() + 30
    while time.time() < deadline:
        st = core.head_call("pg_state", {"pg_id": ghost})["state"]
        if st == "REMOVED":
            break
        time.sleep(0.5)
    assert st == "REMOVED"
