"""GPT flagship model: forward shapes, sharded train step, convergence."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


def test_forward_shapes(nano):
    import jax

    from ray_tpu.models import gpt

    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    tokens = np.zeros((2, 16), np.int32)
    logits = gpt.forward(params, tokens, nano)
    assert logits.shape == (2, 16, nano.vocab_size)
    assert logits.dtype == np.float32


def test_causality(nano):
    """Changing a future token must not affect earlier logits."""
    import jax

    from ray_tpu.models import gpt

    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    t1 = np.zeros((1, 16), np.int32)
    t2 = t1.copy()
    t2[0, -1] = 7
    l1 = np.asarray(gpt.forward(params, t1, nano))
    l2 = np.asarray(gpt.forward(params, t2, nano))
    assert np.allclose(l1[0, :-1], l2[0, :-1], atol=1e-3)
    assert not np.allclose(l1[0, -1], l2[0, -1], atol=1e-3)


@pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 2, "fsdp": 2, "tp": 2},
                                  {"fsdp": 8}])
def test_sharded_train_step_loss_decreases(nano, axes):
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    mesh = create_mesh(axes)
    init, step, state_sh, batch_sh = gpt.make_train_step(nano, mesh)
    state = init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"tokens": jax.device_put(
        rng.integers(0, nano.vocab_size, (8, 33)).astype(np.int32),
        batch_sh)}
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_sharding_plans_agree(nano):
    """dp-only and fsdp+tp shardings compute the same loss trajectory."""
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, nano.vocab_size, (8, 33)).astype(np.int32)

    def run(axes):
        mesh = create_mesh(axes)
        init, step, _, batch_sh = gpt.make_train_step(nano, mesh)
        state = init(jax.random.PRNGKey(0))
        batch = {"tokens": jax.device_put(tokens, batch_sh)}
        out = []
        for _ in range(3):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    a = run({"dp": 8})
    b = run({"fsdp": 4, "tp": 2})
    assert np.allclose(a, b, rtol=2e-2), (a, b)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_chunked_ce_matches_unchunked():
    """loss_chunk>0 reroutes the loss through _chunked_ce (the '1b'
    preset relies on it); loss AND grads must match the unchunked path,
    including a non-dividing chunk (tail) and chunk > S (fallback)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt

    cfg0 = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg0)
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(0).integers(0, cfg0.vocab_size, (2, 65)),
        jnp.int32)}

    def loss_and_grad(chunk):
        cfg = dataclasses.replace(cfg0, loss_chunk=chunk)
        loss, _ = gpt.loss_fn(params, batch, cfg)
        g = jax.grad(lambda p: gpt.loss_fn(p, batch, cfg)[0])(params)
        return float(loss), g

    base_loss, base_g = loss_and_grad(0)
    for chunk in (16, 24, 1000):   # divides, tail, larger-than-S
        loss, g = loss_and_grad(chunk)
        assert abs(loss - base_loss) < 1e-4, (chunk, loss, base_loss)
        diff = max(float(jnp.abs(a - b).max())
                   for a, b in zip(jax.tree.leaves(base_g),
                                   jax.tree.leaves(g)))
        assert diff < 5e-3, (chunk, diff)


def test_kv_decode_matches_forward(nano):
    """prefill + decode_step produce the same greedy continuation as
    re-running the full forward each step (the KV cache is exact, not
    approximate)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt, gpt_decode

    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, nano.vocab_size, (2, 8)).astype(np.int32)

    # Reference: greedy decode by full re-forward.
    toks = jnp.asarray(prompt)
    want = []
    for _ in range(4):
        logits = gpt.forward(params, toks, nano)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(np.asarray(nxt))
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)

    got = [np.asarray(t) for t in gpt_decode.generate(
        params, jnp.asarray(prompt), nano, max_new_tokens=4, max_len=32)]
    assert all((g == w).all() for g, w in zip(got, want)), (got, want)


def test_kv_decode_logits_close(nano):
    """Numerics: decode-step logits at each position match the full
    forward within bf16 tolerance."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt, gpt_decode

    params = gpt.init_params(jax.random.PRNGKey(1), nano)
    rng = np.random.default_rng(1)
    seq = rng.integers(0, nano.vocab_size, (1, 12)).astype(np.int32)

    full = np.asarray(gpt.forward(params, jnp.asarray(seq), nano))

    cache = gpt_decode.init_cache(nano, 1, 16)
    logits_p, cache = gpt_decode.prefill(
        params, jnp.asarray(seq[:, :8]), nano, cache)
    np.testing.assert_allclose(np.asarray(logits_p), full[:, 7],
                               rtol=0.1, atol=0.15)
    for i in range(8, 12):
        logits_d, cache = gpt_decode.decode_step(
            params, cache, jnp.asarray(seq[:, i]), nano)
        np.testing.assert_allclose(np.asarray(logits_d), full[:, i],
                                   rtol=0.1, atol=0.15)


def test_1b_config_compiles_on_8dev_fsdp_mesh():
    """The '1b' preset (VERDICT r2 weak #9): its REAL flags — chunked CE
    (loss_chunk=256), remat='dots', fsdp=8 sharding — must lower AND
    compile on the virtual 8-device mesh. AOT via ShapeDtypeStructs, so
    no 1B-param arrays materialize; GSPMD partitioning still fully
    checks the sharding plan (``benchmarks/lm_sharded.py --config 1b``
    runs this exact construction on hardware)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    cfg = dataclasses.replace(gpt.CONFIGS["1b"], remat="dots",
                              attn_backend="auto")
    assert cfg.num_params() > 1_000_000_000  # it really is the 1B model
    mesh = create_mesh({"fsdp": 8})
    init, step, state_sh, batch_sh = gpt.make_train_step(cfg, mesh)

    state_shapes = jax.eval_shape(init, jax.random.PRNGKey(0))
    state_in = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shapes, state_sh)
    tokens = jax.ShapeDtypeStruct((16, 513), jnp.int32,
                                  sharding=batch_sh)
    lowered = step.lower(state_in, {"tokens": tokens})
    # The partitioner must actually shard the big tensors on the fsdp
    # axis — all-replicated shardings (no axis bindings) would mean the
    # 1B params are copied to every chip. Accept either lowering
    # dialect: Shardy (axis name appears in sdy.sharding bindings) or
    # GSPMD ("devices=[...]" tile assignments).
    txt = lowered.as_text()
    tiled_shardy = "sdy.sharding" in txt and '{"fsdp"' in txt
    tiled_gspmd = "devices=[" in txt
    assert tiled_shardy or tiled_gspmd, \
        "no tiled sharding annotation in lowered module"
    compiled = lowered.compile()
    assert compiled is not None
