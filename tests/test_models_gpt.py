"""GPT flagship model: forward shapes, sharded train step, convergence."""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


def test_forward_shapes(nano):
    import jax

    from ray_tpu.models import gpt

    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    tokens = np.zeros((2, 16), np.int32)
    logits = gpt.forward(params, tokens, nano)
    assert logits.shape == (2, 16, nano.vocab_size)
    assert logits.dtype == np.float32


def test_causality(nano):
    """Changing a future token must not affect earlier logits."""
    import jax

    from ray_tpu.models import gpt

    params = gpt.init_params(jax.random.PRNGKey(0), nano)
    t1 = np.zeros((1, 16), np.int32)
    t2 = t1.copy()
    t2[0, -1] = 7
    l1 = np.asarray(gpt.forward(params, t1, nano))
    l2 = np.asarray(gpt.forward(params, t2, nano))
    assert np.allclose(l1[0, :-1], l2[0, :-1], atol=1e-3)
    assert not np.allclose(l1[0, -1], l2[0, -1], atol=1e-3)


@pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 2, "fsdp": 2, "tp": 2},
                                  {"fsdp": 8}])
def test_sharded_train_step_loss_decreases(nano, axes):
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    mesh = create_mesh(axes)
    init, step, state_sh, batch_sh = gpt.make_train_step(nano, mesh)
    state = init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = {"tokens": jax.device_put(
        rng.integers(0, nano.vocab_size, (8, 33)).astype(np.int32),
        batch_sh)}
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state["step"]) == 5


def test_sharding_plans_agree(nano):
    """dp-only and fsdp+tp shardings compute the same loss trajectory."""
    import jax

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, nano.vocab_size, (8, 33)).astype(np.int32)

    def run(axes):
        mesh = create_mesh(axes)
        init, step, _, batch_sh = gpt.make_train_step(nano, mesh)
        state = init(jax.random.PRNGKey(0))
        batch = {"tokens": jax.device_put(tokens, batch_sh)}
        out = []
        for _ in range(3):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    a = run({"dp": 8})
    b = run({"fsdp": 4, "tp": 2})
    assert np.allclose(a, b, rtol=2e-2), (a, b)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
