"""Lineage-based object recovery (reference capability:
``src/ray/core_worker/object_recovery_manager.h:41``, lineage
resubmission ``task_manager.h:208``): a lost normal-task result is
rebuilt by re-executing its producing task, transitively through its
dependencies, without user-visible errors."""
import gc
import os
import signal
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.worker import CoreWorker


def _core():
    return CoreWorker._current


def _shm_delete(oid):
    """Simulate segment loss (node crash / spill file eviction): unlink
    the backing file so every future attach fails, then drop any local
    index entry."""
    try:
        os.unlink(f"/dev/shm/{_core().shm_store._name(oid)}")
    except FileNotFoundError:
        pass
    _core().shm_store.delete(oid)


def test_recover_shm_result(rt_cluster):
    @rt.remote
    def make(n):
        return np.arange(n, dtype=np.float32)

    ref = make.remote(1 << 20)  # 4 MB -> shm tier
    first = rt.get(ref)
    _shm_delete(ref.object_id)
    rebuilt = rt.get(ref)
    assert np.array_equal(first, rebuilt)


def test_recover_transitive_chain(rt_cluster):
    """Losing both a result AND its (freed) upstream dependency rebuilds
    the whole chain."""

    @rt.remote
    def base():
        return np.ones(1 << 20, dtype=np.float32)  # 4 MB -> shm

    @rt.remote
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    assert rt.get(d)[0] == 2.0
    # Lose the downstream result and the upstream value, then drop the
    # upstream ref entirely — recovery must re-run base() from lineage
    # (its entry is pinned by double's lineage).
    _shm_delete(d.object_id)
    _shm_delete(b.object_id)
    bid = b.object_id
    del b
    gc.collect()
    rebuilt = rt.get(d)
    assert rebuilt[0] == 2.0 and rebuilt.shape == (1 << 20,)


def test_put_objects_not_recoverable(rt_cluster):
    """rt.put has no lineage (matches the reference default): loss is a
    user-visible ObjectLostError, not silent corruption."""
    ref = rt.put(np.zeros(1 << 20, dtype=np.float32))
    rt.get(ref)
    _shm_delete(ref.object_id)
    with pytest.raises((rt.exceptions.ObjectLostError,
                        rt.exceptions.GetTimeoutError)):
        rt.get(ref, timeout=3)


def test_recovery_counted_in_metrics(rt_cluster):
    from ray_tpu._private.metrics import core_metrics

    def total():
        return sum(v for _, v in
                   core_metrics()["objects_recovered"].collect())

    @rt.remote
    def make():
        return np.zeros(1 << 20, dtype=np.float32)

    before = total()
    ref = make.remote()
    rt.get(ref)
    _shm_delete(ref.object_id)
    rt.get(ref)
    assert total() > before


def test_chaos_worker_killed_holding_shm_intermediates(rt_fresh):
    """Kill the worker whose shm holds a pipeline's intermediate objects
    mid-run; downstream consumption recovers via lineage (VERDICT round
    2, 'Next round' item 2)."""
    rt = rt_fresh

    @rt.remote
    def produce(i):
        return np.full(1 << 19, i, dtype=np.float32)  # 2 MB each

    @rt.remote
    def consume(x):
        return float(x[0])

    refs = [produce.remote(i) for i in range(8)]
    rt.get([consume.remote(r) for r in refs])  # materialize all

    # Kill every leased worker (SIGKILL: segments created by them survive
    # in /dev/shm, but lose their creator) AND delete half the segments
    # outright to simulate the crash taking data with it.
    for w in rt.state("workers"):
        if w.get("pid") and w["pid"] != os.getpid():
            try:
                os.kill(w["pid"], signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    for r in refs[::2]:
        _shm_delete(r.object_id)

    # Consumers see every value again — rebuilt where necessary.
    out = rt.get([consume.remote(r) for r in refs], timeout=120)
    assert out == [float(i) for i in range(8)]


def test_multinode_node_death_objects_recovered():
    """Kill a node whose worker produced (and whose shm domain holds)
    objects a live consumer still needs; the owner re-executes the
    producing tasks elsewhere."""
    from ray_tpu.cluster_utils import Cluster

    if rt.is_initialized():
        rt.shutdown()
    cluster = Cluster()  # head has no CPU: tasks land on nodes
    try:
        n1 = cluster.add_node(num_cpus=4)
        cluster.connect()

        @rt.remote
        def produce(i):
            return np.full(1 << 19, i, dtype=np.float32)

        @rt.remote
        def consume(x):
            return float(x[0])

        refs = [produce.remote(i) for i in range(4)]
        assert rt.get([consume.remote(r) for r in refs],
                      timeout=60) == [0.0, 1.0, 2.0, 3.0]

        n2 = cluster.add_node(num_cpus=4)
        cluster.remove_node(n1)  # the producing node (and its shm) dies
        # The driver owns the refs; its pulls now re-execute the
        # producers on the surviving node.
        out = rt.get([consume.remote(r) for r in refs], timeout=120)
        assert out == [0.0, 1.0, 2.0, 3.0]
    finally:
        try:
            rt.shutdown()
        except Exception:
            pass
        cluster.shutdown()
