"""Crash-safe streaming (ISSUE 7): in-flight generation survives
replica failure, driver failure, and planned restarts.

- A mid-stream engine-driver death re-routes the stream through the
  retry path with a replay token (``resume_from``); the resumed stream
  is TOKEN-IDENTICAL to an uninterrupted run (temp 0 and seeded
  temp > 0, flat and paged engines).
- Resume respects the ORIGINAL deadline and withdraws from the retry
  budget; a second crash during replay fails cleanly with a typed
  error after the budget runs dry.
- A wedged driver is detected by ``check_health`` and recovered by a
  one-shot driver restart WITHOUT replacing the replica.
- ``replica.drain`` stops admissions (retryable pushback), finishes
  running lanes, and the controller drains before teardown.
"""
import sys
import threading
import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _ref_chunked(params, prompt, cfg, max_new, **kw):
    from ray_tpu.models import gpt_decode

    return np.concatenate([s[0] for s in gpt_decode.generate_chunked(
        params, np.asarray(prompt)[None], cfg, max_new, **kw)])


def _mk_prompt(rid: int, vocab: int, n: int = 8):
    return np.random.default_rng(900 + rid).integers(
        0, vocab, (n,)).astype(np.int32)


def _chaos_deployment(serve, *, paged=False, temperature=0.0,
                      deployment="chaos", num_replicas=2):
    """Continuous-engine deployment; every stream is a deterministic
    function of (rid, max_new) — identical weights and per-request
    seeds on every replica, so a resume replays exactly."""

    @serve.deployment(num_replicas=num_replicas, max_ongoing_requests=8,
                      health_check_period_s=0.3,
                      graceful_shutdown_timeout_s=10.0)
    class ChaosGPT:
        def __init__(self, paged: bool, temperature: float,
                     deployment: str):
            import jax

            from ray_tpu.models import gpt
            from ray_tpu.serve.engine import DecodeEngine

            self.cfg = gpt.CONFIGS["nano"]
            params = gpt.init_params(jax.random.PRNGKey(0), self.cfg)
            self.engine = DecodeEngine(
                params, self.cfg, slots=2, chunk=4, max_len=64,
                prompt_buckets=(8,), deployment=deployment,
                temperature=temperature, paged=paged, page_size=8,
                wedge_timeout_s=2.0)
            # Compile every program NOW, before the replica registers:
            # health probes start at registration, and a first-dispatch
            # XLA compile stalls the driver loop longer than the tight
            # wedge_timeout_s this test runs with.
            list(self.engine.stream(
                np.arange(8, dtype=np.int32) % self.cfg.vocab_size, 6,
                seed=0))

        @serve.batch(continuous=True)
        def decode(self, request):
            # The prompt rides IN the request so a resume resubmission
            # replays the identical call with zero server-side state.
            import numpy as _np

            return self.engine, {
                "prompt": _np.asarray(request["prompt"], _np.int32),
                "max_new": int(request["max_new"]),
                "seed": int(request["rid"])}

        def __call__(self, request):
            return self.decode(request)

    # One name end to end: app, deployment, and engine metric label.
    return ChaosGPT.options(name=deployment).bind(
        paged, temperature, deployment)


def _req(rid: int, max_new: int, vocab: int) -> dict:
    return {"rid": rid, "max_new": max_new,
            "prompt": _mk_prompt(rid, vocab).tolist()}


def _replica_engine_stats(handles) -> dict:
    """{rid: engine stats dict} via each replica's get_metrics."""
    import ray_tpu as rt

    out = {}
    for r, h in handles.items():
        try:
            m = rt.get(h.get_metrics.remote(), timeout=10)
            out[r] = (m.get("engines") or [{}])[0]
        except Exception:  # noqa: BLE001 - replica dead (chaos test!)
            pass
    return out


def _warm(handle, req, ref):
    """One uninterrupted baseline stream per replica-ish (two passes),
    asserting token identity — also compiles every program so chaos
    timing is not dominated by XLA."""
    for _ in range(2):
        base = np.concatenate([np.asarray(x).ravel() for x in
                               handle.options(stream=True).remote(req)])
        assert (base == ref).all(), (base, ref)


@pytest.mark.parametrize("paged,temperature",
                         [(False, 0.0), (False, 1.0), (True, 0.0),
                          (True, 1.0)])
def test_resume_after_driver_death_token_identical(
        rt_cluster, nano, nano_params, paged, temperature):
    """Kill the serving engine's driver mid-stream: the client stream
    stalls, resumes on the other replica, and the concatenation is
    token-identical to an uninterrupted run — flat AND paged engines,
    greedy AND seeded sampling."""
    import jax

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.testing import _serve_replica_handles, inject_engine_fault

    name = f"chaos_{int(paged)}_{int(temperature)}"
    serve.start(proxy=False)
    try:
        handle = serve.run(
            _chaos_deployment(serve, paged=paged, temperature=temperature,
                              deployment=name),
            name=name, route_prefix=None)
        rid, max_new = 3, 40
        kw = {"chunk": 4, "max_len": 64}
        if temperature:
            kw.update(temperature=1.0, rng=jax.random.PRNGKey(rid))
        req = _req(rid, max_new, nano.vocab_size)
        ref = _ref_chunked(nano_params, _mk_prompt(rid, nano.vocab_size),
                           nano, max_new, **kw)
        _warm(handle, req, ref)
        handles = _serve_replica_handles(name, name)
        assert len(handles) == 2
        # Throttle both engines (~1 chunk / 30 ms) so the stream is
        # reliably mid-flight when the kill lands.
        inject_engine_fault(name, name, kind="driver_slow", wedge_s=0.03)

        def killer():
            # Arm driver death at the CURRENT delivered-token count of
            # whichever engine is serving this stream; the idle engine
            # is left alone.
            for r, st in _replica_engine_stats(handles).items():
                if st.get("active_slots", 0) > 0:
                    rt.get(handles[r].inject_engine_fault.remote(
                        "driver_die", int(st["tokens"]), 0.0), timeout=10)

        fired = False
        toks = []
        it = handle.options(stream=True, resumable=True,
                            timeout_s=60.0).remote(req)
        for item in it:
            toks.extend(int(t) for t in np.asarray(item).ravel())
            if not fired and len(toks) >= 6:
                fired = True
                killer()
        assert fired, "stream finished before the fault could fire"
        assert toks == [int(t) for t in ref], (toks, ref)

        # The resume is visible end to end: router metric, engine stat.
        from ray_tpu._private.metrics import serve_metrics

        resumes = dict(serve_metrics()["stream_resumes"].collect())
        assert resumes.get((("deployment", name),), 0) >= 1
        total_resumed = sum(
            st.get("resumed", 0)
            for st in _replica_engine_stats(handles).values())
        assert total_resumed >= 1
        serve.delete(name)
    finally:
        serve.shutdown()


def test_resume_respects_deadline_and_budget():
    """Unit-level contract of the mid-stream resume decision: an
    expired original deadline forbids the resume (the failure
    surfaces), and each successful resume withdraws one retry-budget
    token and carries the delivered-token replay count."""
    from ray_tpu.exceptions import ActorDiedError
    from ray_tpu.serve.handle import (DeploymentResponseGenerator,
                                      RetryBudget, Router)

    class FakeRouter:
        deployment_name = "fake_dep"

        def __init__(self, tokens):
            self.budget = RetryBudget(deposit_ratio=0.0, reserve_per_s=0.0,
                                      initial=tokens)
            self.submissions = []
            self.marked = []

        def mark_dead(self, rid):
            self.marked.append(rid)

        def note_overloaded(self, rid):
            pass

        def release(self, rid):
            pass

        def _submit_stream_raw(self, method, args, kwargs, deadline_s,
                               model_id, flatten_chunks, resume_from=0):
            self.submissions.append(
                {"resume_from": resume_from, "deadline_s": deadline_s})
            return "rid2", iter(())

    def dead_gen():
        raise ActorDiedError("replica crashed mid-stream")
        yield  # pragma: no cover

    # (a) original deadline already passed: NO resume, original error.
    router = FakeRouter(tokens=10.0)
    g = DeploymentResponseGenerator(
        router, "rid1", dead_gen(), call=("m", (), {}),
        deadline_s=time.time() - 1.0, resumable=True)
    g._got_first, g._delivered = True, 5
    with pytest.raises(ActorDiedError):
        next(g)
    assert router.submissions == []

    # (b) live deadline: resume carries resume_from=delivered and the
    # ORIGINAL deadline, and withdraws exactly one budget token.
    router = FakeRouter(tokens=1.0)
    deadline = time.time() + 60.0
    g = DeploymentResponseGenerator(
        router, "rid1", dead_gen(), call=("m", (), {}),
        deadline_s=deadline, resumable=True)
    g._got_first, g._delivered = True, 7
    # The resubmitted stream is empty -> clean StopIteration after the
    # transparent resume.
    with pytest.raises(StopIteration):
        next(g)
    assert router.submissions == [
        {"resume_from": 7, "deadline_s": deadline}]
    assert router.budget.tokens() < 1.0      # the token was withdrawn
    assert router.marked == ["rid1"]

    # (c) dry budget: the resume is refused, the failure surfaces.
    router = FakeRouter(tokens=0.0)
    g = DeploymentResponseGenerator(
        router, "rid1", dead_gen(), call=("m", (), {}),
        deadline_s=time.time() + 60.0, resumable=True)
    g._got_first, g._delivered = True, 3
    with pytest.raises(ActorDiedError):
        next(g)
    assert router.submissions == []

    # (d) resumable=False keeps the old mid-stream contract: raise.
    router = FakeRouter(tokens=10.0)
    g = DeploymentResponseGenerator(
        router, "rid1", dead_gen(), call=("m", (), {}),
        deadline_s=time.time() + 60.0, resumable=False)
    g._got_first, g._delivered = True, 3
    with pytest.raises(ActorDiedError):
        next(g)
    assert router.submissions == []
    assert Router.DEFAULT_MAX_RETRIES >= 1   # sanity: retries exist


def test_second_crash_during_replay_fails_cleanly(rt_cluster, nano,
                                                  nano_params):
    """Both replicas die (the second DURING the replay) with only one
    retry token in the budget: the client gets a clean typed error — no
    hang — and every token delivered before the failure is the correct
    prefix (no duplicates from the partial replay)."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                    TaskError, WorkerCrashedError)
    from ray_tpu.serve.handle import RetryBudget, get_router
    from ray_tpu.testing import _serve_replica_handles, inject_engine_fault

    name = "chaos_double"
    serve.start(proxy=False)
    try:
        handle = serve.run(_chaos_deployment(serve, deployment=name),
                           name=name, route_prefix=None)
        rid, max_new = 7, 40
        req = _req(rid, max_new, nano.vocab_size)
        ref = _ref_chunked(nano_params, _mk_prompt(rid, nano.vocab_size),
                           nano, max_new, chunk=4, max_len=64)
        _warm(handle, req, ref)
        handles = _serve_replica_handles(name, name)
        inject_engine_fault(name, name, kind="driver_slow", wedge_s=0.03)
        # Exactly ONE retry token, no replenishment: the first process
        # kill resumes, the second exhausts the budget and must raise.
        router = get_router(name, name)
        router.budget = RetryBudget(deposit_ratio=0.0, reserve_per_s=0.0,
                                    initial=1.0)

        def kill_all_soon():
            # Each replica's engine hard-exits two DELIVERED tokens
            # after arming: the serving replica dies now; the resume
            # target dies mid-replay (replayed/suppressed tokens do not
            # count — only the fresh continuation does).
            for r, st in _replica_engine_stats(handles).items():
                rt.get(handles[r].inject_engine_fault.remote(
                    "kill_process", int(st.get("tokens", 0)) + 2, 0.0),
                    timeout=10)

        toks = []
        fired = False
        with pytest.raises(Exception) as ei:
            it = handle.options(stream=True, resumable=True,
                                timeout_s=30.0).remote(req)
            for item in it:
                toks.extend(int(t) for t in np.asarray(item).ravel())
                if not fired and len(toks) >= 6:
                    fired = True
                    kill_all_soon()
        assert fired
        e = ei.value
        assert isinstance(e, (ActorDiedError, ActorUnavailableError,
                              WorkerCrashedError, TaskError,
                              ConnectionError, TimeoutError)), repr(e)
        # Everything delivered before the failure is the exact prefix.
        assert toks == [int(t) for t in ref[:len(toks)]]
        assert len(toks) < max_new
        serve.delete(name)
    finally:
        serve.shutdown()


def test_wedged_driver_recovers_without_replacement(rt_cluster, nano,
                                                    nano_params):
    """A wedged engine driver (live thread, stale heartbeat) is detected
    by check_health on the controller's health pass and recovered by a
    one-shot driver restart — the replica set is UNCHANGED."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.testing import _serve_replica_handles, inject_engine_fault

    name = "chaos_wedge"
    serve.start(proxy=False)
    try:
        handle = serve.run(_chaos_deployment(serve, deployment=name),
                           name=name, route_prefix=None)
        rid, max_new = 9, 24
        req = _req(rid, max_new, nano.vocab_size)
        ref = _ref_chunked(nano_params, _mk_prompt(rid, nano.vocab_size),
                           nano, max_new, chunk=4, max_len=64)
        _warm(handle, req, ref)
        rids_before = set(_serve_replica_handles(name, name))
        assert len(rids_before) == 2
        # Wedge BOTH drivers past wedge_timeout_s=1.0; health period is
        # 0.3 s, so the pass must restart them, not replace replicas.
        armed = inject_engine_fault(name, name, kind="driver_wedge",
                                    wedge_s=4.0)
        assert len(armed) == 2
        deadline = time.time() + 30
        restarted = 0
        while time.time() < deadline:
            handles = _serve_replica_handles(name, name)
            restarted = sum(
                st.get("driver_restarts", 0)
                for st in _replica_engine_stats(handles).values())
            if restarted >= 2:
                break
            time.sleep(0.2)
        assert restarted >= 2, "wedged drivers were not restarted"
        rids_after = set(_serve_replica_handles(name, name))
        assert rids_after == rids_before, \
            f"replica set changed: {rids_before} -> {rids_after}"
        # The deployment still serves, token-identically, on the SAME
        # replicas.
        out = np.concatenate([np.asarray(x).ravel() for x in
                              handle.options(stream=True).remote(req)])
        assert (out == ref).all()
        # Driver-restart visibility: engine stats aggregated into
        # serve.status() by the controller's health pass.
        deadline = time.time() + 10
        agg = {}
        while time.time() < deadline:
            st = serve.status()
            agg = st["applications"][name]["deployments"][name] \
                .get("engine") or {}
            if agg.get("driver_restarts", 0) >= 2:
                break
            time.sleep(0.3)
        assert agg.get("driver_restarts", 0) >= 2, agg
        # queue_depth rides the same controller aggregation (ISSUE 11
        # satellite): present whenever engine stats flow at all.
        assert "queue_depth" in agg, agg
        serve.delete(name)
    finally:
        serve.shutdown()


def test_drain_stops_admissions_finishes_lanes(rt_cluster, nano,
                                               nano_params):
    """replica.drain: a running stream completes token-identically, new
    admissions push back with a retryable typed error, and the drain
    reports clean."""
    from ray_tpu import serve
    from ray_tpu.exceptions import TaskError
    from ray_tpu.serve.request import ReplicaDrainingError
    from ray_tpu.testing import drain_replicas, inject_engine_fault

    name = "chaos_drain"
    serve.start(proxy=False)
    try:
        handle = serve.run(
            _chaos_deployment(serve, deployment=name, num_replicas=1),
            name=name, route_prefix=None)
        rid, max_new = 11, 40
        req = _req(rid, max_new, nano.vocab_size)
        ref = _ref_chunked(nano_params, _mk_prompt(rid, nano.vocab_size),
                           nano, max_new, chunk=4, max_len=64)
        _warm(handle, req, ref)
        inject_engine_fault(name, name, kind="driver_slow", wedge_s=0.02)

        out = {}

        def consume():
            toks = []
            for item in handle.options(stream=True).remote(req):
                toks.extend(int(t) for t in np.asarray(item).ravel())
            out["toks"] = toks

        t = threading.Thread(target=consume)
        t.start()
        time.sleep(0.2)            # stream is mid-flight (throttled)
        drained = drain_replicas(name, name, timeout_s=20.0)
        t.join(timeout=30)
        assert not t.is_alive()
        assert out["toks"] == [int(x) for x in ref], \
            "in-flight stream must finish identically through a drain"
        assert all(drained.values()), drained
        # New admissions on the drained replica push back with a typed
        # retryable error; with no other replica the request times out
        # at its deadline rather than hard-failing.
        with pytest.raises(Exception) as ei:
            list(handle.options(stream=True, timeout_s=2.0).remote(
                _req(rid, 4, nano.vocab_size)))
        e = ei.value
        ok_err = isinstance(e, (ReplicaDrainingError, TimeoutError)) or (
            isinstance(e, TaskError) and e.cause_type in (
                "ReplicaDrainingError", "EngineShutdownError"))
        assert ok_err, repr(e)
        serve.delete(name)
    finally:
        serve.shutdown()


def test_controller_drains_before_teardown(rt_cluster):
    """Teardown routes through the graceful drain: the controller-side
    drain counter reaches the head's merged /metrics with one increment
    per torn-down replica."""
    import ray_tpu as rt
    from ray_tpu import serve

    name = "chaos_scaledown"
    serve.start(proxy=False)
    try:
        @serve.deployment(num_replicas=2)
        class Echo:
            def __call__(self, x):
                return x

        h = serve.run(Echo.bind(), name=name, route_prefix=None)
        assert h.remote("ping").result(timeout=30) == "ping"
        serve.delete(name)
        deadline = time.time() + 30
        drained = 0.0
        while time.time() < deadline:
            try:
                text = rt.metrics_text()
            except Exception:  # noqa: BLE001 - head mid-flush
                text = ""
            drained = sum(
                float(line.rsplit(" ", 1)[1])
                for line in text.splitlines()
                if line.startswith("ray_tpu_serve_replica_drains_total")
                and 'deployment="Echo"' in line)
            if drained >= 2:
                break
            time.sleep(0.5)
        assert drained >= 2, "teardown did not drain replicas"
    finally:
        serve.shutdown()


def test_chaos_smoke_benchmark():
    """Satellite CI hook: ``benchmarks/serve_gpt.py --chaos --smoke``
    kills a replica mid-load and asserts ZERO client-visible broken
    streams, with every stream token-identical to its reference."""
    import json
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "benchmarks", "serve_gpt.py"),
         "--chaos", "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    chaos = [r for r in rows if r["metric"].endswith("chaos_recovery")]
    assert chaos, rows
    row = chaos[0]
    assert row["smoke"] is True
    assert row["broken_streams"] == 0
    assert row["kills"] >= 1
    assert row["completed"] == row["requests"]
