"""Mesh, sharding-rule, and collective-group tests on the virtual 8-CPU mesh.

Mirrors the reference's collective test layout
(``python/ray/util/collective/tests/single_node_cpu_tests/``) with the xla
mesh backend in place of gloo.
"""
import numpy as np
import pytest


@pytest.fixture(scope="module")
def mesh8():
    from ray_tpu.parallel import create_mesh

    return create_mesh({"dp": 8})


def test_mesh_axes_resolution():
    from ray_tpu.parallel import create_mesh, mesh_shape

    m = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert mesh_shape(m) == {"dp": 2, "fsdp": 2, "tp": 2}
    # tp must be the innermost (last) axis
    assert m.axis_names[-1] == "tp"

    m2 = create_mesh({"dp": -1, "tp": 2})
    assert mesh_shape(m2) == {"dp": 4, "tp": 2}


def test_mesh_bad_shape():
    from ray_tpu.parallel import create_mesh

    with pytest.raises(ValueError):
        create_mesh({"dp": 3, "tp": 3})


def test_sharding_rules(mesh8):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import spec_for, LM_RULES

    assert spec_for("block/wq/kernel", (64, 64), LM_RULES, mesh8) != None  # noqa
    # dp-only mesh: fsdp/tp axes degrade to replication
    s = spec_for("block/wq/kernel", (64, 64), LM_RULES, mesh8)
    assert s == P()


def test_sharding_rules_fsdp_tp():
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel import create_mesh, spec_for, LM_RULES

    m = create_mesh({"fsdp": 4, "tp": 2})
    assert spec_for("block/wq/kernel", (64, 64), LM_RULES, m) == \
        P(("fsdp",), "tp")
    # indivisible dim → that dim replicated
    assert spec_for("block/wq/kernel", (63, 64), LM_RULES, m) == \
        P(None, "tp")
    assert spec_for("ln1_scale", (64,), LM_RULES, m) == P()


def test_xla_collective_group(mesh8):
    from ray_tpu.collective import collective as C

    g = C.XlaMeshGroup("t", mesh8, "dp")
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    assert np.allclose(np.asarray(g.allreduce(x)), x.sum(0))
    assert np.allclose(np.asarray(g.allreduce(x, "max")), x.max(0))
    assert np.allclose(np.asarray(g.allgather(x)), x)
    # global view of the scatter: row r (rank r's shard) = sum across ranks
    rs = np.asarray(g.reducescatter(np.ones((8, 4), np.float32)))
    assert rs.shape == (8, 4) and np.allclose(rs, 8.0)
    # non-sum reductions must honor ``op`` (every rank holds the same
    # replicated input, so max/min across ranks is the input itself)
    y = np.arange(32, dtype=np.float32).reshape(8, 4)
    assert np.allclose(np.asarray(g.reducescatter(y, "max")), y)
    assert np.allclose(np.asarray(g.reducescatter(y, "min")), y)
    m = np.arange(64, dtype=np.float32).reshape(8, 8)
    assert np.allclose(np.asarray(g.alltoall(m)), m.T)
    g.barrier()


def test_store_collective_group_across_actors(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Ranker:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self):
            import numpy as np

            from ray_tpu.collective import collective as C

            g = C.StoreGroup(f"grp", self.world, self.rank)
            out = g.allreduce(np.full((4,), float(self.rank + 1)))
            bc = g.broadcast(
                np.arange(3.0) if self.rank == 0 else None, src_rank=0)
            g.barrier()
            return out.tolist(), list(np.asarray(bc))

    world = 3
    actors = [Ranker.remote(r, world) for r in range(world)]
    outs = rt.get([a.run.remote() for a in actors], timeout=60)
    for ar, bc in outs:
        assert ar == [6.0, 6.0, 6.0, 6.0]  # 1+2+3
        assert bc == [0.0, 1.0, 2.0]
