"""TPU gang resources: slice detection, head anchors, topology gangs.

Mirrors the reference's TPU accelerator-manager coverage
(``python/ray/tests/accelerators/test_tpu.py``): pod-type parsing, the
``TPU-{pod}-head`` anchor on worker 0, and topology-driven gang placement
refusing to straddle slices.
"""
import pytest

from ray_tpu._private import accelerators as acc
from ray_tpu.train.config import ScalingConfig


def test_normalize_and_parse():
    assert acc.normalize_pod_type("v5litepod-16") == "v5e-16"
    assert acc.normalize_pod_type("v4-8") == "v4-8"
    assert acc.parse_topology("v5e-16") == ("v5e", 16)
    with pytest.raises(ValueError, match="malformed"):
        acc.parse_topology("v5e")


def test_gang_resources_head_anchor(monkeypatch):
    monkeypatch.setenv("RT_TPU_TOPOLOGY", "v5litepod-16")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    res = acc.gang_resources(4)
    assert res["TPU-v5e-16-head"] == 1.0
    assert res["accelerator_type:TPU-V5E"] == 4.0

    monkeypatch.setenv("TPU_WORKER_ID", "2")
    res = acc.gang_resources(4)
    assert "TPU-v5e-16-head" not in res  # only worker 0 anchors the slice

    monkeypatch.delenv("RT_TPU_TOPOLOGY")
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    assert acc.gang_resources(4) == {}  # off-TPU: no gang resources


def test_scaling_config_topology_bundles():
    sc = ScalingConfig(num_workers=4, use_tpu=True, tpus_per_worker=4,
                       topology="v5e-16")
    bs = sc.bundles()
    assert len(bs) == 4
    assert bs[0]["TPU-v5e-16-head"] == 1.0
    assert all("TPU-v5e-16-head" not in b for b in bs[1:])
    assert sc.effective_placement_strategy == "STRICT_PACK"

    with pytest.raises(ValueError, match="16 chips"):
        ScalingConfig(num_workers=2, use_tpu=True, tpus_per_worker=4,
                      topology="v5e-16").bundles()


def test_gang_placement_refuses_mixed_slices():
    """Two single-host slices: a 2-host gang anchored to one slice must
    place both bundles on that slice's node (STRICT_PACK), and a gang
    anchored to a slice that lacks capacity must stay infeasible."""
    from ray_tpu.cluster_utils import Cluster
    import ray_tpu as rt_mod

    if rt_mod.is_initialized():
        rt_mod.shutdown()
    cluster = Cluster(head_resources={"CPU": 0.0})
    try:
        cluster.add_node(num_cpus=4, num_tpus=4,
                         resources={"TPU-v5e-8-head": 1.0})
        cluster.add_node(num_cpus=4, num_tpus=4,
                         resources={"TPU-v5e-16-head": 1.0})
        rt = cluster.connect()

        sc = ScalingConfig(num_workers=1, use_tpu=True, tpus_per_worker=4,
                           resources_per_worker={"CPU": 1.0},
                           topology="v5e-8")
        # Drop chip validation mismatch: 1x4 != 8 chips → use plain bundles
        bundles = [{"CPU": 1.0, "TPU": 4.0, "TPU-v5e-8-head": 1.0}]
        pg = rt.placement_group(bundles, strategy="STRICT_PACK")
        pg.ready(timeout=30)

        # A STRICT_PACK gang needing more TPU than the anchored slice's
        # node offers cannot be satisfied by borrowing the other slice.
        bad = rt.placement_group(
            [{"TPU": 4.0, "TPU-v5e-8-head": 1.0}, {"TPU": 8.0}],
            strategy="STRICT_PACK")
        with pytest.raises(Exception, match="not ready"):
            bad.ready(timeout=3)
        rt.remove_placement_group(bad)
        rt.remove_placement_group(pg)
    finally:
        cluster.shutdown()
