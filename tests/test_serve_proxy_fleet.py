"""Per-node serve proxies + locality-preferring replica routing
(reference: serve/_private/proxy.py:1116 — a proxy on every node;
pow_2_scheduler's prefer-local-node replica choice)."""
import json
import time
import urllib.request

import pytest


@pytest.fixture
def two_node_cluster():
    import ray_tpu as _rt

    if _rt.is_initialized():
        _rt.shutdown()
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_resources={"CPU": 4})
    rt = c.connect()
    c.add_node(num_cpus=4, shared_shm=True)
    c.wait_for_nodes(2)
    yield c, rt
    try:
        from ray_tpu import serve

        serve.shutdown()
    except Exception:  # noqa: BLE001
        pass
    c.shutdown()


def _http_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def test_proxy_per_node_and_locality(two_node_cluster):
    c, rt = two_node_cluster
    from ray_tpu import serve

    serve.start(http_options={"host": "127.0.0.1", "port": 0})

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 1})
    class Echo:
        def __call__(self, req):
            return {"msg": "hi"}

    serve.run(Echo.bind(), name="app", route_prefix="/echo")

    ctrl = serve.api._controller()
    # One proxy per alive node, reconciled by the controller.
    deadline = time.time() + 30
    proxies = {}
    while time.time() < deadline:
        proxies = rt.get(ctrl.get_proxies.remote(), timeout=10)
        if len(proxies) >= 2:
            break
        time.sleep(0.2)
    assert len(proxies) == 2, proxies
    names = {p["name"] for p in proxies.values()}
    assert "SERVE_PROXY" in names  # legacy primary name retained
    # Every proxy serves traffic (external traffic can hit any node).
    for p in proxies.values():
        info = p["info"]
        out = _http_json(
            f"http://{info['host']}:{info['port']}/echo")
        assert out == {"msg": "hi"}

    # Replicas spread across nodes (SPREAD default) and the controller
    # records each replica's node for locality routing.
    info = rt.get(ctrl.get_replicas.remote("app", "Echo"), timeout=10)
    nodes = set(info["replica_nodes"].values())
    assert len(info["replica_nodes"]) == 2
    assert None not in nodes
    assert len(nodes) == 2, f"replicas not spread: {info['replica_nodes']}"

    # Locality: a driver-side handle prefers the replica on its own node
    # when it has capacity.
    from ray_tpu.core.worker import CoreWorker
    from ray_tpu.serve.handle import get_router

    router = get_router("app", "Echo")
    router.refresh(force=True)
    local_node = CoreWorker._current.node_id
    picked = {router._pick_locked() for _ in range(16)}
    local_rids = {rid for rid, nid in router._replica_nodes.items()
                  if nid == local_node}
    if local_rids:  # driver node hosts a replica -> always chosen
        assert picked <= local_rids, (picked, router._replica_nodes)

    # Node death: its proxy leaves the fleet, the other keeps serving.
    victim = next(n for n in c._nodes)
    dead_node = victim.node_id
    c.remove_node(victim, graceful=False)
    deadline = time.time() + 30
    while time.time() < deadline:
        proxies = rt.get(ctrl.get_proxies.remote(), timeout=10)
        if dead_node not in proxies and len(proxies) == 1:
            break
        time.sleep(0.2)
    assert dead_node not in proxies, proxies
    survivor = next(iter(proxies.values()))["info"]
    out = _http_json(
        f"http://{survivor['host']}:{survivor['port']}/echo")
    assert out == {"msg": "hi"}
