"""Serve: deployments, HTTP proxy, batching, autoscaling, composition, FT.

Mirrors the reference's serve test strategy (e.g.
``python/ray/serve/tests/test_deploy.py``, ``test_batching.py``,
``test_autoscaling_policy.py``): real controller + replicas in-process,
requests through the public API and through raw HTTP.
"""
import json
import threading
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def serve_instance(rt_cluster):
    serve.start(http_options={"host": "127.0.0.1", "port": 0})
    yield serve
    serve.shutdown()


def _http(port, path, payload=None, method=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.read()


def test_deploy_and_handle_call(serve_instance):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

        def triple(self, x):
            return x * 3

    h = serve.run(Doubler.bind(), name="doubler", route_prefix=None)
    assert h.remote(21).result() == 42
    assert h.triple.remote(5).result() == 15
    assert h.options(method_name="triple").remote(4).result() == 12
    serve.delete("doubler")


def test_function_deployment(serve_instance):
    @serve.deployment
    def add_one(req):
        return req + 1

    h = serve.run(add_one.bind(), name="fn", route_prefix=None)
    assert h.remote(41).result() == 42
    serve.delete("fn")


def test_init_args_and_user_config(serve_instance):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting
            self.suffix = ""

        def reconfigure(self, cfg):
            self.suffix = cfg["suffix"]

        def __call__(self, name):
            return f"{self.greeting} {name}{self.suffix}"

    app = Greeter.options(user_config={"suffix": "!"}).bind("hello")
    h = serve.run(app, name="greet", route_prefix=None)
    assert h.remote("tpu").result() == "hello tpu!"
    serve.delete("greet")


def test_http_proxy_routing(serve_instance):
    @serve.deployment
    class Echo:
        def __call__(self, request):
            body = request.json()
            return {"path": request.path, "doubled": body["x"] * 2}

    serve.run(Echo.bind(), name="echo", route_prefix="/echo")
    port = serve.status()["http"]["port"]

    status, body = _http(port, "/echo", {"x": 7})
    assert status == 200
    out = json.loads(body)
    assert out["doubled"] == 14 and out["path"] == "/echo"

    # Unknown route -> 404; health + route listing endpoints work.
    with pytest.raises(urllib.error.HTTPError):
        _http(port, "/nope", {"x": 1})
    status, body = _http(port, "/-/routes")
    assert json.loads(body) == {"/echo": "echo:Echo"}
    serve.delete("echo")


def test_composition_nested_handles(serve_instance):
    @serve.deployment
    class Adder:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.remote(x).result() * 10

    app = Ingress.bind(Adder.bind())
    h = serve.run(app, name="composed", route_prefix=None)
    assert h.remote(3).result() == 40
    serve.delete("composed")


def test_batching_with_bucketed_padding(serve_instance):
    @serve.deployment(max_ongoing_requests=32)
    class BatchModel:
        def __init__(self):
            self.seen_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05,
                     pad_to_bucket=True)
        def predict(self, items):
            # The (padded) batch must land exactly on a bucket size, so a
            # jitted model would only ever compile len(buckets) shapes.
            self.seen_sizes.append(len(items))
            return [x * 2 for x in items]

        def __call__(self, x):
            return self.predict(x)

        def sizes(self, _):
            return self.seen_sizes

    h = serve.run(BatchModel.options(num_replicas=1).bind(),
                  name="batched", route_prefix=None)
    results = [None] * 12

    def call(i):
        results[i] = h.remote(i).result()

    threads = [threading.Thread(target=call, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [i * 2 for i in range(12)]
    sizes = h.sizes.remote(None).result()
    assert sizes, "batch handler never ran"
    assert all(s in (1, 2, 4, 8) for s in sizes), sizes
    assert max(sizes) > 1, f"no batching happened: {sizes}"
    serve.delete("batched")


def test_num_replicas_scaling_and_status(serve_instance):
    @serve.deployment(num_replicas=2)
    class D:
        def __call__(self, x):
            return x

    serve.run(D.bind(), name="multi", route_prefix=None)
    st = serve.status()["applications"]["multi"]["deployments"]["D"]
    assert st["replicas"] == 2 and st["status"] == "HEALTHY"
    serve.delete("multi")


def test_autoscaling_up_and_down(serve_instance):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1,
            upscale_delay_s=0.2, downscale_delay_s=0.5,
            metrics_interval_s=0.1))
    class Slow:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    h = serve.run(Slow.bind(), name="auto", route_prefix=None)

    def hammer():
        for _ in range(12):
            h.remote(1).result()

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    # Under sustained load the controller should add replicas.
    saw_up = False
    deadline = time.time() + 20
    while time.time() < deadline:
        st = serve.status()["applications"]["auto"]["deployments"]["Slow"]
        if st["replicas"] > 1:
            saw_up = True
            break
        time.sleep(0.2)
    for t in threads:
        t.join()
    assert saw_up, "never scaled above 1 replica under load"
    # Idle -> back down to min_replicas.
    deadline = time.time() + 20
    while time.time() < deadline:
        st = serve.status()["applications"]["auto"]["deployments"]["Slow"]
        if st["replicas"] == 1 and st["target"] == 1:
            break
        time.sleep(0.2)
    else:
        pytest.fail("never scaled back down to 1 replica")
    serve.delete("auto")


def test_replica_death_recovery(serve_instance):
    @serve.deployment(num_replicas=2, health_check_period_s=0.2)
    class Svc:
        def __call__(self, x):
            return x + 1

        def die(self, _):
            import os

            os._exit(1)

    h = serve.run(Svc.bind(), name="ft", route_prefix=None)
    assert h.remote(1).result() == 2
    try:
        h.die.remote(None).result(timeout=5)
    except Exception:
        pass
    # Requests keep succeeding (retry on the surviving replica)...
    for i in range(8):
        assert h.remote(i).result(timeout=30) == i + 1
    # ...and the controller heals back to 2 replicas.
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()["applications"]["ft"]["deployments"]["Svc"]
        if st["replicas"] == 2:
            break
        time.sleep(0.2)
    else:
        pytest.fail("controller never restored the dead replica")
    serve.delete("ft")


def test_jitted_model_serving(serve_instance):
    """End-to-end: HTTP -> batched, bucket-padded, jitted forward pass."""
    import numpy as np

    @serve.deployment(max_ongoing_requests=16)
    class JaxModel:
        def __init__(self):
            import jax
            import jax.numpy as jnp

            self.n_compiles = 0
            key = jax.random.PRNGKey(0)
            self.w = jax.random.normal(key, (4, 3))

            @jax.jit
            def fwd(w, x):
                return x @ w

            self._fwd = fwd

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02,
                     pad_to_bucket=True)
        def predict(self, xs):
            import numpy as np

            batch = np.stack(xs)
            return list(np.asarray(self._fwd(self.w, batch)))

        def __call__(self, request):
            x = np.asarray(request.json()["x"], dtype=np.float32)
            return self.predict(x).tolist()

    serve.run(JaxModel.bind(), name="model", route_prefix="/predict")
    port = serve.status()["http"]["port"]
    status, body = _http(port, "/predict", {"x": [1.0, 0.0, 0.0, 0.0]})
    assert status == 200
    out = json.loads(body)
    assert len(out) == 3
    serve.delete("model")


def test_multiplexed_models_lru_and_context(serve_instance):
    @serve.deployment(num_replicas=1)
    class Zoo:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            self.loads.append(model_id)
            return {"id": model_id, "scale": len(model_id)}

        def __call__(self, x):
            model = self.get_model(serve.get_multiplexed_model_id())
            return {"model": model["id"], "y": x * model["scale"]}

        def load_history(self, _):
            return list(self.loads)

    h = serve.run(Zoo.bind(), name="zoo", route_prefix=None)
    # two tenants fit in the cache: one load each
    assert h.options(multiplexed_model_id="a").remote(3).result() == \
        {"model": "a", "y": 3}
    assert h.options(multiplexed_model_id="bb").remote(3).result() == \
        {"model": "bb", "y": 6}
    assert h.options(multiplexed_model_id="a").remote(1).result() == \
        {"model": "a", "y": 1}
    assert h.options(method_name="load_history").remote(0).result() == \
        ["a", "bb"]
    # third tenant evicts LRU ("bb"); revisiting "bb" reloads it
    assert h.options(multiplexed_model_id="ccc").remote(1).result() == \
        {"model": "ccc", "y": 3}
    assert h.options(multiplexed_model_id="bb").remote(1).result() == \
        {"model": "bb", "y": 2}
    assert h.options(method_name="load_history").remote(0).result() == \
        ["a", "bb", "ccc", "bb"]
    # no model id set -> empty string context
    @serve.deployment
    def whoami(_x):
        return serve.get_multiplexed_model_id()

    h2 = serve.run(whoami.bind(), name="whoami", route_prefix=None)
    assert h2.remote(0).result() == ""
    serve.delete("zoo")
    serve.delete("whoami")
