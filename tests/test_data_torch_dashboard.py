"""iter_torch_batches (torch ingestion parity) + dashboard HTML UI."""
import urllib.request

import numpy as np


def test_iter_torch_batches(rt_cluster):
    import torch

    from ray_tpu import data as rtd

    ds = rtd.range(20, block_size=5).map(
        lambda r: {"x": float(r["id"]), "y": r["id"] * 2})
    batches = list(ds.iter_torch_batches(batch_size=8))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    total = torch.cat([b["y"] for b in batches]).sum().item()
    assert total == 2 * sum(range(20))
    # dtype override
    b0 = next(iter(ds.iter_torch_batches(batch_size=4,
                                         dtypes=torch.float32)))
    assert b0["y"].dtype == torch.float32


def test_streaming_split_torch_batches(rt_cluster):
    import torch

    from ray_tpu import data as rtd

    ds = rtd.range(16, block_size=4)
    (it,) = ds.streaming_split(1, equal=True)
    vals = []
    for b in it.iter_torch_batches(batch_size=8):
        assert isinstance(b["id"], torch.Tensor)
        vals.extend(b["id"].tolist())
    assert sorted(vals) == list(range(16))


def test_dashboard_html_ui(rt_fresh):
    rt = rt_fresh
    url = rt.dashboard_url()
    assert url
    with urllib.request.urlopen(url + "/", timeout=10) as resp:
        body = resp.read().decode()
    assert resp.status == 200
    # real UI, not just a link list: the SPA shell + auto-refresh
    # (full per-view coverage lives in tests/test_dashboard_ui.py)
    for marker in ("id=\"nav\"", "/api/state", "setInterval(refresh"):
        assert marker in body, marker
    with urllib.request.urlopen(url + "/api/state?kind=nodes",
                                timeout=10) as resp:
        import json

        nodes = json.loads(resp.read())
    assert len(nodes) >= 1
