"""Observability plane: metrics, prometheus exposition, state API,
dashboard HTTP endpoint, chrome-trace timeline, CLI.

Mirrors the reference's stats/state/dashboard coverage
(``python/ray/tests/test_metrics_agent.py``, ``test_state_api.py``):
instruments aggregate across processes, exposition parses, and the state
listings reflect live cluster entities.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from ray_tpu._private import metrics as m


def test_registry_instruments():
    reg = m.MetricsRegistry()
    c = m.Counter("reqs_total", "requests", registry=reg)
    g = m.Gauge("depth", registry=reg)
    h = m.Histogram("lat_seconds", bounds=(0.1, 1.0), registry=reg)
    c.inc()
    c.inc(2, labels={"route": "/a"})
    g.set(7)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap["reqs_total"]["kind"] == "counter"
    merged = m.merge_snapshots([snap, snap])  # two identical processes
    text = m.render_prometheus(merged)
    assert "ray_tpu_reqs_total 2" in text          # summed counters
    assert 'route="/a"' in text
    assert "ray_tpu_depth 7" in text               # gauge not summed
    assert 'lat_seconds_bucket{le="0.1"} 2' in text
    assert "lat_seconds_count 6" in text


def test_public_metrics_api_and_timer():
    """ray_tpu.util.metrics re-exports the instruments (reference:
    ``ray.util.metrics``) and Histogram.timer observes wall time."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    reg = m.MetricsRegistry()
    h = Histogram("api_lat_seconds", bounds=(0.001, 10.0), registry=reg)
    with h.timer():
        time.sleep(0.005)
    snap = reg.snapshot()["api_lat_seconds"]
    assert snap["kind"] == "histogram"
    ((_, ent),) = snap["values"]
    assert ent[-1] == 1 and 0.001 < ent[-2] < 5.0  # one obs, sane sum
    assert Counter is not None and Gauge is not None


def test_cluster_metrics_and_state(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def work(x):
        return x * 2

    assert rt.get([work.remote(i) for i in range(10)]) == \
        [i * 2 for i in range(10)]
    # Worker snapshots arrive on the ~1s flush cadence; poll briefly.
    deadline = time.time() + 10
    while time.time() < deadline:
        text = rt.metrics_text()
        if "ray_tpu_task_duration_seconds_bucket" in text:
            break
        time.sleep(0.25)
    else:
        pytest.fail("worker metrics never reached the head")
    assert "ray_tpu_tasks_finished_total" in text
    assert "ray_tpu_workers_alive" in text

    summary = rt.state("summary")
    assert summary["workers"] >= 1
    assert summary["resources_total"]["CPU"] == 8.0
    nodes = rt.state("nodes")
    assert any(n["is_head"] for n in nodes)
    workers = rt.state("workers")
    assert len(workers) >= 1
    # Workers flush task events on a ~1s cadence; poll briefly.
    deadline = time.time() + 10
    while time.time() < deadline:
        if any(t["name"] == "work" for t in rt.state("tasks")):
            break
        time.sleep(0.25)
    else:
        pytest.fail("task events never reached the head")


def test_dashboard_http(rt_cluster):
    rt = rt_cluster
    url = rt.dashboard_url()
    assert url and url.startswith("http://127.0.0.1:")

    with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
        body = resp.read().decode()
    assert "ray_tpu_workers_alive" in body

    with urllib.request.urlopen(url + "/api/state?kind=summary",
                                timeout=10) as resp:
        summary = json.loads(resp.read())
    assert summary["nodes"] >= 1

    with urllib.request.urlopen(url + "/api/timeline", timeout=10) as resp:
        events = json.loads(resp.read())
    assert isinstance(events, list)

    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(url + "/nope", timeout=10)


def test_worker_log_tailing(rt_cluster):
    """Worker stdout is fetchable by worker id — via head RPC and via
    the dashboard /api/logs endpoint (reference:
    ``dashboard/modules/log/`` per-node log serving)."""
    rt = rt_cluster

    @rt.remote
    class Chatty:
        def speak(self):
            print("chatty-actor-log-line", flush=True)
            return os.getpid()

    a = Chatty.remote()
    rt.get(a.speak.remote())
    time.sleep(0.3)  # stdout reaches the redirected file

    from ray_tpu.core.worker import CoreWorker

    core = CoreWorker.current()
    listing = core.head_call("worker_log", {})
    assert any(f.startswith("worker-") for f in listing["files"])

    workers = rt.state("workers")
    tails = []
    for w in workers:
        out = core.head_call("worker_log", {"worker_id": w["worker_id"]})
        tails.append(out["data"])
    assert any("chatty-actor-log-line" in t for t in tails)

    url = rt.dashboard_url()
    hit = False
    for w in workers:
        with urllib.request.urlopen(
                url + f"/api/logs?worker_id={w['worker_id']}",
                timeout=10) as resp:
            if "chatty-actor-log-line" in json.loads(resp.read())["data"]:
                hit = True
    assert hit


def test_chrome_timeline(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def traced():
        return 1

    rt.get(traced.remote())
    deadline = time.time() + 10
    events = []
    while time.time() < deadline and not events:
        events = rt.timeline(format="chrome")
        time.sleep(0.25)
    assert events, "no timeline events"
    ev = events[-1]
    assert ev["ph"] == "X" and ev["ts"] > 0 and ev["dur"] >= 0


def test_cli_status_and_list(rt_cluster):
    rt = rt_cluster
    from ray_tpu.core.worker import CoreWorker

    session_dir = CoreWorker.current().session_dir
    env = dict(os.environ, PYTHONPATH="/root/repo")
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--session-dir", session_dir,
         "status"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "workers:" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--session-dir", session_dir,
         "list", "nodes"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)[0]["node_id"]
