"""Observability plane: metrics, prometheus exposition, state API,
dashboard HTTP endpoint, chrome-trace timeline, CLI.

Mirrors the reference's stats/state/dashboard coverage
(``python/ray/tests/test_metrics_agent.py``, ``test_state_api.py``):
instruments aggregate across processes, exposition parses, and the state
listings reflect live cluster entities.
"""
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from ray_tpu._private import metrics as m


def test_registry_instruments():
    reg = m.MetricsRegistry()
    c = m.Counter("reqs_total", "requests", registry=reg)
    g = m.Gauge("depth", registry=reg)
    h = m.Histogram("lat_seconds", bounds=(0.1, 1.0), registry=reg)
    c.inc()
    c.inc(2, labels={"route": "/a"})
    g.set(7)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = reg.snapshot()
    assert snap["reqs_total"]["kind"] == "counter"
    merged = m.merge_snapshots([snap, snap])  # two identical processes
    text = m.render_prometheus(merged)
    assert "ray_tpu_reqs_total 2" in text          # summed counters
    assert 'route="/a"' in text
    assert "ray_tpu_depth 7" in text               # gauge not summed
    assert 'lat_seconds_bucket{le="0.1"} 2' in text
    assert "lat_seconds_count 6" in text


def test_public_metrics_api_and_timer():
    """ray_tpu.util.metrics re-exports the instruments (reference:
    ``ray.util.metrics``) and Histogram.timer observes wall time."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    reg = m.MetricsRegistry()
    h = Histogram("api_lat_seconds", bounds=(0.001, 10.0), registry=reg)
    with h.timer():
        time.sleep(0.005)
    snap = reg.snapshot()["api_lat_seconds"]
    assert snap["kind"] == "histogram"
    ((_, ent),) = snap["values"]
    assert ent[-1] == 1 and 0.001 < ent[-2] < 5.0  # one obs, sane sum
    assert Counter is not None and Gauge is not None


def test_cluster_metrics_and_state(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def work(x):
        return x * 2

    assert rt.get([work.remote(i) for i in range(10)]) == \
        [i * 2 for i in range(10)]
    # Worker snapshots arrive on the ~1s flush cadence; poll briefly.
    deadline = time.time() + 10
    while time.time() < deadline:
        text = rt.metrics_text()
        if "ray_tpu_task_duration_seconds_bucket" in text:
            break
        time.sleep(0.25)
    else:
        pytest.fail("worker metrics never reached the head")
    assert "ray_tpu_tasks_finished_total" in text
    assert "ray_tpu_workers_alive" in text

    summary = rt.state("summary")
    assert summary["workers"] >= 1
    assert summary["resources_total"]["CPU"] == 8.0
    nodes = rt.state("nodes")
    assert any(n["is_head"] for n in nodes)
    workers = rt.state("workers")
    assert len(workers) >= 1
    # Workers flush task events on a ~1s cadence; poll briefly.
    deadline = time.time() + 10
    while time.time() < deadline:
        if any(t["name"] == "work" for t in rt.state("tasks")):
            break
        time.sleep(0.25)
    else:
        pytest.fail("task events never reached the head")


def test_dashboard_http(rt_cluster):
    rt = rt_cluster
    url = rt.dashboard_url()
    assert url and url.startswith("http://127.0.0.1:")

    with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
        body = resp.read().decode()
    assert "ray_tpu_workers_alive" in body

    with urllib.request.urlopen(url + "/api/state?kind=summary",
                                timeout=10) as resp:
        summary = json.loads(resp.read())
    assert summary["nodes"] >= 1

    with urllib.request.urlopen(url + "/api/timeline", timeout=10) as resp:
        events = json.loads(resp.read())
    assert isinstance(events, list)

    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(url + "/nope", timeout=10)


def test_worker_log_tailing(rt_cluster):
    """Worker stdout is fetchable by worker id — via head RPC and via
    the dashboard /api/logs endpoint (reference:
    ``dashboard/modules/log/`` per-node log serving)."""
    rt = rt_cluster

    @rt.remote
    class Chatty:
        def speak(self):
            print("chatty-actor-log-line", flush=True)
            return os.getpid()

    a = Chatty.remote()
    rt.get(a.speak.remote())
    time.sleep(0.3)  # stdout reaches the redirected file

    from ray_tpu.core.worker import CoreWorker

    core = CoreWorker.current()
    listing = core.head_call("worker_log", {})
    assert any(f.startswith("worker-") for f in listing["files"])

    workers = rt.state("workers")
    tails = []
    for w in workers:
        out = core.head_call("worker_log", {"worker_id": w["worker_id"]})
        tails.append(out["data"])
    assert any("chatty-actor-log-line" in t for t in tails)

    url = rt.dashboard_url()
    hit = False
    for w in workers:
        with urllib.request.urlopen(
                url + f"/api/logs?worker_id={w['worker_id']}",
                timeout=10) as resp:
            if "chatty-actor-log-line" in json.loads(resp.read())["data"]:
                hit = True
    assert hit


def test_chrome_timeline(rt_cluster):
    rt = rt_cluster

    @rt.remote
    def traced():
        return 1

    rt.get(traced.remote())
    deadline = time.time() + 10
    events = []
    while time.time() < deadline and not events:
        events = rt.timeline(format="chrome")
        time.sleep(0.25)
    assert events, "no timeline events"
    ev = events[-1]
    assert ev["ph"] == "X" and ev["ts"] > 0 and ev["dur"] >= 0


# --------------------------------------------------------------- ISSUE 4
def _parse_exposition(text):
    """Minimal exposition parser for round-trip assertions: returns
    {metric_name: [(labels_dict, value)]}. Unescapes label values per
    the spec (the inverse of render_prometheus's escaping)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_labels, _, value = line.rpartition(" ")
        if "{" in name_labels:
            name, _, rest = name_labels.partition("{")
            raw = rest.rstrip("}")
            labels = {}
            i = 0
            while i < len(raw):
                eq = raw.index("=", i)
                key = raw[i:eq]
                assert raw[eq + 1] == '"'
                j = eq + 2
                buf = []
                while raw[j] != '"':
                    if raw[j] == "\\":
                        nxt = raw[j + 1]
                        buf.append({"n": "\n", "\\": "\\",
                                    '"': '"'}[nxt])
                        j += 2
                    else:
                        buf.append(raw[j])
                        j += 1
                labels[key] = "".join(buf)
                i = j + 2  # past closing quote + comma
            out.setdefault(name, []).append((labels, float(value)))
        else:
            out.setdefault(name_labels, []).append(({}, float(value)))
    return out


def test_label_escaping_roundtrip():
    """Backslash, double-quote, and newline in a label value must
    escape to valid exposition text and parse back verbatim."""
    reg = m.MetricsRegistry()
    c = m.Counter("escapes_total", "desc with\nnewline", registry=reg)
    nasty = 'back\\slash "quoted"\nmultiline'
    c.inc(3, labels={"path": nasty})
    text = m.render_prometheus(m.merge_snapshots([reg.snapshot()]))
    # Every physical line must be a single logical sample (the raw
    # newline would have split one).
    for line in text.splitlines():
        if line.startswith("ray_tpu_escapes_total"):
            assert line.endswith(" 3.0")
    parsed = _parse_exposition(text)
    ((labels, value),) = parsed["ray_tpu_escapes_total"]
    assert labels["path"] == nasty
    assert value == 3.0
    # HELP text: the newline must be escaped onto one line.
    help_lines = [l for l in text.splitlines() if l.startswith("# HELP")]
    assert any("desc with\\nnewline" in l for l in help_lines)


def test_histogram_bucket_cumulativity():
    reg = m.MetricsRegistry()
    h = m.Histogram("cumul_seconds", bounds=(0.1, 0.5, 1.0),
                    registry=reg)
    for v in (0.05, 0.05, 0.3, 0.7, 2.0, 5.0):
        h.observe(v)
    text = m.render_prometheus(m.merge_snapshots([reg.snapshot()]))
    parsed = _parse_exposition(text)
    buckets = sorted(parsed["ray_tpu_cumul_seconds_bucket"],
                     key=lambda kv: float("inf")
                     if kv[0]["le"] == "+Inf" else float(kv[0]["le"]))
    counts = [v for _, v in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert counts == [2, 3, 4, 6]
    ((_, total),) = parsed["ray_tpu_cumul_seconds_count"]
    assert counts[-1] == total == 6


def test_merge_snapshots_bounds_conflict():
    """Two processes reporting different bounds for one histogram must
    not be zip-truncated into corrupt counts: they merge as separate
    series under a bounds_conflict note."""
    r1, r2, r3 = (m.MetricsRegistry() for _ in range(3))
    h1 = m.Histogram("conf_seconds", bounds=(0.1, 1.0), registry=r1)
    h2 = m.Histogram("conf_seconds", bounds=(0.5, 2.0, 5.0), registry=r2)
    h3 = m.Histogram("conf_seconds", bounds=(0.1, 1.0), registry=r3)
    h1.observe(0.05)
    h2.observe(3.0)
    h2.observe(0.2)
    h3.observe(0.5)
    merged = m.merge_snapshots([r1.snapshot(), r2.snapshot(),
                                r3.snapshot()])
    ent = merged["conf_seconds"]
    # Matching-bounds snapshots (r1, r3) merged element-wise...
    assert ent["bounds"] == [0.1, 1.0]
    ((key, vals),) = ent["values"].items()
    assert vals[-1] == 2  # h1 + h3 observations
    # ...the conflicting one kept separate with ALL its counts intact.
    (sub,) = ent["bounds_conflict"]
    assert sub["bounds"] == [0.5, 2.0, 5.0]
    ((_, cvals),) = sub["values"].items()
    assert cvals[-1] == 2 and cvals[-2] == 3.2
    # Exposition renders both, disambiguated by a bounds_conflict label.
    text = m.render_prometheus(merged)
    parsed = _parse_exposition(text)
    counts = parsed["ray_tpu_conf_seconds_count"]
    assert sorted(v for _, v in counts) == [2.0, 2.0]
    assert any(l.get("bounds_conflict") == "1" for l, _ in counts)


def test_metric_name_lint():
    """register() lints names: warn by default, raise in strict mode."""
    strict = m.MetricsRegistry(strict=True)
    with pytest.raises(ValueError, match="_total"):
        m.Counter("requests", registry=strict)
    with pytest.raises(ValueError, match="_seconds"):
        m.Histogram("request_latency", registry=strict)
    with pytest.raises(ValueError, match="naming regex"):
        m.Gauge("bad-name", registry=strict)
    # Conforming names register fine in strict mode.
    m.Counter("good_total", registry=strict)
    m.Histogram("req_latency_seconds", registry=strict)
    m.Histogram("batch_size", registry=strict)  # not a duration
    # Default mode: same problems warn instead of raising.
    lax = m.MetricsRegistry(strict=False)
    with pytest.warns(UserWarning, match="_total"):
        m.Counter("requests", registry=lax)


def test_tracing_span_drop_accounting():
    """The span buffer counts what the bounded deque silently evicts
    (satellite: tracing_spans_dropped_total + get_spans metadata)."""
    import collections

    from ray_tpu.util import tracing

    saved_buf = tracing._buffer
    tracing._buffer = collections.deque(maxlen=3)
    tracing.take_dropped()  # reset
    was_enabled = tracing.enabled()
    tracing.enable()
    try:
        for i in range(5):
            with tracing.span(f"s{i}"):
                pass
        assert len(tracing._buffer) == 3
        assert tracing.dropped_total() == 2
        # requeue past capacity also counts its evictions
        tracing.requeue([{"name": f"r{i}"} for i in range(2)])
        assert tracing.dropped_total() == 4
        assert tracing.take_dropped() == 4
        assert tracing.take_dropped() == 0
        # ...and the counter instrument recorded every drop.
        c = m.global_registry().get("tracing_spans_dropped_total")
        assert c is not None and sum(v for _, v in c.collect()) >= 4
    finally:
        tracing._buffer = saved_buf
        if not was_enabled:
            tracing.disable()


def test_serve_latency_histograms_stream(rt_cluster):
    """A streamed request populates the serve TTFT/TPOT/e2e histograms
    (observed caller-side by the router) and serve.status() surfaces a
    per-deployment latency block computed from the buckets."""
    from ray_tpu import serve
    from ray_tpu._private.metrics import serve_metrics

    serve.start(proxy=False)
    try:
        @serve.deployment
        class Tok:
            def __call__(self, n):
                for i in range(n):
                    time.sleep(0.005)
                    yield [i, i]  # a 2-token chunk per arrival

        h = serve.run(Tok.bind(), name="tokapp", route_prefix=None)

        def series_count(hist, dep):
            return sum(v[-1] for k, v in hist.collect()
                       if ("deployment", dep) in k)

        sm = serve_metrics()
        before = series_count(sm["tpot"], "Tok")
        assert list(h.options(stream=True).remote(5)) == \
            [[i, i] for i in range(5)]
        assert series_count(sm["ttft"], "Tok") >= 1
        # 4 post-first arrivals x 2 tokens each
        assert series_count(sm["tpot"], "Tok") - before >= 8
        assert series_count(sm["e2e_latency"], "Tok") >= 1

        # status() latency block: p50/p95/p99 from the head-merged
        # buckets (the driver shares the head's registry in-process).
        deadline = time.time() + 15
        block = None
        while time.time() < deadline:
            st = serve.status()
            block = st["applications"]["tokapp"]["deployments"]["Tok"] \
                .get("latency")
            if block and "ttft" in block and "e2e" in block:
                break
            time.sleep(0.5)
        assert block, f"no latency block in status: {st}"
        assert block["e2e"]["count"] >= 1
        assert block["ttft"]["p50_s"] is not None
        assert block["e2e"]["p99_s"] >= block["e2e"]["p50_s"]
        # The exposition carries the histograms for /metrics scrapers.
        deadline = time.time() + 15
        while time.time() < deadline:
            text = rt_cluster.metrics_text()
            if "ray_tpu_serve_ttft_seconds_bucket" in text and \
                    "ray_tpu_serve_tpot_seconds_bucket" in text:
                break
            time.sleep(0.25)
        assert "ray_tpu_serve_ttft_seconds_bucket" in text
    finally:
        serve.shutdown()


def test_cli_status_and_list(rt_cluster):
    rt = rt_cluster
    from ray_tpu.core.worker import CoreWorker

    session_dir = CoreWorker.current().session_dir
    env = dict(os.environ, PYTHONPATH="/root/repo")
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--session-dir", session_dir,
         "status"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "workers:" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "--session-dir", session_dir,
         "list", "nodes"],
        capture_output=True, text=True, env=env, timeout=60)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)[0]["node_id"]
