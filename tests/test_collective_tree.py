"""Tree/object-store StoreGroup collectives: per-rank payload traffic
scales O(log W), not O(W) (VERDICT r4 #5 — the old symmetric KV gather
was O(world²) cluster-wide). Reference surface: util/collective."""
import numpy as np


def _spawn_group(rt, world, fn_name, payload_kb, name):
    @rt.remote
    class Ranker:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self, fn_name, payload_kb, name):
            import numpy as np

            from ray_tpu.collective import collective as C

            g = C.init_collective_group(self.world, self.rank,
                                        backend="store", group_name=name)
            x = np.full((payload_kb * 128,), float(self.rank + 1),
                        np.float64)  # payload_kb KiB
            if fn_name == "allreduce":
                out = g.allreduce(x)
                expect = sum(range(1, self.world + 1))
                assert np.allclose(out, expect), out[:4]
            elif fn_name == "broadcast":
                out = g.broadcast(x if self.rank == 0 else None,
                                  src_rank=0)
                assert np.allclose(out, 1.0), out[:4]
            return dict(g.stats)

    actors = [Ranker.remote(r, world) for r in range(world)]
    return rt.get([a.run.remote(fn_name, payload_kb, name)
                   for a in actors], timeout=120)


def test_allreduce_per_rank_transfers_logarithmic(rt_cluster):
    """8-rank allreduce of 64 KiB payloads: every rank moves at most
    log2(8)+1 = 4 payloads through the store (the old design moved W=8
    per rank), and the KV carries only tiny ref records."""
    stats = _spawn_group(rt_cluster, 8, "allreduce", 64, "tree_ar8")
    total_puts = sum(s["store_puts"] for s in stats)
    assert total_puts <= 8, stats  # W-1 reduce edges + 1 broadcast
    for s in stats:
        assert s["store_gets"] <= 4, s       # <= log2(W) + 1
        assert s["kv_bytes_in"] < 16 * 1024, s   # refs, not payloads
        assert s["kv_bytes_out"] < 4 * 1024, s


def test_broadcast_src_puts_once(rt_cluster):
    """Broadcast: the source puts ONE object; receivers each pull it
    via the store (multi-source chunked path), no payload in the KV."""
    stats = _spawn_group(rt_cluster, 8, "broadcast", 64, "tree_bc8")
    assert sum(s["store_puts"] for s in stats) == 1, stats
    for i, s in enumerate(stats):
        assert s["store_gets"] == (0 if i == 0 else 1), stats
        assert s["kv_bytes_in"] < 4 * 1024, s


def test_small_payloads_stay_inline(rt_cluster):
    """Sub-threshold payloads skip the object store entirely — the KV
    round-trip is cheaper than put+get for tiny rendezvous values."""
    stats = _spawn_group(rt_cluster, 4, "allreduce", 1, "tree_inl4")  # 1 KiB < 4 KiB
    assert all(s["store_puts"] == 0 and s["store_gets"] == 0
               for s in stats), stats


def test_many_generations_gc_bounded(rt_cluster):
    """Back-to-back ops cross several sync generations; held refs and
    own-slot records stay bounded by GC_LAG."""
    rt = rt_cluster

    @rt.remote
    class Looper:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self, iters):
            import numpy as np

            from ray_tpu.collective import collective as C

            g = C.init_collective_group(self.world, self.rank,
                                        backend="store", group_name="gcgrp")
            for i in range(iters):
                out = g.allreduce(np.full((4096,), 1.0))  # > INLINE_MAX
                assert np.allclose(out, self.world)
            return {"slots_gens": len(g._own_slots),
                    "held_gens": len(g._held)}

    world, iters = 2, 40
    actors = [Looper.remote(r, world) for r in range(world)]
    outs = rt.get([a.run.remote(iters) for a in actors], timeout=180)
    from ray_tpu.collective.collective import StoreGroup

    cap = StoreGroup.GC_LAG + StoreGroup.SYNC_EVERY
    for o in outs:
        assert o["slots_gens"] <= cap, o
        assert o["held_gens"] <= cap, o


def test_colocated_ranks_share_a_process(rt_cluster):
    """The head may pack two gang actors into ONE worker process; each
    rank must then hold its own group object (regression: the registry
    was keyed by name alone and the second rank's init exploded with
    'already exists'). Reference semantics: rank identity belongs to
    the caller, not the process."""
    import pytest

    from ray_tpu.collective import collective as C

    g0 = C.init_collective_group(2, 0, backend="store", group_name="colo")
    g1 = C.init_collective_group(2, 1, backend="store", group_name="colo")
    assert g0 is not g1 and (g0.rank, g1.rank) == (0, 1)
    # re-join is idempotent per (name, rank)
    assert C.init_collective_group(2, 0, backend="store",
                                   group_name="colo") is g0
    # same rank, different world: still rejected
    with pytest.raises(ValueError, match="already exists"):
        C.init_collective_group(8, 0, backend="store", group_name="colo")
    # ambiguous bare lookup names the problem; rank= disambiguates
    with pytest.raises(KeyError, match="pass rank="):
        C.get_group("colo")
    assert C.get_group("colo", rank=1) is g1

    # the two co-located ranks can actually COMMUNICATE (store-backed
    # groups talk through the object plane, not process state); payload
    # > INLINE_MAX so real slots are published
    import threading

    out = {}

    def run(g):
        out[g.rank] = g.allreduce(
            np.full((4096,), float(g.rank + 1)))

    ts = [threading.Thread(target=run, args=(g,)) for g in (g0, g1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert np.allclose(out[0], 3.0) and np.allclose(out[1], 3.0)

    # ONE rank leaving must not wipe the other's published state, and
    # a completed send must stay deliverable after the sender leaves
    g0.send(np.arange(4.0), dst_rank=1)
    C.destroy_collective_group("colo", rank=0)
    assert C.get_group("colo") is g1  # one rank left: bare lookup works
    survivors = g1._core.kv_keys("__coll__/colo/", ns="collective")
    assert survivors, "rank-0 destroy wiped rank-1's keys"
    assert all(g1._is_own_key(k) for k in survivors), survivors
    assert np.allclose(g1.recv(src_rank=0), np.arange(4.0))
    C.destroy_collective_group("colo")  # full destructor wipes the rest
    with pytest.raises(KeyError, match="not initialized"):
        C.get_group("colo")
    assert not g1._core.kv_keys("__coll__/colo/", ns="collective")
