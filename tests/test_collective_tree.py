"""Tree/object-store StoreGroup collectives: per-rank payload traffic
scales O(log W), not O(W) (VERDICT r4 #5 — the old symmetric KV gather
was O(world²) cluster-wide). Reference surface: util/collective."""
import numpy as np


def _spawn_group(rt, world, fn_name, payload_kb, name):
    @rt.remote
    class Ranker:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self, fn_name, payload_kb, name):
            import numpy as np

            from ray_tpu.collective import collective as C

            g = C.init_collective_group(self.world, self.rank,
                                        backend="store", group_name=name)
            x = np.full((payload_kb * 128,), float(self.rank + 1),
                        np.float64)  # payload_kb KiB
            if fn_name == "allreduce":
                out = g.allreduce(x)
                expect = sum(range(1, self.world + 1))
                assert np.allclose(out, expect), out[:4]
            elif fn_name == "broadcast":
                out = g.broadcast(x if self.rank == 0 else None,
                                  src_rank=0)
                assert np.allclose(out, 1.0), out[:4]
            return dict(g.stats)

    actors = [Ranker.remote(r, world) for r in range(world)]
    return rt.get([a.run.remote(fn_name, payload_kb, name)
                   for a in actors], timeout=120)


def test_allreduce_per_rank_transfers_logarithmic(rt_cluster):
    """8-rank allreduce of 64 KiB payloads: every rank moves at most
    log2(8)+1 = 4 payloads through the store (the old design moved W=8
    per rank), and the KV carries only tiny ref records."""
    stats = _spawn_group(rt_cluster, 8, "allreduce", 64, "tree_ar8")
    total_puts = sum(s["store_puts"] for s in stats)
    assert total_puts <= 8, stats  # W-1 reduce edges + 1 broadcast
    for s in stats:
        assert s["store_gets"] <= 4, s       # <= log2(W) + 1
        assert s["kv_bytes_in"] < 16 * 1024, s   # refs, not payloads
        assert s["kv_bytes_out"] < 4 * 1024, s


def test_broadcast_src_puts_once(rt_cluster):
    """Broadcast: the source puts ONE object; receivers each pull it
    via the store (multi-source chunked path), no payload in the KV."""
    stats = _spawn_group(rt_cluster, 8, "broadcast", 64, "tree_bc8")
    assert sum(s["store_puts"] for s in stats) == 1, stats
    for i, s in enumerate(stats):
        assert s["store_gets"] == (0 if i == 0 else 1), stats
        assert s["kv_bytes_in"] < 4 * 1024, s


def test_small_payloads_stay_inline(rt_cluster):
    """Sub-threshold payloads skip the object store entirely — the KV
    round-trip is cheaper than put+get for tiny rendezvous values."""
    stats = _spawn_group(rt_cluster, 4, "allreduce", 1, "tree_inl4")  # 1 KiB < 4 KiB
    assert all(s["store_puts"] == 0 and s["store_gets"] == 0
               for s in stats), stats


def test_many_generations_gc_bounded(rt_cluster):
    """Back-to-back ops cross several sync generations; held refs and
    own-slot records stay bounded by GC_LAG."""
    rt = rt_cluster

    @rt.remote
    class Looper:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self, iters):
            import numpy as np

            from ray_tpu.collective import collective as C

            g = C.init_collective_group(self.world, self.rank,
                                        backend="store", group_name="gcgrp")
            for i in range(iters):
                out = g.allreduce(np.full((4096,), 1.0))  # > INLINE_MAX
                assert np.allclose(out, self.world)
            return {"slots_gens": len(g._own_slots),
                    "held_gens": len(g._held)}

    world, iters = 2, 40
    actors = [Looper.remote(r, world) for r in range(world)]
    outs = rt.get([a.run.remote(iters) for a in actors], timeout=180)
    from ray_tpu.collective.collective import StoreGroup

    cap = StoreGroup.GC_LAG + StoreGroup.SYNC_EVERY
    for o in outs:
        assert o["slots_gens"] <= cap, o
        assert o["held_gens"] <= cap, o
