"""Wheelhouse pip runtime env (reference:
``python/ray/_private/runtime_env/pip.py`` + ``uri_cache.py``): a
wheel-only package ships to a dedicated worker through a local
wheelhouse install, cached per env hash with LRU eviction."""
import base64
import hashlib
import os
import time
import zipfile

import pytest

from ray_tpu._private import runtime_env as renv


def build_wheel(wheelhouse: str, name: str = "tinypkg",
                version: str = "0.1.0", value: int = 42) -> str:
    """Hand-craft a minimal valid wheel (a wheel IS a zip + dist-info)."""
    os.makedirs(wheelhouse, exist_ok=True)
    whl = os.path.join(wheelhouse,
                       f"{name}-{version}-py3-none-any.whl")
    files = {
        f"{name}/__init__.py": f"VALUE = {value}\n".encode(),
        f"{name}-{version}.dist-info/METADATA":
            f"Metadata-Version: 2.1\nName: {name}\n"
            f"Version: {version}\n".encode(),
        f"{name}-{version}.dist-info/WHEEL":
            b"Wheel-Version: 1.0\nGenerator: test\n"
            b"Root-Is-Purelib: true\nTag: py3-none-any\n",
    }
    record = []
    with zipfile.ZipFile(whl, "w") as z:
        for fn, data in files.items():
            z.writestr(fn, data)
            digest = base64.urlsafe_b64encode(
                hashlib.sha256(data).digest()).rstrip(b"=").decode()
            record.append(f"{fn},sha256={digest},{len(data)}")
        record.append(f"{name}-{version}.dist-info/RECORD,,")
        z.writestr(f"{name}-{version}.dist-info/RECORD",
                   "\n".join(record) + "\n")
    return whl


def test_ensure_pip_env_installs_and_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    wh = str(tmp_path / "wheelhouse")
    build_wheel(wh)
    env_dir = renv.ensure_pip_env(["tinypkg"], wh)
    assert os.path.isdir(os.path.join(env_dir, "tinypkg"))
    # cache hit: pip must NOT run again
    import subprocess as sp

    def boom(*a, **k):
        raise AssertionError("pip ran on a cache hit")

    monkeypatch.setattr(sp, "run", boom)
    assert renv.ensure_pip_env(["tinypkg"], wh) == env_dir


def test_pip_env_eviction(tmp_path, monkeypatch):
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    root = renv._pip_cache_root()
    os.makedirs(root)
    for i in range(5):
        d = os.path.join(root, f"env{i}")
        os.makedirs(d)
        open(d + ".ok", "w").close()
        open(d + ".lock", "w").close()
        t = time.time() - 1000 + i
        os.utime(d + ".ok", (t, t))
    renv._evict_pip_envs(cap=2)
    left = sorted(f for f in os.listdir(root) if f.endswith(".ok"))
    assert left == ["env3.ok", "env4.ok"]


def test_missing_package_clear_error(tmp_path, monkeypatch):
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    wh = str(tmp_path / "wheelhouse")
    os.makedirs(wh)
    with pytest.raises(RuntimeError, match="pip install from wheelhouse"):
        renv.ensure_pip_env(["no-such-package-xyz"], wh)


def test_worker_imports_wheel_only_package(tmp_path, monkeypatch):
    """The e2e gate: a package existing ONLY as a wheel in a local
    wheelhouse imports inside a dedicated worker; a second task in the
    same env reuses the cached install."""
    import ray_tpu as rt

    wh = str(tmp_path / "wheelhouse")
    build_wheel(wh, value=1234)
    env = {"pip": {"packages": ["tinypkg"], "wheelhouse": wh}}

    if rt.is_initialized():
        rt.shutdown()  # a session fixture may have left a cluster up
    rt.init(num_cpus=2, num_tpus=0)
    try:
        @rt.remote(runtime_env=env)
        def use_pkg():
            import tinypkg

            return tinypkg.VALUE, tinypkg.__file__

        value, path = rt.get(use_pkg.remote(), timeout=180)
        assert value == 1234
        assert "pip_envs" in path
        # driver process must NOT see it (isolation); find_spec, not
        # import, so module-cache state from other tests can't matter
        import importlib.util

        assert importlib.util.find_spec("tinypkg") is None
        # second use: cached (marker mtime identical modulo touch is
        # hard to observe cross-process; instead assert same env dir)
        value2, path2 = rt.get(use_pkg.remote(), timeout=120)
        assert (value2, os.path.dirname(path2)) == (
            value, os.path.dirname(path))
    finally:
        rt.shutdown()
