"""DreamerV3: symlog/twohot invariants, world-model learning on a
predictable env, imagination-driven policy improvement, e2e Algorithm.

Mirrors the reference's DreamerV3 coverage
(``rllib/algorithms/dreamerv3/tests/test_dreamerv3.py`` — compile/run of
the training loop; learning gates live in tuned examples)."""
import numpy as np
import pytest


def test_symlog_twohot_roundtrip():
    from ray_tpu.rllib import dreamerv3 as d

    x = np.array([-55.0, -1.0, 0.0, 0.3, 7.0, 400.0], np.float32)
    np.testing.assert_allclose(d.symexp(d.symlog(x)), x, rtol=1e-5,
                               atol=1e-5)
    # twohot is an exact two-bin interpolation: decoding recovers the
    # value for anything inside the support.
    y = np.array([-10.0, -0.5, 0.0, 1.7, 12.0], np.float32)
    enc = d.twohot(y)
    assert enc.shape == (5, d.NUM_BINS)
    np.testing.assert_allclose(enc.sum(-1), 1.0, atol=1e-6)
    dec = enc @ d._bins()
    np.testing.assert_allclose(dec, y, rtol=1e-4, atol=1e-4)


class _CounterEnv:
    """Deterministic chain: obs counts up, reward = +1 on action 1 at
    even steps else 0 — world model must become able to predict both."""

    class _Space:
        def __init__(self, n=None, shape=None):
            self.n = n
            self.shape = shape

    def __init__(self):
        self.observation_space = self._Space(shape=(3,))
        self.action_space = self._Space(n=2)
        self.t = 0

    def reset(self, seed=None):
        self.t = 0
        return self._obs(), {}

    def _obs(self):
        return np.array([self.t / 10.0, (self.t % 2), 1.0], np.float32)

    def step(self, action):
        rew = 1.0 if (self.t % 2 == 0 and action == 1) else 0.0
        self.t += 1
        done = self.t >= 20
        return self._obs(), rew, done, False, {}

    def close(self):
        pass


def _tiny_config():
    from ray_tpu.rllib import DreamerV3Config

    cfg = DreamerV3Config().environment(env_creator=_CounterEnv)
    cfg.deter_dim = 32
    cfg.units = 32
    cfg.stoch_dims = 4
    cfg.stoch_classes = 4
    cfg.horizon = 5
    cfg.seq_len = 8
    cfg.batch_seqs = 4
    cfg.lr = 3e-4
    cfg.rollout_fragment_length = 32
    cfg.num_steps_before_learning = 32
    cfg.updates_per_iteration = 4
    return cfg


def test_world_model_learns_predictable_env():
    """WM losses (recon + reward) drop sharply on a deterministic env."""
    import jax

    from ray_tpu.rllib import dreamerv3 as d

    cfg = _tiny_config()
    spec = cfg.module_spec()
    learner = d.DreamerV3Learner(spec, cfg, seed=0)

    # Scripted experience from the counter env, in the replay's
    # ARRIVAL-row convention: each row is the observation arrived at,
    # tagged with the action/reward that produced it; episode starts
    # are explicit is_first rows and terminal arrivals are real rows.
    env, rng = _CounterEnv(), np.random.default_rng(0)
    seq = {"obs": [], "a_prev": [], "rewards": [], "terms": [],
           "is_first": []}

    def add(obs, a_prev, r, term, first):
        seq["obs"].append(obs)
        seq["a_prev"].append(a_prev)
        seq["rewards"].append(r)
        seq["terms"].append(float(term))
        seq["is_first"].append(float(first))

    obs, _ = env.reset()
    need_start = True
    for _ in range(512):
        if need_start:
            add(obs, 0, 0.0, 0.0, 1.0)
            need_start = False
        a = int(rng.integers(2))
        nxt, r, done, _, _ = env.step(a)
        add(nxt, a, r, done, 0.0)
        if done:
            obs, _ = env.reset()
            need_start = True
        else:
            obs = nxt
    n = (len(seq["obs"]) // cfg.seq_len) * cfg.seq_len
    batchify = lambda k: np.asarray(  # noqa: E731
        seq[k][:n], np.float32).reshape(-1, cfg.seq_len)

    full = {
        "obs": np.asarray(seq["obs"][:n], np.float32).reshape(
            -1, cfg.seq_len, 3),
        "a_prev": batchify("a_prev"),
        "rewards": batchify("rewards"),
        # counter env only terminates (never truncates): terms == dones
        "terms": batchify("terms"),
        "is_first": batchify("is_first"),
    }

    key = jax.random.PRNGKey(0)
    _, m0 = learner.wm_only(learner.params, key, full)
    for _ in range(150):
        learner.update(full)
    _, m1 = learner.wm_only(learner.params, key, full)
    assert float(m1["wm/obs"]) < 0.5 * float(m0["wm/obs"]), (m0, m1)
    assert float(m1["wm/reward"]) < 0.8 * float(m0["wm/reward"]), (m0, m1)


def test_dreamer_e2e_and_checkpoint(tmp_path):
    """Full Algorithm loop: sample → replay → update → sync; metrics are
    finite and state round-trips through save/restore."""
    from ray_tpu.rllib import dreamerv3 as d

    algo = _tiny_config().build()
    try:
        for _ in range(3):
            m = algo.train()
        assert m["num_updates"] > 0
        assert np.isfinite(m["loss"])
        assert np.isfinite(m["ac/entropy"])
        assert m["replay_fragments"] >= 1

        path = algo.save_to_path(str(tmp_path / "ckpt"))
        w0 = algo.learner_group.get_state()["params"]["actor"][0]["w"].copy()
        algo.train()
        algo.restore_from_path(path)
        w1 = algo.learner_group.get_state()["params"]["actor"][0]["w"]
        np.testing.assert_array_equal(w0, w1)
    finally:
        algo.stop()


def test_imagination_trains_the_actor():
    """The imagination pathway delivers gradient to the actor: over a
    dozen iterations on the deterministic counter env the policy
    entropy falls from ln(2) as the world model's reward predictions
    sharpen, and returns do not degrade below random (~5)."""
    cfg = _tiny_config()
    cfg.updates_per_iteration = 16
    algo = cfg.build()
    try:
        ents, rets = [], []
        for _ in range(16):
            m = algo.train()
            ents.append(m["ac/entropy"])
            if m.get("episode_return_mean") is not None:
                rets.append(m["episode_return_mean"])
        assert ents[-1] < 0.685, ents  # moved off ln(2) = uniform
        assert ents[-1] < ents[0], ents
        assert rets[-1] > 5.0, rets
    finally:
        algo.stop()


class _Drive1D:
    """Continuous control (Pendulum-class, XS-budget): steer a point
    toward a per-episode target with dense negative-distance reward."""

    class _Box:
        def __init__(self, shape):
            self.shape = shape
            self.low = -np.ones(shape, np.float32)
            self.high = np.ones(shape, np.float32)

    def __init__(self):
        self.observation_space = self._Box((2,))
        self.action_space = self._Box((1,))
        self._rng = np.random.default_rng(0)
        self.pos = self.target = 0.0
        self.t = 0

    def _obs(self):
        return np.array([self.pos, self.target], np.float32)

    def reset(self, seed=None):
        self.pos = 0.0
        self.target = float(self._rng.uniform(-0.8, 0.8))
        self.t = 0
        return self._obs(), {}

    def step(self, action):
        a = float(np.clip(np.asarray(action).reshape(-1)[0], -1, 1))
        self.pos = float(np.clip(self.pos + 0.3 * a, -1.5, 1.5))
        self.t += 1
        rew = -abs(self.pos - self.target)
        return self._obs(), rew, self.t >= 10, False, {}

    def close(self):
        pass


def test_continuous_public_config_rejects_box_actions():
    """Continuous DreamerV3 is GATED out of the public surface: round-5
    probes (NOTES_r05) show XS-budget continuous control failing its
    improvement-over-random gate even after the entropy-gradient fix
    and the switch to paper-faithful REINFORCE. The public config
    refuses loudly instead of shipping a known-diverging mode; the
    experimental flag opts in."""
    import pytest

    from ray_tpu.rllib import DreamerV3Config

    cfg = DreamerV3Config().environment(env_creator=_Drive1D)
    cfg.deter_dim = 32
    cfg.units = 32
    with pytest.raises(ValueError, match="EXPERIMENTAL"):
        cfg.build()


def test_continuous_control_mechanism():
    """Continuous-action DreamerV3 end-to-end (EXPERIMENTAL opt-in):
    the arrival-aligned stream, tanh-gaussian actor with the paper's
    2σ(raw/2)+0.1 std parameterization, REINFORCE + pathwise entropy,
    and checkpointing all work — actions stay in bounds and the update
    is finite. The LEARNING gate is the public-config rejection above:
    this mode ships as experimental precisely because it has not
    passed one (probe record: NOTES_r05)."""
    from ray_tpu.rllib import DreamerV3Config

    cfg = DreamerV3Config().environment(env_creator=_Drive1D)
    cfg.experimental_continuous = True
    cfg.deter_dim = 32
    cfg.units = 32
    cfg.stoch_dims = 4
    cfg.stoch_classes = 4
    cfg.horizon = 5
    cfg.seq_len = 8
    cfg.batch_seqs = 4
    cfg.rollout_fragment_length = 32
    cfg.num_steps_before_learning = 32
    cfg.updates_per_iteration = 4
    algo = cfg.build()
    try:
        for _ in range(3):
            m = algo.train()
        assert m["num_updates"] > 0
        assert np.isfinite(m["loss"])
        assert np.isfinite(m["ac/entropy"])
        # acting path: bounded continuous actions from the module
        mod = algo.env_runner_group.local.module
        rng = np.random.default_rng(0)
        obs = np.zeros((2, 2), np.float32)
        acts, logp, values = mod.forward_exploration(obs, rng)
        assert acts.shape == (2, 1)
        assert np.all(acts >= -1.0) and np.all(acts <= 1.0)
        assert np.isfinite(logp).all() and np.isfinite(values).all()
        det = mod.forward_inference(obs)
        assert np.all(det >= -1.0) and np.all(det <= 1.0)
    finally:
        algo.stop()


def test_recurrent_module_state_resets():
    """The acting module carries per-slot RSSM state and zeroes it on
    episode reset (the env-runner hook)."""
    from ray_tpu.rllib import dreamerv3 as d

    cfg = _tiny_config()
    spec = cfg.module_spec()
    mod = d.DreamerV3Module(spec, seed=0, cfg=cfg)
    rng = np.random.default_rng(0)
    obs = np.ones((2, 3), np.float32)
    mod.forward_exploration(obs, rng)
    assert 0 in mod._state and 1 in mod._state
    h_before = mod._state[0][0].copy()
    mod.forward_exploration(obs, rng)
    assert not np.allclose(mod._state[0][0], h_before)  # state evolved
    mod.on_episode_reset(0)
    assert 0 not in mod._state and 1 in mod._state


class _TargetEnv:
    """Continuous control: reward = 1 - (a - 0.6)^2; best policy pushes
    its action to the fixed target regardless of state."""

    class _Box:
        def __init__(self, shape, low, high):
            self.shape = shape
            self.low = np.full(shape, low, np.float32)
            self.high = np.full(shape, high, np.float32)

    def __init__(self):
        self.observation_space = self._Box((2,), -1.0, 1.0)
        self.action_space = self._Box((1,), -1.0, 1.0)
        self.t = 0

    def reset(self, seed=None):
        self.t = 0
        return np.array([0.1, -0.1], np.float32), {}

    def step(self, action):
        a = float(np.asarray(action).reshape(-1)[0])
        rew = 1.0 - (a - 0.6) ** 2
        self.t += 1
        done = self.t >= 16
        return np.array([0.1, -0.1], np.float32), rew, done, False, {}

    def close(self):
        pass


def test_dreamer_continuous_actions_e2e():
    """Continuous DreamerV3 (experimental): tanh-gaussian actor with
    pathwise (dynamics-backprop) gradients runs end-to-end — finite
    losses, world model learns, weights train, actions bounded. A
    learning-rate gate like the discrete one is deferred: tiny-budget
    continuous control is dominated by tanh-saturation/model-
    exploitation dynamics that need the full-size model (NOTES_r03)."""
    from ray_tpu.rllib import DreamerV3Config
    from ray_tpu.rllib import dreamerv3 as d

    cfg = DreamerV3Config().environment(env_creator=_TargetEnv)
    cfg.experimental_continuous = True
    cfg.deter_dim = 32
    cfg.units = 32
    cfg.stoch_dims = 4
    cfg.stoch_classes = 4
    cfg.horizon = 5
    cfg.seq_len = 8
    cfg.batch_seqs = 4
    cfg.lr = 1e-3
    cfg.rollout_fragment_length = 32
    cfg.num_steps_before_learning = 32
    cfg.updates_per_iteration = 8
    algo = cfg.build()
    try:
        w0 = algo.learner_group.get_weights()["actor"][0]["w"].copy()
        m0 = None
        for _ in range(4):
            m = algo.train()
            m0 = m0 or m
        assert np.isfinite(m["loss"]) and np.isfinite(m["ac/entropy"])
        assert float(m["wm/obs"]) < float(m0["wm/obs"]), (m0, m)
        w1 = algo.learner_group.get_weights()["actor"][0]["w"]
        assert not np.allclose(w0, w1)  # actor receives gradient

        probe = d.DreamerV3Module(algo.module_spec, seed=0, cfg=cfg)
        probe.set_weights(algo.learner_group.get_weights())
        obs = np.array([[0.1, -0.1]], np.float32)
        rngp = np.random.default_rng(0)
        env_a, logp, vals = probe.forward_exploration(obs, rngp)
        assert env_a.shape == (1, 1) and np.all(np.abs(env_a) <= 1.0)
        assert np.isfinite(logp).all() and np.isfinite(vals).all()
        mode = probe.forward_inference(obs)
        assert np.all(np.abs(mode) <= 1.0)
    finally:
        algo.stop()
