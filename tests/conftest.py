"""Test fixtures: virtual 8-device CPU mesh for jax + mini-cluster fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): real mini-clusters
in-process per fixture, the same way ``ray_start_regular`` works
(reference: ``python/ray/tests/conftest.py:419``).
"""
import os

# Must run before jax backends initialize anywhere in the test process.
# (Handles vendor PJRT plugins force-registered by sitecustomize too.)
from ray_tpu.testing import force_host_devices  # noqa: E402

force_host_devices(8)
os.environ.setdefault("RT_HEALTH_CHECK_PERIOD_S", "0.2")
# The graft-entry dryrun's 1b pp×fsdp pass executes a real 1.2B-param
# train step — minutes of single-core work the DRIVER exercises at
# round end; inside the suite it would blow the per-test watchdog.
# The nano passes (all five parallelism combos) still run here.
os.environ.setdefault("RT_DRYRUN_SKIP_1B", "1")


# Stale-segment hygiene lives in the runtime, not here: synthetic test
# domains are swept by Cluster.shutdown/remove_node and NodeService.stop
# (each knows its own domain, so live clusters are never touched; a
# blanket mtime-based sweep would be unsafe — mmap writes don't update
# st_mtime).

import faulthandler  # noqa: E402
import sys  # noqa: E402

import pytest  # noqa: E402

# ---- runtime sanitizer (tools/rtsan, ISSUE 13) -------------------------
# RT_SAN=1  -> sanitize EVERY test (and worker processes, which read the
#              same env in worker_main);
# unset     -> patch dormant, enforce only inside the opt-in modules
#              below (the highest-concurrency paths, sanitized on every
#              tier-1 run at ~one flag check of overhead elsewhere);
# RT_SAN=0  -> fully off: no patching at all (zero overhead).
_RT_SAN_MODE = os.environ.get("RT_SAN", "")
_RTSAN = None
if _RT_SAN_MODE != "0":
    _repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo_root not in sys.path:
        sys.path.insert(0, _repo_root)
    import tempfile  # noqa: E402

    import tools.rtsan as _rtsan_mod  # noqa: E402

    _RTSAN = _rtsan_mod
    if _RT_SAN_MODE == "1":
        if not os.environ.get("RT_SAN_DIR"):
            # Worker processes drop their run artifacts here
            # (best-effort); the session gate merges them.
            os.environ["RT_SAN_DIR"] = tempfile.mkdtemp(prefix="rtsan-")
        else:
            # A caller-supplied dir may hold a PREVIOUS run's artifacts;
            # merging those would fail a now-clean suite with phantom
            # findings, so this run starts from an empty dir.
            import glob as _glob

            for _p in _glob.glob(
                    os.path.join(os.environ["RT_SAN_DIR"], "*.json")):
                try:
                    os.unlink(_p)
                except OSError:
                    pass
    _RTSAN.enable(active=(_RT_SAN_MODE == "1"))

#: Modules whose tests always run with enforcement on (and a per-test
#: leaked-thread watch over engine/drafter/pipeline start sites).
_RTSAN_OPT_IN = {
    "test_serve_engine", "test_serve_engine_paged",
    "test_serve_engine_spec", "test_serve_chaos", "test_data_llm",
    "test_rtsan",
}


@pytest.fixture(autouse=True)
def _rtsan_window(request):
    if _RTSAN is None:
        yield
        return
    name = getattr(getattr(request, "module", None), "__name__", "")
    if _RT_SAN_MODE == "1" or name.rpartition(".")[-1] in _RTSAN_OPT_IN:
        # thread_watch exits (and flags leaked drivers) while the
        # activation window is still open.
        with _RTSAN.activated(), _RTSAN.thread_watch():
            yield
    else:
        yield


def pytest_sessionfinish(session, exitstatus):
    """The rtsan --check-style gate: any NEW runtime finding (not
    inline-suppressed, not in the EMPTY-by-policy baseline) fails the
    suite, exactly like a new rtlint finding does."""
    if _RTSAN is None or not _RTSAN.is_enabled():
        return
    import glob
    import json

    if _RT_SAN_MODE == "1":
        # Worker artifacts are written by each worker's atexit hook —
        # which only runs once the worker EXITS. The reused rt_cluster
        # deliberately outlives the tests, so flush it now (idempotent;
        # the session atexit teardown becomes a no-op) and give the
        # dying workers a beat to dump before the merge below. Workers
        # killed uncleanly (SIGKILL chaos) still lose theirs — that
        # path is covered by the in-test engine stats sanitizer block.
        try:
            import ray_tpu as _rt

            if _rt.is_initialized():
                _rt.shutdown()
                import time as _time

                _time.sleep(0.5)
        except Exception:  # noqa: BLE001 - gate must never wedge exit
            pass

    extra = []
    d = os.environ.get("RT_SAN_DIR")
    if d and os.path.isdir(d):
        for p in sorted(glob.glob(os.path.join(d, "*.json"))):
            try:
                with open(p) as f:
                    extra.extend(json.load(f).get("findings", []))
            except Exception:  # noqa: BLE001 - torn worker artifact
                pass
    verdict = _RTSAN.gate(extra=extra)
    art = os.path.join(d, f"rtsan-{os.getpid()}.json") if d \
        else f"/tmp/rtsan-{os.getpid()}.json"
    try:
        _RTSAN.dump(art)
    except Exception:  # noqa: BLE001 - report-only path
        art = None
    if verdict["new"]:
        print("\nrtsan: NEW runtime findings — the gate fails the "
              "suite; fix them (preferred) or suppress inline with "
              "'# rtsan: disable=RSxxx <why>':")
        for f in verdict["new"]:
            print("  " + f.render().splitlines()[0])
        if art:
            print(f"rtsan: full report: "
                  f"python -m tools.rtsan --report {art}")
        session.exitstatus = 1


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'`; long chaos/soak variants opt out.
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 suite")

# Hang watchdog: any single test running >120s dumps every thread's stack
# AND every asyncio task's coroutine stack (the part thread dumps can't see)
# to /tmp/rt_stacks_<pid>.txt (pytest's fd capture would swallow stderr).
_stack_dump_file = open(f"/tmp/rt_stacks_{os.getpid()}.txt", "w")


def _dump_asyncio_tasks():
    import asyncio
    import threading as _threading

    f = _stack_dump_file

    loops = []
    try:
        from ray_tpu.core.worker import CoreWorker

        core = CoreWorker._current
        if core is not None and core._loop is not None:
            loops.append(("core", core._loop))
    except Exception:
        pass
    try:
        from ray_tpu import api as _api

        ht = _api._global_state.get("head_thread")
        if ht is not None and ht._loop is not None:
            loops.append(("head", ht._loop))
    except Exception:
        pass

    for name, loop in loops:
        done = _threading.Event()

        def dump(name=name, loop=loop, done=done):
            try:
                print(f"--- asyncio tasks: {name} loop ---", file=f)
                for t in asyncio.all_tasks(loop):
                    print(repr(t), file=f)
                    t.print_stack(file=f)
            finally:
                f.flush()
                done.set()

        try:
            loop.call_soon_threadsafe(dump)
            done.wait(5)
        except Exception:
            pass
    f.flush()


class TestHungError(Exception):
    """Raised IN the hung test by the watchdog — a hang becomes a FAILURE
    with stacks on disk, never a silent multi-hour stall (round-4
    post-mortem: one lost RPC reply hung the cold suite for 55 min)."""


_WATCHDOG_S = float(os.environ.get("RT_TEST_WATCHDOG_S", "300"))


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    import signal
    import threading as _threading

    done = _threading.Event()
    # Serializes "watchdog fires" against "teardown begins": teardown
    # sets done as its first statement and then passes through this
    # gate before restoring the handler; the watchdog re-checks done
    # under the gate right before pthread_kill, and the signal handler
    # itself re-checks done at delivery. Together these close both
    # SIGALRM races (ADVICE.md low): a test finishing at the deadline
    # can't be failed post-hoc, and a stack dump outlasting the
    # finally's join can't fire into a restored (default) handler and
    # kill pytest.
    kill_gate = _threading.Lock()

    def watch():
        if not done.wait(_WATCHDOG_S):
            print(f"=== WATCHDOG: {item.nodeid} hung ===",
                  file=_stack_dump_file)
            faulthandler.dump_traceback(file=_stack_dump_file,
                                        all_threads=True)
            _dump_asyncio_tasks()
            # Fail the test rather than hang the suite. The signal lands
            # in the MAIN thread (test body); loops on worker threads
            # keep running so teardown fixtures can still clean up.
            import signal as _signal

            with kill_gate:
                # Teardown may have begun while the (slow) stack dumps
                # ran: once done is set the test finished — firing now
                # would fail it after the fact (or, after the handler
                # restore, terminate the whole process).
                if done.is_set():
                    return
                try:
                    _signal.pthread_kill(_threading.main_thread().ident,
                                         _signal.SIGALRM)
                except Exception:
                    pass

    def _raise(signum, frame):
        # The handler runs on the main thread, possibly only once it
        # re-enters the interpreter INSIDE the finally below — after the
        # test body already returned. done is the test-completion fact,
        # so a late-delivered signal becomes a no-op instead of failing
        # a finished test from its own teardown.
        if done.is_set():
            return
        raise TestHungError(
            f"{item.nodeid} exceeded {_WATCHDOG_S}s watchdog; stacks in "
            f"/tmp/rt_stacks_{os.getpid()}.txt")

    prev = signal.signal(signal.SIGALRM, _raise)
    t = _threading.Thread(target=watch, daemon=True)
    t.start()
    try:
        return (yield)
    finally:
        # done FIRST (single atomic call): both the watchdog's gate
        # check and the signal handler consult it, so a kill decided or
        # delivered from here on is a no-op.
        done.set()
        with kill_gate:
            # Barrier only: if the watchdog is mid-decision, wait it
            # out before restoring the handler.
            pass
        # The join is best-effort (a slow dump may outlast it); the
        # done/gate pair above keeps a late watchdog from firing either
        # way, so restoring the handler here is safe even on timeout.
        t.join(timeout=10)
        try:
            signal.signal(signal.SIGALRM, prev)
        except Exception:
            pass


@pytest.fixture
def rt_cluster():
    """A running 8-CPU cluster, reused across tests (re-inits if torn down)."""
    import ray_tpu as rt

    rt.init(num_cpus=8, num_tpus=0, ignore_reinit_error=True)
    yield rt
    # Leave running for reuse; session-level atexit handles final teardown.


@pytest.fixture
def rt_fresh():
    """A fresh cluster per test (for failure-injection tests)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=8, num_tpus=0)
    yield rt
    rt.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """Ensure jax sees 8 virtual CPU devices."""
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {devs}"
    return devs
