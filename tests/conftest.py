"""Test fixtures: virtual 8-device CPU mesh for jax + mini-cluster fixtures.

Mirrors the reference's test strategy (SURVEY.md §4): real mini-clusters
in-process per fixture, the same way ``ray_start_regular`` works
(reference: ``python/ray/tests/conftest.py:419``).
"""
import os

# Must run before jax backends initialize anywhere in the test process.
# (Handles vendor PJRT plugins force-registered by sitecustomize too.)
from ray_tpu.testing import force_host_devices  # noqa: E402

force_host_devices(8)
os.environ.setdefault("RT_HEALTH_CHECK_PERIOD_S", "0.2")

import pytest  # noqa: E402


@pytest.fixture
def rt_cluster():
    """A running 8-CPU cluster, reused across tests (re-inits if torn down)."""
    import ray_tpu as rt

    rt.init(num_cpus=8, num_tpus=0, ignore_reinit_error=True)
    yield rt
    # Leave running for reuse; session-level atexit handles final teardown.


@pytest.fixture
def rt_fresh():
    """A fresh cluster per test (for failure-injection tests)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=8, num_tpus=0)
    yield rt
    rt.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """Ensure jax sees 8 virtual CPU devices."""
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual cpu devices, got {devs}"
    return devs
