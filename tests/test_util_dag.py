"""Util shims (ActorPool / Queue / mp Pool), mutable channels, compiled DAG.

Mirrors the reference's coverage (``python/ray/tests/test_actor_pool.py``,
``test_queue.py``, ``util/multiprocessing`` tests,
``test_channel.py`` / accelerated-DAG tests).
"""
import threading
import time

import pytest


def test_actor_pool(rt_cluster):
    rt = rt_cluster
    from ray_tpu.util.actor_pool import ActorPool

    @rt.remote
    class Doubler:
        def work(self, x):
            return x * 2

    actors = [Doubler.remote() for _ in range(2)]
    pool = ActorPool(actors)
    out = list(pool.map(lambda a, v: a.work.remote(v), range(8)))
    assert out == [v * 2 for v in range(8)]
    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v),
                                    range(8)))
    assert out == sorted(v * 2 for v in range(8))
    for a in actors:
        rt.kill(a)


def test_queue_blocking(rt_cluster):
    rt = rt_cluster
    from ray_tpu.util.queue import Empty, Queue

    q = Queue(maxsize=4)
    try:
        for i in range(4):
            q.put(i)
        assert q.qsize() == 4 and q.full()
        assert [q.get() for _ in range(4)] == [0, 1, 2, 3]
        with pytest.raises(Empty):
            q.get_nowait()

        # cross-task use: the queue handle pickles into a remote task
        @rt.remote
        def producer(queue, n):
            for i in range(n):
                queue.put(i * 10)
            return True

        producer.remote(q, 3)
        assert [q.get(timeout=30) for _ in range(3)] == [0, 10, 20]
    finally:
        q.shutdown()


def test_multiprocessing_pool(rt_cluster):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=4) as pool:
        assert pool.map(lambda x: x * x, range(10)) == \
            [x * x for x in range(10)]
        assert pool.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
        assert sorted(pool.imap_unordered(lambda x: -x, range(5))) == \
            [-4, -3, -2, -1, 0]
        r = pool.apply_async(lambda: 99)
        assert r.get(timeout=30) == 99


def test_channel_write_read(rt_cluster):
    from ray_tpu.experimental.channel import Channel, ChannelClosed

    ch = Channel(capacity_bytes=1 << 16, num_readers=1)
    try:
        results = []

        def reader():
            for _ in range(3):
                results.append(ch.read(0, timeout=10))

        t = threading.Thread(target=reader)
        t.start()
        for v in ("a", {"b": 1}, [1, 2, 3]):
            ch.write(v, timeout=10)
        t.join(timeout=15)
        assert results == ["a", {"b": 1}, [1, 2, 3]]

        ch.close()
        with pytest.raises(ChannelClosed):
            ch.read(0, timeout=5)
    finally:
        ch.destroy()


def test_channel_backpressure(rt_cluster):
    from ray_tpu.experimental.channel import Channel

    ch = Channel(capacity_bytes=1 << 12, num_readers=1)
    try:
        ch.write(1)
        with pytest.raises(TimeoutError):
            ch.write(2, timeout=0.3)  # reader never acked slot 1
        assert ch.read(0) == 1
        ch.write(2)  # now the slot is free
        assert ch.read(0) == 2
    finally:
        ch.destroy()


def test_compiled_dag_pipeline(rt_cluster):
    rt = rt_cluster
    from ray_tpu.dag import InputNode

    @rt.remote
    class Stage:
        def __init__(self, add):
            self.add = add

        def step(self, x):
            return x + self.add

    a = Stage.remote(1)
    b = Stage.remote(10)
    with InputNode() as inp:
        dag = b.step.bind(a.step.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5) == 16   # (5+1)+10
        assert compiled.execute(0) == 11
        # steady state: repeated executes over the same channels
        t0 = time.perf_counter()
        n = 200
        for i in range(n):
            assert compiled.execute(i) == i + 11
        per_call_ms = (time.perf_counter() - t0) / n * 1e3
        assert per_call_ms < 50, f"{per_call_ms:.2f} ms/call"
    finally:
        compiled.teardown()
        rt.kill(a)
        rt.kill(b)
