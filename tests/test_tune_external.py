"""External-searcher adapter conformance (reference:
``python/ray/tune/search/optuna/optuna_search.py`` — the adapter
contract: DSL->library space conversion, ask/tell flow, warm start,
save/restore, import gating)."""
import math

import pytest

from ray_tpu import tune
from ray_tpu.tune import simpleopt
from ray_tpu.tune.external import (ExternalSearcher, OptunaSearch,
                                   SimpleOptSearch, flatten_space,
                                   unflatten_config)


def test_flatten_unflatten_roundtrip():
    space = {"lr": tune.uniform(1e-4, 1e-1),
             "model": {"layers": tune.randint(1, 5), "act": "relu"},
             "seed": 7}
    domains, consts = flatten_space(space)
    assert set(domains) == {"lr", "model/layers"}
    assert consts == {"model/act": "relu", "seed": 7}
    cfg = unflatten_config({"lr": 0.01, "model/layers": 2,
                            "model/act": "relu", "seed": 7})
    assert cfg == {"lr": 0.01, "model": {"layers": 2, "act": "relu"},
                   "seed": 7}


def test_adapter_lifecycle_ask_tell():
    """The base class drives _setup/_ask/_tell with oriented values and
    pending bookkeeping — the seam a third-party adapter implements."""
    calls = {"setup": 0, "ask": 0, "tell": []}

    class Probe(ExternalSearcher):
        def _setup(self, domains):
            calls["setup"] += 1
            self._keys = list(domains)

        def _ask(self):
            calls["ask"] += 1
            return {k: 0.5 for k in self._keys}

        def _tell(self, point, value, error=False):
            calls["tell"].append((point, value, error))

    s = Probe(metric="loss", mode="min")
    s.set_search_space({"x": tune.uniform(0, 1)})
    cfg = s.suggest("t1")
    assert cfg == {"x": 0.5} and calls["setup"] == 1
    # min mode: the library always maximizes, so value arrives negated
    s.on_trial_complete("t1", {"loss": 2.0})
    assert calls["tell"] == [({"x": 0.5}, -2.0, False)]
    # errored trials surface error=True with NaN
    s.suggest("t2")
    s.on_trial_complete("t2", error=True)
    assert calls["tell"][-1][2] is True
    # unknown trial ids are ignored (restored-controller replays)
    s.on_trial_complete("ghost", {"loss": 1.0})
    assert len(calls["tell"]) == 2


def test_simpleopt_study_exploits_best():
    dists = {"x": simpleopt.FloatDist(0.0, 1.0)}
    study = simpleopt.Study(dists, seed=0, exploit_prob=1.0)
    for v in (0.1, 0.9, 0.2, 0.85):
        study.tell({"x": v}, -abs(v - 0.9))
    assert study.best[0]["x"] == 0.9
    picks = [study.ask()["x"] for _ in range(16)]
    # perturbations of the best cluster near 0.9, not uniform
    assert sum(1 for p in picks if abs(p - 0.9) < 0.25) >= 12, picks


def test_simpleopt_nan_discarded_and_missing_axes_rejected():
    study = simpleopt.Study({"x": simpleopt.FloatDist(0, 1)}, seed=0)
    study.tell({"x": 0.5}, float("nan"))
    assert study.best is None and not study.trials
    with pytest.raises(ValueError, match="missing axes"):
        study.tell({}, 1.0)


def test_adapter_converts_all_domain_kinds():
    s = SimpleOptSearch("score", seed=0)
    s.set_search_space({"lr": tune.loguniform(1e-4, 1e-1),
                        "bs": tune.randint(8, 64),
                        "opt": tune.choice(["sgd", "adam"]),
                        "nested": {"w": tune.uniform(0, 1)},
                        "tag": "fixed"})
    cfg = s.suggest("t0")
    assert 1e-4 <= cfg["lr"] <= 1e-1
    assert 8 <= cfg["bs"] < 64 and isinstance(cfg["bs"], int)
    assert cfg["opt"] in ("sgd", "adam")
    assert 0 <= cfg["nested"]["w"] <= 1
    assert cfg["tag"] == "fixed"


def test_adapter_rejects_grid_and_empty():
    with pytest.raises(ValueError, match="grid_search"):
        SimpleOptSearch("s").set_search_space(
            {"x": tune.grid_search([1, 2])})
    with pytest.raises(ValueError, match="at least one Domain"):
        SimpleOptSearch("s").set_search_space({"x": 3})


def test_adapter_learns_toward_optimum():
    """Sequential ask/tell on a 1-d quadratic: the adapter's late
    suggestions concentrate near the optimum (library exploitation
    flows through the seam)."""
    s = SimpleOptSearch("score", mode="max", seed=3, exploit_prob=0.8)
    s.set_search_space({"x": tune.uniform(0.0, 1.0)})
    late = []
    for i in range(40):
        tid = f"t{i}"
        cfg = s.suggest(tid)
        if i >= 30:
            late.append(cfg["x"])
        s.on_trial_complete(tid, {"score": -((cfg["x"] - 0.7) ** 2)})
    assert sum(1 for x in late if abs(x - 0.7) < 0.2) >= 7, late


def test_warm_start_and_save_restore(tmp_path):
    s = SimpleOptSearch("score", seed=0, exploit_prob=1.0)
    s.set_search_space({"x": tune.uniform(0, 1)})
    for v, sc in ((0.2, -0.5), (0.62, -0.01), (0.9, -0.3), (0.4, -0.2)):
        s.add_evaluated_point({"x": v}, sc)
    assert s.best[0] == {"x": 0.62}
    path = tmp_path / "searcher.pkl"
    s.save(str(path))
    s2 = SimpleOptSearch("score")
    s2.restore(str(path))
    assert s2.best == s.best and len(s2._study.trials) == 4
    # restored searcher keeps exploiting the learned best
    picks = [s2.suggest(f"r{i}")["x"] for i in range(8)]
    assert sum(1 for p in picks if abs(p - 0.62) < 0.3) >= 6


def test_min_mode_orientation():
    s = SimpleOptSearch("loss", mode="min", seed=0)
    s.set_search_space({"x": tune.uniform(0, 1)})
    for i, (v, loss) in enumerate(((0.1, 5.0), (0.5, 1.0), (0.9, 3.0))):
        s.register_trial(f"t{i}", {"x": v})
        s.on_trial_complete(f"t{i}", {"loss": loss})
    # lowest loss wins, and best reports the USER-oriented value (the
    # study maximizes an internally-negated score under mode='min')
    assert s.best == ({"x": 0.5}, 1.0)


def test_optuna_adapter_import_gated():
    with pytest.raises(ImportError, match="optuna"):
        OptunaSearch("score")


def test_external_with_tuner(rt_cluster):
    def trainable(config):
        score = -((config["x"] - 0.3) ** 2) - ((config["y"] - 0.6) ** 2)
        tune.report({"score": score})

    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1), "y": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", num_samples=10,
            search_alg=SimpleOptSearch("score", mode="max", seed=0)),
    ).fit()
    assert len(grid) == 10
    assert grid.get_best_result().metrics["score"] > -0.3


def test_external_under_concurrency_limiter(rt_cluster):
    def trainable(config):
        tune.report({"score": -abs(config["x"] - 0.5)})

    limited = tune.ConcurrencyLimiter(
        SimpleOptSearch("score", seed=1), max_concurrent=2)
    grid = tune.Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=6, search_alg=limited),
    ).fit()
    assert len(grid) == 6 and not grid.errors
