"""Offline batch inference (ISSUE 11): the Data → DecodeEngine pipeline
must stream token-identical generations at full occupancy, throttle
admission by live engine queue depth, survive retryable engine failures
in-run via ``resume_from`` replay, resume a SIGKILLed driver from its
progress log exactly-once with byte-identical output, and leave engines
clean + admissible when the consumer walks away."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _ref_chunked(params, prompt, cfg, max_new, **kw):
    from ray_tpu.models import gpt_decode

    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    return np.concatenate([s[0] for s in gpt_decode.generate_chunked(
        params, np.asarray(prompt)[None], cfg, max_new, **kw)])


def _make_engine(nano, nano_params, **kw):
    from ray_tpu.serve.engine import DecodeEngine

    # Same static knobs as test_serve_engine.py: the jitted programs
    # are already in the process-wide lru caches.
    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    return DecodeEngine(nano_params, nano, **kw)


def _rows(nano, n, base_seed=0):
    rng = np.random.default_rng(base_seed)
    return [{"rid": int(i),
             "prompt": rng.integers(0, nano.vocab_size,
                                    (int(rng.integers(5, 17)),)
                                    ).astype(np.int32)}
            for i in range(n)]


def _flat_rows(blocks):
    from ray_tpu.data import block as B

    return [r for b in blocks for r in B.iter_rows(b)]


def test_pipeline_token_identity_and_order(nano, nano_params):
    """Every row's generation is token-identical to generate_chunked,
    rows come back in input order across block boundaries, and the
    pipeline accounting adds up."""
    from ray_tpu import data as rd

    eng = _make_engine(nano, nano_params)
    try:
        rows = _rows(nano, 10)
        ds = rd.from_items(rows, block_size=3)
        bi = rd.BatchInferencer(eng, prompts_col="prompt", max_new=9)
        got = _flat_rows(bi.run(ds))
        assert [r["rid"] for r in got] == list(range(10))
        for r in got:
            ref = _ref_chunked(nano_params, r["prompt"], nano, 9)
            assert (np.asarray(r["generated"]) == ref).all(), r["rid"]
        assert bi.stats["rows"] == 10 and bi.stats["tokens"] == 90
        assert bi.stats["blocks"] == 4
        st = eng.stats()
        assert st["admitted"] == 10 and st["active_slots"] == 0
    finally:
        eng.shutdown()


def test_dataset_generate_end_to_end(nano, nano_params):
    """Dataset.generate builds (and tears down) engines from a
    (params, cfg) ref and honors a per-row max_new column."""
    from ray_tpu import data as rd

    rows = [{"rid": i, "prompt": np.arange(5 + i, dtype=np.int32)
             % nano.vocab_size, "n": 3 + (i % 3)} for i in range(6)]
    out = rd.from_items(rows, block_size=2).generate(
        (nano_params, nano), "prompt", max_new_col="n",
        slots=2, chunk=4, max_len=64, prompt_buckets=(8, 16)).take_all()
    assert [r["rid"] for r in out] == list(range(6))
    for r in out:
        assert len(r["generated"]) == r["n"]
        ref = _ref_chunked(nano_params, r["prompt"], nano, r["n"])
        assert (np.asarray(r["generated"]) == ref).all()


def test_saturation_policy_bounds_queue():
    """The policy admits while any engine has backlog headroom and
    routes to the least-backlogged engine; at the bound it refuses."""
    from ray_tpu.data.llm import EngineSaturationPolicy

    class Fake:
        def __init__(self, slots, depth):
            self.slots, self._d = slots, depth

        def queue_depth(self):
            return self._d

    a, b = Fake(4, 0), Fake(4, 5)
    pol = EngineSaturationPolicy([a, b], queue_factor=2.0)  # limit 8
    assert pol.can_add_input(0) and pol.pick() is a
    a._d = 8
    assert pol.pick() is b and pol.can_add_input(0)
    b._d = 8
    assert pol.pick() is None and not pol.can_add_input(0)
    with pytest.raises(ValueError):
        EngineSaturationPolicy([], queue_factor=2.0)
    with pytest.raises(ValueError):
        EngineSaturationPolicy([a], queue_factor=0)


def test_queue_depth_signal_and_gauge(nano, nano_params):
    """queue_depth counts accepted-not-yet-admitted requests, shows up
    in engine.stats() (as both queue_depth and the legacy queued), and
    the driver exports it as the serve_engine_queue_depth gauge."""
    from ray_tpu._private.metrics import serve_metrics

    eng = _make_engine(nano, nano_params, deployment="qd_probe")
    try:
        eng.inject_fault("driver_slow", wedge_s=0.05)
        prompt = np.arange(8, dtype=np.int32) % nano.vocab_size
        streams = [eng.stream(prompt, 8, seed=i) for i in range(6)]
        deadline = time.time() + 5
        seen = 0
        while time.time() < deadline:
            seen = max(seen, eng.queue_depth())
            st = eng.stats()
            assert st["queue_depth"] == st["queued"]
            if seen >= 2:
                break
            time.sleep(0.01)
        assert seen >= 2, "backlog never formed behind the slow driver"
        eng.inject_fault("driver_slow", wedge_s=0.0)
        for s in streams:
            list(s)
        assert eng.queue_depth() == 0
        deadline = time.time() + 5
        key = (("deployment", "qd_probe"),)
        while time.time() < deadline:
            vals = dict(serve_metrics()["engine_queue_depth"].collect())
            if vals.get(key) == 0:
                break
            time.sleep(0.02)
        assert vals.get(key) == 0, vals
    finally:
        eng.shutdown()


def test_progress_log_resume_skips_committed(tmp_path, nano, nano_params):
    """Exactly-once: a completed run's log satisfies a rerun without a
    single resubmission, and the outputs match row for row."""
    from ray_tpu import data as rd

    rows = _rows(nano, 8)
    ds = rd.from_items(rows, block_size=3)
    d = str(tmp_path / "progress")
    eng = _make_engine(nano, nano_params)
    try:
        bi = rd.BatchInferencer(eng, prompts_col="prompt", max_new=7,
                                progress_path=d)
        first = _flat_rows(bi.run(ds))
        assert bi.stats["blocks"] == 3
    finally:
        eng.shutdown()
    eng2 = _make_engine(nano, nano_params)
    try:
        bi2 = rd.BatchInferencer(eng2, prompts_col="prompt", max_new=7,
                                 progress_path=d)
        again = _flat_rows(bi2.run(ds))
        assert eng2.stats()["admitted"] == 0     # zero rows resubmitted
        assert bi2.stats["blocks_from_log"] == 3
        assert bi2.stats["rows_resumed_from_log"] == 8
        assert [r["rid"] for r in again] == [r["rid"] for r in first]
        for a, b in zip(first, again):
            assert (np.asarray(a["generated"])
                    == np.asarray(b["generated"])).all()
            # Rows served from the log are indistinguishable from fresh
            # ones: numpy types survive the commit round-trip exactly.
            assert type(b["prompt"]) is type(a["prompt"])
            assert b["prompt"].dtype == a["prompt"].dtype
    finally:
        eng2.shutdown()


def test_progress_log_fingerprint_mismatch(tmp_path, nano, nano_params):
    """Resuming with different generation knobs must refuse, not mix
    token streams from two configurations."""
    from ray_tpu import data as rd

    d = str(tmp_path / "progress")
    eng = _make_engine(nano, nano_params)
    try:
        bi = rd.BatchInferencer(eng, prompts_col="prompt", max_new=4,
                                progress_path=d)
        list(bi.run(rd.from_items(_rows(nano, 2), block_size=2)))
        with pytest.raises(ValueError, match="different generation"):
            rd.BatchInferencer(eng, prompts_col="prompt", max_new=5,
                               progress_path=d)
        # A heterogeneous pool (different generation-determining knobs)
        # refuses up front: row routing is load-dependent, so mixed
        # engines would make output depend on timing.
        hot = _make_engine(nano, nano_params, temperature=1.0)
        try:
            with pytest.raises(ValueError, match="disagree"):
                rd.BatchInferencer([eng, hot], prompts_col="prompt",
                                   max_new=4)
        finally:
            hot.shutdown()
    finally:
        eng.shutdown()


def test_retryable_engine_failure_resumes_in_run(nano, nano_params):
    """A mid-run engine-driver death (retryable EngineRestartError)
    costs a replay, not the run: the pipeline supervises the driver
    back up and resubmits with resume_from, and the seeded temp>0
    output stays token-identical to an undisturbed engine's."""
    from ray_tpu import data as rd

    rows = _rows(nano, 8)
    ds = rd.from_items(rows, block_size=4)

    def run(arm_fault):
        eng = _make_engine(nano, nano_params, temperature=1.0)
        try:
            if arm_fault:
                eng.inject_fault("driver_die", at_tokens=20)
            bi = rd.BatchInferencer(eng, prompts_col="prompt",
                                    max_new=12, seed=5)
            out = _flat_rows(bi.run(ds))
            return out, bi.stats, eng.stats()
        finally:
            eng.shutdown()

    ref, _, _ = run(arm_fault=False)
    got, stats, est = run(arm_fault=True)
    assert est["driver_restarts"] == 1
    assert stats["retries"] >= 1
    assert [r["rid"] for r in got] == [r["rid"] for r in ref]
    for a, b in zip(ref, got):
        assert (np.asarray(a["generated"])
                == np.asarray(b["generated"])).all()


def test_abandoned_pipeline_frees_engine(nano, nano_params):
    """Satellite: walking away from the pipeline closes every in-flight
    engine stream, the engine frees slots AND pages at its next chunk
    boundary, and it remains admissible for the next run."""
    from ray_tpu import data as rd

    eng = _make_engine(nano, nano_params, paged=True, page_size=8,
                       prefix_cache=False)
    n_pages = eng.n_pages
    try:
        rows = _rows(nano, 12)
        bi = rd.BatchInferencer(eng, prompts_col="prompt", max_new=40)
        gen = bi.run(rd.from_items(rows, block_size=2))
        next(gen)                       # block 0 done; more in flight
        assert bi._flights, "no in-flight streams to abandon"
        lanes = [fl.stream._lane for fl in bi._flights.values()]
        gen.close()                     # consumer walks away
        assert all(lane.closed for lane in lanes)
        deadline = time.time() + 10
        st = {}
        while time.time() < deadline:
            st = eng.stats()
            if st["active_slots"] == 0 and st["queue_depth"] == 0 \
                    and st["pages_free"] == n_pages:
                break
            time.sleep(0.02)
        assert st["active_slots"] == 0 and st["queue_depth"] == 0, st
        assert st["pages_free"] == n_pages, st
        assert st["abandoned"] >= 1, st
        # Still admissible: a fresh stream decodes token-identically.
        prompt = rows[0]["prompt"]
        out = np.concatenate(list(eng.stream(prompt, 6)))
        assert (out == _ref_chunked(nano_params, prompt, nano, 6)).all()
    finally:
        eng.shutdown()


def _bench():
    """Import benchmarks/batch_infer.py as a module (its run_pipeline
    is the shared driver body the --child subprocess runs)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "batch_infer_bench",
        os.path.join(ROOT, "benchmarks", "batch_infer.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_args(temperature, **over):
    import argparse

    a = argparse.Namespace(
        config="nano", slots=2, chunk=4, engines=1, rows=24,
        block_size=4, max_new=12, max_len=64, temperature=temperature,
        seed=0, queue_factor=2.0, throttle=0.0)
    for k, v in over.items():
        setattr(a, k, v)
    return a


@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_sigkill_preemption_resume_exactly_once(temperature, tmp_path):
    """THE kill-and-resume acceptance: a throttled driver subprocess is
    SIGKILLed mid-run (>= 1 block durably committed), and the resumed
    run loses nothing, duplicates nothing, and writes output files
    byte-identical to an uninterrupted run — temp 0 AND seeded
    temp > 0. The reference and resumed runs drive the same benchmark
    pipeline body in-process (programs already compiled here); only the
    victim is a subprocess, because SIGKILL must take the whole driver."""
    from ray_tpu.data.llm import ProgressLog
    from ray_tpu.testing import sigkill_when

    mod = _bench()
    out_ref = str(tmp_path / "out_ref")
    out_res = str(tmp_path / "out_res")
    progress = str(tmp_path / "progress")
    n_blocks = 6

    # Uninterrupted reference, in-process.
    _bi, engines, _ = mod.run_pipeline(
        _bench_args(temperature), out_dir=out_ref)
    for e in engines:
        e.shutdown()

    # Victim: throttled child driver, SIGKILLed once 2 blocks committed.
    a = _bench_args(temperature, throttle=0.05)
    child = mod._child_cmd(a, out=str(tmp_path / "out_killed"),
                           progress=progress, throttle=a.throttle)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(child, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env, cwd=ROOT)
    killed = sigkill_when(
        proc, lambda: len(ProgressLog.scan(progress)) >= 2,
        timeout_s=300)
    committed = len(ProgressLog.scan(progress))
    assert killed, "driver outran the kill predicate"
    assert 1 <= committed < n_blocks, committed

    # Resume in-process from the same progress log, full speed.
    bi, engines, _ = mod.run_pipeline(
        _bench_args(temperature), out_dir=out_res, progress=progress)
    for e in engines:
        e.shutdown()
    assert bi.stats["rows_resumed_from_log"] >= committed * a.block_size
    files_ref, rids_ref = mod._read_out_dir(out_ref)
    files_res, rids_res = mod._read_out_dir(out_res)
    assert files_ref == files_res            # byte-identical output
    assert sorted(rids_res) == sorted(set(rids_res)) == sorted(rids_ref)


def test_batch_infer_smoke_benchmark():
    """Satellite CI hook: ``benchmarks/batch_infer.py --smoke`` runs
    both phases end to end; the saturation row must report >= 0.8
    steady-state slot occupancy (the ISSUE acceptance bar) with a
    bounded admission queue, and the resume row must be clean."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmarks",
                                      "batch_infer.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    sat = [r for r in rows if r["metric"].endswith("_saturation")]
    res = [r for r in rows if r["metric"].endswith("_resume")]
    assert sat and res, rows
    s, r = sat[0], res[0]
    assert s["smoke"] is True and s["value"] > 0
    assert s["avg_slot_occupancy"] >= 0.8, s
    assert s["queue_depth_max"] <= 2 * s["queue_factor"] * s["slots"], s
    assert s["cost_per_mtok"] > 0
    assert r["killed"] is True and r["identical"] is True, r
    assert r["lost_rows"] == 0 and r["dup_rows"] == 0, r
