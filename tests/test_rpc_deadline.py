"""RPC deadline + task-retention regression tests (round-4 post-mortem).

The cold-suite hang traced to two layered defects:
  1. serve tasks were fire-and-forget ``create_task`` calls with no strong
     reference — asyncio keeps only weak refs, so GC pressure could
     collect a serve task mid-execution and its reply was never sent;
  2. control-plane callers had no deadline, so a lost reply hung forever.
These tests pin both fixes: lost replies surface as ``RpcError`` within
the deadline, and serve tasks are strongly referenced until done.
"""
import asyncio
import gc

import pytest

from ray_tpu._private import rpc


def test_call_simple_deadline_on_lost_reply(tmp_path):
    """A handler that never replies must fail the caller at the deadline
    with the method name in the error — not hang."""
    path = str(tmp_path / "srv.sock")

    async def go():
        hung = asyncio.Event()

        async def handler(method, payload, bufs, conn):
            if method == "blackhole":
                hung.set()
                await asyncio.Event().wait()  # never replies
            return {"ok": True}

        server = await rpc.RpcServer(handler, path=path).start()
        conn = await rpc.connect(path)
        try:
            # Sanity: normal call works with a deadline.
            assert (await conn.call_simple("ping", {}, timeout=5.0))["ok"]
            with pytest.raises(rpc.RpcError, match="blackhole"):
                await conn.call_simple("blackhole", {}, timeout=0.5)
            assert hung.is_set()
            # Connection survives a timed-out call: next call still works.
            assert (await conn.call_simple("ping", {}, timeout=5.0))["ok"]
            # The timed-out request no longer leaks a pending future.
            assert not conn._pending
        finally:
            await conn.close()
            await server.stop()

    asyncio.run(go())


def test_serve_tasks_survive_gc(tmp_path):
    """Serve tasks must be strongly referenced: run requests whose handler
    yields across an aggressive gc.collect() and require every reply to
    arrive. (Before the fix the loop held only weak refs to these tasks.)"""
    path = str(tmp_path / "srv.sock")

    async def go():
        async def handler(method, payload, bufs, conn):
            # Suspend so the serve task is alive across collections.
            await asyncio.sleep(0.01)
            return {"n": payload["n"]}

        server = await rpc.RpcServer(handler, path=path).start()
        conn = await rpc.connect(path)
        try:
            futs = [conn.send_request("echo", {"n": i}) for i in range(64)]
            for _ in range(5):
                gc.collect()
                await asyncio.sleep(0.005)
            payloads = [
                (await asyncio.wait_for(f, 10))[0] for f in futs]
            assert sorted(p["n"] for p in payloads) == list(range(64))
        finally:
            await conn.close()
            await server.stop()

    asyncio.run(go())


def test_spawn_keeps_strong_reference():
    async def go():
        saw = asyncio.Event()

        async def bg():
            await asyncio.sleep(0.01)
            saw.set()

        t = rpc.spawn(bg())
        assert t in rpc._background_tasks
        gc.collect()
        await asyncio.wait_for(saw.wait(), 5)
        await asyncio.sleep(0)  # let the done-callback run
        assert t not in rpc._background_tasks

    asyncio.run(go())
