"""Fused paged-attention kernel + int8 quantized KV cache (ISSUE 16).

The exactness contract under test:

- ``attn_kernel="pallas"`` (Pallas ``pallas_call`` on TPU, interpret
  mode on CPU — tier-1 exercises the REAL kernel body either way) is
  token-identical to the XLA gather reference at temperature 0 AND
  under seeded sampling, across sentinel-padded page tables, mid-page
  COW prefix forks, and the int8 cache layout.
- ``kv_dtype="int8"`` (per-page-per-head scales, quantize on scatter /
  dequantize at attention) bounds its round-trip error by one quantum
  (``1/127`` relative to the page's absmax) and documents a temp-0
  divergence RATE vs fp rather than pretending bit-identity: measured
  ~0.2 of streams diverge somewhere on random nano weights, asserted
  here under a loose 0.5 ceiling, with the FIRST token exact (the
  prefill's own forward runs in fp).
- Both knobs preserve the ``len(prompt_buckets) + k`` compiled-program
  budget and the handoff plane (int8 ships codes + scales; the digest
  covers both; any layout mismatch degrades to the counted local
  re-prefill).
"""
import threading

import numpy as np
import pytest


def _drain(lane):
    from ray_tpu.serve.batching import _EngineStream

    return np.concatenate(list(_EngineStream(lane)))


@pytest.fixture(scope="module")
def nano():
    from ray_tpu.models import gpt

    return gpt.CONFIGS["nano"]


@pytest.fixture(scope="module")
def nano_params(nano):
    import jax

    from ray_tpu.models import gpt

    return gpt.init_params(jax.random.PRNGKey(0), nano)


def _make(nano, nano_params, **kw):
    from ray_tpu.serve.engine import DecodeEngine

    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("paged", True)
    kw.setdefault("page_size", 8)
    return DecodeEngine(nano_params, nano, **kw)


def _drain_concurrent(eng, prompts, max_news, seeds=None):
    outs = {}

    def consume(i):
        kw = {"seed": seeds[i]} if seeds else {}
        outs[i] = np.concatenate(
            list(eng.stream(prompts[i], max_news[i], **kw)))

    threads = [threading.Thread(target=consume, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outs


def _prefix_prompts(nano, rng, n_fresh=2):
    """A shared 12-token system prompt with fresh 4-token tails: the
    second admission hits the prefix cache mid-page (12 % 8 != 0) and
    forks the partial page copy-on-write."""
    sysp = rng.integers(0, nano.vocab_size, (12,)).astype(np.int32)
    out = []
    for _ in range(n_fresh):
        tail = rng.integers(0, nano.vocab_size, (4,)).astype(np.int32)
        out.append(np.concatenate([sysp, tail]))
    return out


# --------------------------------------------------- kernel vs reference
def test_paged_attention_matches_gather_direct(nano, nano_params):
    """Direct kernel-vs-reference on a hand-built pool: random pages,
    page tables with SENTINEL padding and out-of-order mappings, per
    -slot lengths that end mid-page. The fused kernel must match the
    gather reference to f32-accumulation-reorder noise (well below one
    bf16 ulp of the output scale) — and garbage in pages past a slot's
    pos must not leak in (the length mask and the sentinel skip are
    fused into the kernel)."""
    import jax.numpy as jnp

    from ray_tpu.models import gpt_decode

    H, hd, ps, n_pages, max_pages, B = nano.n_head, nano.head_dim, 8, \
        16, 4, 3
    rng = np.random.default_rng(21)
    kc = jnp.asarray(rng.standard_normal((n_pages, ps, H, hd)),
                     nano.dtype)
    vc = jnp.asarray(rng.standard_normal((n_pages, ps, H, hd)),
                     nano.dtype)
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), nano.dtype)
    pt = np.full((B, max_pages), gpt_decode.PT_SENTINEL, np.int32)
    pt[0, :2] = [5, 3]            # out of order, 2 pages + sentinels
    pt[1, :4] = [7, 0, 9, 2]      # full table
    pt[2, :1] = [11]              # single page, ends mid-page
    pos = jnp.asarray([12, 30, 4], jnp.int32)   # mid-page lengths
    ref = gpt_decode.paged_attention(q, kc, vc, jnp.asarray(pt), pos,
                                     page_size=ps, kernel="gather")
    out = gpt_decode.paged_attention(q, kc, vc, jnp.asarray(pt), pos,
                                     page_size=ps, kernel="pallas")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=0, atol=1e-2)
    # Sentinel/length fusion: clobber every page the tables never map
    # AND the tail of slot 2's single page past pos=4 — outputs for
    # the mapped slots must not move at all.
    live = {5, 3, 7, 0, 9, 2, 11}
    kc2, vc2 = np.array(kc, np.float32), np.array(vc, np.float32)
    for p in range(n_pages):
        if p not in live:
            kc2[p] = 1e4
            vc2[p] = 1e4
    kc2[11, 5:] = 1e4             # past slot 2's pos, same page
    vc2[11, 5:] = 1e4
    out2 = gpt_decode.paged_attention(
        jnp.asarray(q), jnp.asarray(kc2, nano.dtype),
        jnp.asarray(vc2, nano.dtype), jnp.asarray(pt), pos,
        page_size=ps, kernel="pallas")
    assert np.array_equal(np.asarray(out2, np.float32),
                          np.asarray(out, np.float32))


@pytest.mark.parametrize("kv_dtype", ["fp", "int8"])
def test_kernel_token_identity_greedy(nano, nano_params, kv_dtype):
    """Kernel on vs off at temperature 0: identical token streams for
    every lane — mixed prompt lengths (sentinel-padded tables), a
    shared prefix hit that forks mid-page (COW), concurrent slots —
    on BOTH cache layouts. The kernel's exactness contract is against
    the gather reference on the SAME cache bytes, so it holds for int8
    exactly as for fp."""
    ref = _make(nano, nano_params, prefix_cache=True,
                prompt_buckets=(8, 16), kv_dtype=kv_dtype,
                attn_kernel="gather")
    ker = _make(nano, nano_params, prefix_cache=True,
                prompt_buckets=(8, 16), kv_dtype=kv_dtype,
                attn_kernel="pallas")
    try:
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, nano.vocab_size,
                                (n,)).astype(np.int32)
                   for n in (5, 11, 16)] + _prefix_prompts(nano, rng)
        max_news = [9, 7, 12, 8, 8]
        of = _drain_concurrent(ref, prompts, max_news)
        ok = _drain_concurrent(ker, prompts, max_news)
        for i in range(len(prompts)):
            assert (of[i] == ok[i]).all(), (i, of[i], ok[i])
        st = ker.stats()
        assert st["attn_kernel"] == "pallas"
        assert st["attn_kernel_dispatches"] > 0
        assert ref.stats()["attn_kernel_dispatches"] == 0
    finally:
        ref.shutdown()
        ker.shutdown()


@pytest.mark.parametrize("kv_dtype", ["fp", "int8"])
def test_kernel_token_identity_temperature(nano, nano_params, kv_dtype):
    """Seeded sampling (temp 1.0): kernel on vs off reproduces the
    same per-slot PRNG chains token-for-token; a different seed still
    diverges (the identity is not an artifact of a dead sampler)."""
    ref = _make(nano, nano_params, temperature=1.0, prefix_cache=False,
                kv_dtype=kv_dtype, attn_kernel="gather")
    ker = _make(nano, nano_params, temperature=1.0, prefix_cache=False,
                kv_dtype=kv_dtype, attn_kernel="pallas")
    try:
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, nano.vocab_size,
                                (n,)).astype(np.int32)
                   for n in (8, 13)]
        max_news = [8, 10]
        seeds = [7, 11]
        of = _drain_concurrent(ref, prompts, max_news, seeds)
        ok = _drain_concurrent(ker, prompts, max_news, seeds)
        for i in range(2):
            assert (of[i] == ok[i]).all(), (i, of[i], ok[i])
        other = np.concatenate(list(ker.stream(prompts[0], 8, seed=8)))
        assert not (other == ok[0]).all()
    finally:
        ref.shutdown()
        ker.shutdown()


# ------------------------------------------------------------ int8 layout
def test_int8_roundtrip_error_bound(nano):
    """Quantize-on-scatter round trip: one page written through
    ``_merge_span_int8`` dequantizes back within ONE quantum — the
    per-page-per-head scale is absmax/127, so |x - deq(q(x))| <=
    scale/2 elementwise, i.e. rel err <= 1/127 of the page-head
    absmax. Codes past the written span must be canonical zeros (page
    bytes are a pure function of held tokens — what the handoff digest
    relies on)."""
    import jax.numpy as jnp

    from ray_tpu.models import gpt_decode

    H, hd, ps = nano.n_head, nano.head_dim, 8
    rng = np.random.default_rng(5)
    vals = rng.standard_normal((1, 6, H, hd)).astype(np.float32)
    codes = jnp.zeros((4, ps, H, hd), jnp.int8)     # per-layer pool
    scales = jnp.zeros((4, H), jnp.float32)
    pt = jnp.asarray([[0, 1, 2, 3]], jnp.int32)
    c2, s2 = gpt_decode._merge_span_int8(
        codes, scales, jnp.asarray(vals), pt, jnp.asarray([0]),
        jnp.asarray(6), jnp.asarray([True]), ps)
    deq = np.asarray(c2, np.float32) * \
        np.asarray(s2)[:, None, :, None]
    absmax = np.abs(vals[0, :6]).max(axis=(0, 2))       # per head
    err = np.abs(deq[0, :6] - vals[0, :6])
    assert (err <= absmax[None, :, None] / 127.0 + 1e-7).all()
    # Canonical zeros past the span, in codes AND untouched pages.
    assert (np.asarray(c2)[0, 6:] == 0).all()
    assert (np.asarray(c2)[1:] == 0).all()
    assert (np.asarray(s2)[1:] == 0).all()


def test_int8_divergence_rate_documented(nano, nano_params):
    """fp vs int8 at temperature 0 on the SAME weights: the FIRST
    token of every stream is exact (prefill's forward runs in fp; only
    the CACHE is quantized), and the stream-divergence rate sits under
    the documented 0.5 ceiling (measured ~0.2 on random nano weights —
    real checkpoints with peaked logits sit far lower)."""
    fp = _make(nano, nano_params, slots=2, prefix_cache=False,
               kv_dtype="fp")
    q8 = _make(nano, nano_params, slots=2, prefix_cache=False,
               kv_dtype="int8")
    try:
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, nano.vocab_size,
                                (int(n),)).astype(np.int32)
                   for n in rng.integers(5, 16, 10)]
        max_news = [8] * len(prompts)
        of = _drain_concurrent(fp, prompts, max_news)
        oq = _drain_concurrent(q8, prompts, max_news)
        diverged = 0
        for i in range(len(prompts)):
            assert of[i][0] == oq[i][0], "first token must be exact"
            if not (of[i] == oq[i]).all():
                diverged += 1
        rate = diverged / len(prompts)
        assert rate <= 0.5, f"int8 divergence rate {rate} > 0.5 bound"
    finally:
        fp.shutdown()
        q8.shutdown()


def test_int8_spec_decode_identity(nano, nano_params):
    """Speculative decoding on a quantized pool: the verify forward
    reads the SAME int8 cache as plain decode, so spec on vs off is
    token-identical at temp 0 — acceptance arithmetic never sees the
    quantization, only the committed tokens do."""
    plain = _make(nano, nano_params, prefix_cache=False,
                  kv_dtype="int8")
    spec = _make(nano, nano_params, prefix_cache=False,
                 kv_dtype="int8", spec_decode="ngram", draft_k=4)
    try:
        rng = np.random.default_rng(7)
        base = rng.integers(0, nano.vocab_size, (4,)).astype(np.int32)
        prompts = [np.tile(base, 3)[:n] for n in (9, 12)]  # repetitive
        max_news = [10, 8]
        op = _drain_concurrent(plain, prompts, max_news)
        os_ = _drain_concurrent(spec, prompts, max_news)
        for i in range(2):
            assert (op[i] == os_[i]).all(), (i, op[i], os_[i])
    finally:
        plain.shutdown()
        spec.shutdown()


# ------------------------------------------------------- quantized handoff
def test_quantized_handoff_roundtrip(nano, nano_params):
    """int8 prefill engine -> int8 decode engine: the payload ships
    CODES + per-page scales, the digest covers both, and the decode
    stream is token-identical to an uninterrupted run on one int8
    engine. Tampering with a shipped scale fails byte-verification and
    degrades to the counted local re-prefill; so does landing the int8
    payload on an fp engine (layout mismatch)."""
    kw = dict(paged=True, page_size=8, prefix_cache=False,
              kv_dtype="int8")
    pre = _make(nano, nano_params, role="prefill", **kw)
    dec = _make(nano, nano_params, role="decode", **kw)
    ref_eng = _make(nano, nano_params, **kw)
    fp_dec = _make(nano, nano_params, role="decode", paged=True,
                   page_size=8, prefix_cache=False, kv_dtype="fp")
    try:
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, nano.vocab_size, (11,)).astype(np.int32)
        ref = np.concatenate(list(ref_eng.stream(prompt, 10, seed=3)))
        desc = pre.handoff(prompt, 10, seed=3)
        payload = desc["payload"]
        assert payload["k"].dtype == np.int8
        assert payload["kv_dtype"] == "int8"
        assert payload["page_size"] == 8
        assert payload["ks"].shape == (nano.n_layer, 2, nano.n_head)
        out = _drain(dec.admit_prefilled(desc))
        assert (out == ref).all(), (out, ref)
        assert dec.stats()["handoff"]["imported"] == 1
        # Scale tamper: the digest covers the scales, so a flipped
        # scale fails verification -> local re-prefill, same tokens.
        bad = dict(desc)
        bad["payload"] = dict(payload)
        bad["payload"]["ks"] = np.array(payload["ks"])
        bad["payload"]["ks"][0, 0, 0] *= 2
        out_t = _drain(dec.admit_prefilled(bad))
        assert (out_t == ref).all()
        assert dec.stats()["handoff"]["import_fallbacks"] == 1
        # Layout mismatch: int8 payload on an fp engine falls back to
        # a local fp re-prefill (token-identical by determinism).
        fp_ref = np.concatenate(list(
            _ref_fp_stream(nano, nano_params, prompt)))
        out_fp = _drain(fp_dec.admit_prefilled(desc))
        assert (out_fp == fp_ref).all()
        assert fp_dec.stats()["handoff"]["import_fallbacks"] == 1
        assert fp_dec.stats()["handoff"]["imported"] == 0
    finally:
        pre.shutdown()
        dec.shutdown()
        ref_eng.shutdown()
        fp_dec.shutdown()


def _ref_fp_stream(nano, nano_params, prompt):
    eng = _make(nano, nano_params, paged=True, page_size=8,
                prefix_cache=False, kv_dtype="fp")
    try:
        return list(eng.stream(prompt, 10, seed=3))
    finally:
        eng.shutdown()


# ------------------------------------------------------- program budget
def test_recompile_guard_both_knobs(nano, nano_params):
    """With attn_kernel=pallas AND kv_dtype=int8 the compiled-program
    set is STILL ``len(prompt_buckets)`` prefill programs + 1 fused
    chunk program — quantization scatter, scale updates, and the
    kernel dispatch are all inside the same jitted programs, keyed by
    static knobs only. page_size=24 is unique to this test, so the
    (process-wide, lru-shared) wrappers count only this pool's
    programs."""
    from ray_tpu.models.gpt_decode import (jit_decode_chunk_slots_paged,
                                           jit_prefill_into_slot_paged)

    eng = _make(nano, nano_params, slots=3, max_len=48,
                prompt_buckets=(8, 16), page_size=24,
                prefix_cache=True, kv_dtype="int8",
                attn_kernel="pallas")
    try:
        rng = np.random.default_rng(9)
        sysp = rng.integers(0, nano.vocab_size, (12,)).astype(np.int32)

        def storm(lens):
            prompts = []
            for i, n in enumerate(lens):
                if i % 3 == 0:
                    tail = rng.integers(0, nano.vocab_size,
                                        (4,)).astype(np.int32)
                    prompts.append(np.concatenate([sysp, tail]))
                else:
                    prompts.append(rng.integers(
                        0, nano.vocab_size, (int(n),)).astype(np.int32))
            _drain_concurrent(eng, prompts,
                              [int(rng.integers(1, 10))
                               for _ in prompts])

        storm([5, 16, 8])                     # warm every bucket
        pre_prefill = eng._prefill._cache_size()
        pre_step = eng._step._cache_size()
        assert pre_prefill == len(eng.prompt_buckets)
        assert pre_step == 1
        storm([1, 3, 7, 9, 12, 15, 16, 2])    # mixed-shape storm
        assert eng._prefill._cache_size() == pre_prefill
        assert eng._step._cache_size() == pre_step
        # lru wrappers keyed on the FULL static-knob tuple.
        assert jit_prefill_into_slot_paged(nano, 24, 0.0, "int8") \
            is eng._prefill
        assert jit_decode_chunk_slots_paged(
            nano, 4, 24, 0.0, -1, "int8", "pallas") is eng._step
    finally:
        eng.shutdown()


# ----------------------------------------------------------- plumbing
def test_knob_validation_and_plumbing(nano, nano_params):
    """Config-plane guards: the knobs are paged-pool-only and
    validated everywhere they enter — engine ctor, ensure_paging,
    @serve.batch, and the deployment schema."""
    from ray_tpu.serve import batching
    from ray_tpu.serve.schema import DeploymentSchema

    with pytest.raises(ValueError, match="attn_kernel"):
        _make(nano, nano_params, attn_kernel="fused")
    with pytest.raises(ValueError, match="kv_dtype"):
        _make(nano, nano_params, kv_dtype="int4")
    with pytest.raises(ValueError, match="paged"):
        _make(nano, nano_params, paged=False, page_size=None,
              kv_dtype="int8")
    with pytest.raises(ValueError, match="continuous"):
        batching.batch(kv_dtype="int8")(lambda xs: xs)
    with pytest.raises(ValueError, match="continuous"):
        batching.batch(attn_kernel="pallas")(lambda xs: xs)
    DeploymentSchema.from_dict({
        "name": "d",
        "engine": {"page_size": 8, "kv_dtype": "int8",
                   "attn_kernel": "pallas"}})
    with pytest.raises(ValueError, match="unknown engine config"):
        DeploymentSchema.from_dict({"name": "d",
                                    "engine": {"kv_dtyp": "int8"}})
    # Live reconfigure through the same applier the deployment path
    # uses: flat engine + knobs repages; knob change rebuilds the pool.
    eng = _make(nano, nano_params, paged=False, page_size=None)
    try:
        eng.apply_config(page_size=8, kv_dtype="int8",
                         attn_kernel="pallas")
        assert eng.paged and eng.kv_dtype == "int8"
        assert eng.attn_kernel == "pallas"
        st = eng.stats()
        assert st["kv_dtype"] == "int8"
        assert st["kv_bytes_per_token"] < 2 * nano.n_layer * \
            nano.n_head * nano.head_dim * 2   # below the bf16 cost
        out = np.concatenate(list(eng.stream(
            np.arange(5, dtype=np.int32) % nano.vocab_size, 4)))
        assert out.shape == (4,)
    finally:
        eng.shutdown()


def test_kv_bytes_per_page_accounting(nano):
    """The sizing fix: ``kv_bytes_per_page`` charges the CONFIGURED
    element size (int8 codes + amortized f32 scales), so the default
    ``n_pages`` budget admits ~2x lanes — not the param dtype."""
    from ray_tpu.models import gpt_decode

    fp = gpt_decode.kv_bytes_per_page(nano, 8)
    i8 = gpt_decode.kv_bytes_per_page(nano, 8, "int8")
    assert fp == nano.n_layer * 2 * 8 * nano.n_head * nano.head_dim * 2
    assert i8 == nano.n_layer * 2 * (8 * nano.n_head * nano.head_dim
                                     + 4 * nano.n_head)
    assert fp / i8 > 1.5
