"""Serve streaming responses (reference:
``serve/_private/replica.py:391-543`` handle_request_streaming +
``proxy.py`` chunked streaming): generator deployments stream items
through handles and as chunked HTTP, token by token."""
import http.client
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def serve_instance(rt_cluster):
    serve.start(http_options={"host": "127.0.0.1", "port": 0})
    yield serve
    serve.shutdown()


def test_handle_streaming(serve_instance):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield i * 10

    h = serve.run(Streamer.bind(), name="streamer", route_prefix=None)
    gen = h.options(stream=True).remote(5)
    assert isinstance(gen, serve.DeploymentResponseGenerator)
    assert list(gen) == [0, 10, 20, 30, 40]
    serve.delete("streamer")


def test_handle_streaming_async_gen(serve_instance):
    @serve.deployment
    class AsyncStreamer:
        async def __call__(self, n):
            for i in range(n):
                yield f"tok{i}"

    h = serve.run(AsyncStreamer.bind(), name="astream", route_prefix=None)
    out = list(h.options(stream=True).remote(3))
    assert out == ["tok0", "tok1", "tok2"]
    serve.delete("astream")


def test_streaming_error_propagates(serve_instance):
    @serve.deployment
    class Bad:
        def __call__(self, n):
            yield 1
            raise ValueError("boom mid-stream")

    h = serve.run(Bad.bind(), name="bad", route_prefix=None)
    gen = h.options(stream=True).remote(1)
    assert next(gen) == 1
    with pytest.raises(Exception) as ei:
        list(gen)
    assert "boom" in str(ei.value)
    serve.delete("bad")


def test_http_chunked_streaming(serve_instance):
    """A generator ingress streams over HTTP with chunked transfer
    encoding — chunks arrive incrementally, not as one buffered body."""

    @serve.deployment
    class TokenStream:
        def __call__(self, request):
            for i in range(4):
                yield f"tok{i} ".encode()
                time.sleep(0.05)

    serve.run(TokenStream.bind(), name="toks", route_prefix="/toks")
    from ray_tpu.serve import api as serve_api

    port = serve_api._client["http"]["port"]

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/toks")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Transfer-Encoding") == "chunked"
    first = resp.read(5)          # arrives before the stream finishes
    rest = resp.read()
    assert (first + rest) == b"tok0 tok1 tok2 tok3 "
    conn.close()
    serve.delete("toks")


def test_streaming_releases_router_slot(serve_instance):
    """Abandoned/finished streams must return their in-flight slot or
    the router would wedge at max_ongoing_requests."""

    @serve.deployment(max_ongoing_requests=2)
    class S:
        def __call__(self, n):
            for i in range(n):
                yield i

    h = serve.run(S.bind(), name="slots", route_prefix=None)
    for _ in range(8):  # > max_ongoing: only passes if slots release
        assert list(h.options(stream=True).remote(3)) == [0, 1, 2]
    serve.delete("slots")
