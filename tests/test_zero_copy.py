"""Zero-copy shm → numpy/jax adoption (SURVEY §7): big values come out
of the object store as read-only views over the shared segment — no
host copy — and stage onto devices directly."""
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.core.worker import CoreWorker


def test_get_is_zero_copy_and_readonly(rt_cluster):
    arr = np.arange(8 << 20, dtype=np.uint8)  # 8MB -> shm tier
    ref = rt.put(arr)
    out = rt.get(ref)
    core = CoreWorker._current
    frames = core._load_frames(ref.object_id)
    raw = np.frombuffer(frames[-1], dtype=np.uint8)
    # Aliases the segment (no copy was made)...
    assert np.shares_memory(out, raw)
    # ...and is immutable, so user writes can't corrupt the stored
    # value for other readers (plasma semantics).
    assert not out.flags.writeable
    with pytest.raises((ValueError, RuntimeError)):
        out[0] = 1


def test_zero_copy_view_device_put(rt_cluster):
    import jax

    from ray_tpu.utils.device import device_put_shm

    arr = np.ones((512, 512), dtype=np.float32)  # 1MB -> shm
    ref = rt.put(arr)
    out = rt.get(ref)
    dev = device_put_shm(out)
    assert isinstance(dev, jax.Array)
    assert float(dev.sum()) == 512 * 512


def test_inline_values_snapshot_and_readonly(rt_cluster):
    """Inline (non-shm) values are snapshotted at put time: mutating
    the source array after put, or the array a get returned, never
    changes the stored value (matches the reference's immutable-object
    semantics at every size)."""
    src = np.arange(16, dtype=np.int64)
    ref = rt.put(src)
    src[0] = -1  # putter mutates AFTER put
    out = rt.get(ref)
    assert out[0] == 0  # snapshot, not an alias
    assert not out.flags.writeable
    assert rt.get(ref)[0] == 0
