"""SLO-driven autoscaling + crash-safe reconciliation (ISSUE 17).

Three layers:

- unit: ``EMA`` time-constant semantics (``_private/metrics.py``) and
  the pure ``decide()`` contract — hysteresis, step caps, cooldowns,
  stale/missing-signal holds, scale-to-zero idle gate, cold-start
  grace, scale-from-zero, TPOT SLO overlay — tick by tick with a fake
  clock, no cluster;
- integration: a live deployment scales up under load and back down
  when idle, scale-down routes through the drain path, and
  ``serve.status()`` surfaces ``signal_age_s`` + the last decision;
- chaos: the controller is SIGKILLed (``os._exit``) mid-scale-up and
  mid-drain via the ``inject_crash`` hook; a revived controller
  converges to the journaled desired state with zero orphan replicas
  and zero failed client calls.
"""
import math
import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def serve_instance(rt_cluster):
    serve.start(proxy=False)
    yield serve
    serve.shutdown()


# ------------------------------------------------------------------- EMA
def test_ema_time_constant_semantics():
    from ray_tpu._private.metrics import EMA

    with pytest.raises(ValueError):
        EMA(0.0)

    # First sample initializes outright.
    e = EMA(tau_s=2.0)
    assert e.update(10.0, t=100.0) == 10.0

    # One step of exactly tau closes ~63.2% of the gap to the new
    # level; 3*tau closes ~95% — the defining time-constant property.
    e = EMA(tau_s=2.0)
    e.update(0.0, t=0.0)
    v = e.update(1.0, t=2.0)
    assert abs(v - (1 - math.exp(-1))) < 1e-9
    e = EMA(tau_s=2.0)
    e.update(0.0, t=0.0)
    v = e.update(1.0, t=6.0)
    assert abs(v - (1 - math.exp(-3))) < 1e-9

    # Rate independence: sampling a steady level every 0.1 s or every
    # 1.0 s lands at the same value at the same wall-clock time (the
    # property a fixed-alpha EMA does NOT have).
    fine, coarse = EMA(tau_s=2.0), EMA(tau_s=2.0)
    fine.update(0.0, t=0.0)
    coarse.update(0.0, t=0.0)
    for i in range(1, 41):
        fine.update(1.0, t=i * 0.1)
    for i in range(1, 5):
        coarse.update(1.0, t=i * 1.0)
    assert abs(fine.value - coarse.value) < 1e-9

    # Non-positive dt holds (clock skew must not corrupt the average).
    e = EMA(tau_s=2.0)
    e.update(5.0, t=10.0)
    assert e.update(100.0, t=10.0) == 5.0
    assert e.update(100.0, t=9.0) == 5.0

    e.reset()
    assert e.value is None and e.update(7.0, t=0.0) == 7.0


# ---------------------------------------------------------------- decide()
def _cfg(**kw):
    from ray_tpu.serve.config import AutoscalingConfig

    base = dict(min_replicas=1, max_replicas=10,
                target_ongoing_requests=1.0, upscale_delay_s=0.0,
                downscale_delay_s=0.0, hysteresis=0.1, upscale_step=2,
                downscale_step=1, ema_tau_s=0.001)
    base.update(kw)
    return AutoscalingConfig(**base)


def _sig(**kw):
    from ray_tpu.serve.autoscaler import GroupSignals

    return GroupSignals(**kw)


def _st(cfg):
    from ray_tpu.serve.autoscaler import GroupState

    return GroupState(cfg.ema_tau_s)


def test_decide_scale_from_zero_and_cold_grace():
    from ray_tpu.serve.autoscaler import decide

    # min_replicas > 0 never sits at zero.
    cfg = _cfg(min_replicas=1)
    d = decide(cfg, 0, _sig(), _st(cfg), now=0.0)
    assert (d.target, d.direction, d.reason) == (1, "up", "min_replicas")

    # min=0, no demand: stay at zero.
    cfg = _cfg(min_replicas=0, cold_start_grace_s=30.0)
    st = _st(cfg)
    d = decide(cfg, 0, _sig(), st, now=0.0)
    assert (d.target, d.direction, d.reason) == (0, "hold", "idle")

    # Router-pending demand wakes the group (bypassing the stability
    # delay — the burst is already queued) and stamps the grace window.
    d = decide(cfg, 0, _sig(pending=5.0), st, now=1.0)
    assert d.direction == "up" and d.reason == "scale_from_zero"
    assert 1 <= d.target <= cfg.upscale_step
    assert st.cold_until == 1.0 + cfg.cold_start_grace_s

    # During the grace window further upscale is suppressed: the burst
    # queued behind the compiling replica must not panic-scale...
    sig = _sig(n=1, fresh=1, ongoing=50.0)
    d = decide(cfg, 1, sig, st, now=2.0)
    assert (d.target, d.direction, d.reason) == (1, "hold", "cold_start")
    # ...but after it expires the same load scales (capped by step).
    d = decide(cfg, 1, sig, st, now=2.0 + cfg.cold_start_grace_s)
    assert d.reason == "stabilizing"
    d = decide(cfg, 1, sig, st, now=2.1 + cfg.cold_start_grace_s)
    assert (d.target, d.direction) == (1 + cfg.upscale_step, "up")


def test_decide_freshness_degrades_to_hold():
    from ray_tpu.serve.autoscaler import decide

    cfg = _cfg()
    # Every signal rotted: hold, no matter how big the last load was.
    d = decide(cfg, 3, _sig(n=2, fresh=0, ongoing=99.0), _st(cfg), now=0.0)
    assert (d.target, d.direction, d.reason) == (3, "hold", "stale_signal")
    # One member missed its health pass: conservative hold (we cannot
    # tell an idle replica from a wedged probe).
    d = decide(cfg, 3, _sig(n=2, fresh=1, ongoing=99.0), _st(cfg), now=0.0)
    assert (d.target, d.direction, d.reason) == (3, "hold",
                                                 "missing_signal")


def test_decide_hysteresis_steps_cooldowns():
    from ray_tpu.serve.autoscaler import decide

    # Hysteresis dead-band: a load within 10% of the current size is
    # steady, no flap.
    cfg = _cfg()
    d = decide(cfg, 4, _sig(n=4, fresh=4, ongoing=4.3), _st(cfg), now=0.0)
    assert (d.target, d.reason) == (4, "steady")

    # Upscale is step-capped and needs the desired size to survive the
    # stability window (one extra tick at delay 0).
    st = _st(cfg)
    sig = _sig(n=2, fresh=2, ongoing=8.0)
    assert decide(cfg, 2, sig, st, now=0.0).reason == "stabilizing"
    d = decide(cfg, 2, sig, st, now=0.1)
    assert (d.target, d.direction) == (2 + cfg.upscale_step, "up")

    # Downscale is step-capped independently.
    st = _st(cfg)
    idle = _sig(n=4, fresh=4, ongoing=0.0)
    assert decide(cfg, 4, idle, st, now=0.0).reason == "stabilizing"
    d = decide(cfg, 4, idle, st, now=0.1)
    assert (d.target, d.direction) == (4 - cfg.downscale_step, "down")

    # Per-direction cooldown: right after an up actuation, another up
    # holds until the window passes.
    cfg = _cfg(upscale_cooldown_s=100.0)
    st = _st(cfg)
    sig = _sig(n=1, fresh=1, ongoing=9.0)
    decide(cfg, 1, sig, st, now=0.0)
    d = decide(cfg, 1, sig, st, now=0.1)
    assert d.direction == "up"            # first actuation
    sig = _sig(n=3, fresh=3, ongoing=27.0)
    decide(cfg, 3, sig, st, now=0.2)      # stabilizing
    d = decide(cfg, 3, sig, st, now=0.3)
    assert (d.target, d.direction, d.reason) == (3, "hold", "cooldown")
    assert decide(cfg, 3, sig, st, now=200.0).direction == "up"


def test_decide_scale_to_zero_is_opt_in():
    from ray_tpu.serve.autoscaler import decide

    # Without the opt-in a zero-min group still floors at one replica.
    cfg = _cfg(min_replicas=0)
    st = _st(cfg)
    d = decide(cfg, 1, _sig(n=1, fresh=1), st, now=0.0)
    assert (d.target, d.direction, d.reason) == (1, "hold", "idle_wait")

    # With the opt-in, the group must be idle for the full window, then
    # the decision still rides the stability delay before actuating.
    cfg = _cfg(min_replicas=0, scale_to_zero_idle_s=5.0)
    st = _st(cfg)
    idle = _sig(n=1, fresh=1)
    assert decide(cfg, 1, idle, st, now=0.0).reason == "idle_wait"
    assert decide(cfg, 1, idle, st, now=2.0).reason == "idle_wait"
    assert decide(cfg, 1, idle, st, now=6.0).reason == "stabilizing"
    d = decide(cfg, 1, idle, st, now=6.1)
    assert (d.target, d.direction, d.reason) == (0, "down",
                                                 "scale_to_zero")

    # Any load resets the idle clock.
    st = _st(cfg)
    decide(cfg, 1, idle, st, now=0.0)
    decide(cfg, 1, _sig(n=1, fresh=1, ongoing=1.0), st, now=4.0)
    assert st.idle_since is None


def test_decide_slo_overlay_and_occupancy_mode():
    from ray_tpu.serve.autoscaler import decide

    # A breached TPOT p95 forces upscale pressure even at low load.
    cfg = _cfg(tpot_slo_s=0.1)
    st = _st(cfg)
    sig = _sig(n=2, fresh=2, ongoing=1.0, tpot_p95=0.5)
    assert decide(cfg, 2, sig, st, now=0.0).reason == "stabilizing"
    d = decide(cfg, 2, sig, st, now=0.1)
    assert (d.target, d.direction, d.reason) == (3, "up", "slo")

    # Occupancy mode: queued work counts against the slot budget just
    # like admitted work (2 replicas * 4 slots * 0.5 target = 4 per
    # replica; 10 active+queued slots over target -> upscale).
    cfg = _cfg(target_occupancy=0.5)
    st = _st(cfg)
    sig = _sig(n=2, fresh=2, active_slots=6.0, queue_depth=4.0, slots=8.0)
    assert decide(cfg, 2, sig, st, now=0.0).reason == "stabilizing"
    d = decide(cfg, 2, sig, st, now=0.1)
    assert d.direction == "up" and d.reason == "occupancy"


def test_autoscaler_signal_book_prune_and_pending():
    from ray_tpu.serve.autoscaler import PLAIN_GROUP, Autoscaler

    a = Autoscaler()
    a.record("app", "D", "D#1",
             {"ongoing": 2, "engines": [{"queue_depth": 3,
                                         "active_slots": 1, "slots": 4,
                                         "role": "decode"}]}, now=100.0)
    a.record("app", "D", "D#2", {"ongoing": 1}, now=100.5)
    ages = a.signal_ages("app", "D", {"g": ["D#1", "D#2"], "h": ["D#9"]},
                         now=101.0)
    assert ages["g"] == 0.5 and ages["h"] is None

    # Ghost entries (replicas the controller no longer lists) are
    # pruned; quiet routers' pending reports expire.
    a.note_pending("app", "D", "router-a", 4, now=100.0)
    a.note_pending("app", "D", "router-b", 2, now=130.0)
    assert a.pending_total("app", "D", now=131.0) == 2
    a.prune("app", "D", live_rids={"D#2"}, now=131.0)
    assert a.signal_ages("app", "D", {"g": ["D#1"]}, now=131.0) == \
        {"g": None}
    assert a.pending_total("app", "D", now=131.0, window_s=5.0) == 2

    # tick() decides per group and remembers the decision for status().
    cfg = _cfg()
    a.record("app", "D", "D#2", {"ongoing": 9}, now=131.0)
    groups = {PLAIN_GROUP: {"cur": 1, "rids": ["D#2"]}}
    a.tick("app", "D", cfg, groups, now=131.0)
    decs = a.tick("app", "D", cfg, groups, now=131.2)
    assert decs[PLAIN_GROUP].direction == "up"
    assert a.last_decisions("app", "D")[PLAIN_GROUP]["direction"] == "up"

    # forget() drops book + decision state (same-name redeploys start
    # cold).
    a.forget("app")
    assert a.last_decisions("app", "D") == {}


def test_autoscaling_config_validation_and_roles():
    from ray_tpu.serve.config import AutoscalingConfig
    from ray_tpu.serve.schema import DeploymentSchema

    with pytest.raises(ValueError):
        AutoscalingConfig(min_replicas=2, max_replicas=1)
    with pytest.raises(ValueError):
        AutoscalingConfig(target_occupancy=1.5)
    with pytest.raises(ValueError):
        AutoscalingConfig(roles={"bogus_role": {}})
    with pytest.raises(ValueError):
        AutoscalingConfig(roles={"decode": {"not_a_knob": 1}})

    ac = AutoscalingConfig(max_replicas=8, target_queue_depth=4.0,
                           roles={"decode": {"target_occupancy": 0.8,
                                             "target_queue_depth": None,
                                             "max_replicas": 6}})
    dec = ac.for_role("decode")
    assert dec.target_occupancy == 0.8 and dec.max_replicas == 6
    assert dec.roles is None
    assert ac.for_role("prefill").target_queue_depth == 4.0
    assert ac.for_role(None) is ac

    # The declarative surface validates the block at parse time.
    with pytest.raises(ValueError):
        DeploymentSchema.from_dict(
            {"name": "D", "autoscaling_config": {"bogus": 1}})
    with pytest.raises(ValueError):
        DeploymentSchema.from_dict(
            {"name": "D",
             "autoscaling_config": {"min_replicas": 3, "max_replicas": 1}})
    DeploymentSchema.from_dict(
        {"name": "D", "autoscaling_config": {"max_replicas": 4,
                                             "target_occupancy": 0.7}})


# -------------------------------------------------------------- integration
def _drain_count(dname: str) -> float:
    try:
        text = rt.metrics_text()
    except Exception:  # noqa: BLE001 - head mid-flush
        return 0.0
    return sum(float(line.rsplit(" ", 1)[1])
               for line in text.splitlines()
               if line.startswith("ray_tpu_serve_replica_drains_total")
               and f'deployment="{dname}"' in line)


def test_autoscale_up_down_drains_and_status(serve_instance):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1,
            upscale_delay_s=0.2, downscale_delay_s=0.4,
            metrics_interval_s=0.1, ema_tau_s=0.3, hysteresis=0.1))
    class Slow:
        def __call__(self, x):
            time.sleep(0.3)
            return x

    h = serve.run(Slow.bind(), name="auto17", route_prefix=None)
    failures = []

    def hammer():
        for _ in range(10):
            try:
                h.remote(1).result(timeout=60)
            except Exception as e:  # noqa: BLE001 - counted, asserted 0
                failures.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    saw_up = False
    deadline = time.time() + 25
    while time.time() < deadline:
        st = serve.status()["applications"]["auto17"]["deployments"]["Slow"]
        if st["replicas"] > 1:
            saw_up = True
            break
        time.sleep(0.2)
    assert saw_up, "never scaled above 1 replica under load"

    # Diagnosability satellite: per-group signal freshness + the last
    # decision ride status() next to the engine block.
    assert "signal_age_s" in st and "all" in st["signal_age_s"]
    age = st["signal_age_s"]["all"]
    assert age is None or age >= 0.0
    assert st["autoscale"]["all"]["direction"] in ("up", "down", "hold")

    for t in threads:
        t.join()
    assert failures == [], failures

    # Idle -> back to min, and the scale-down DRAINED its victims (the
    # drain counter moved; no in-flight call was killed — failures
    # above stayed empty while scaling was happening).
    base_drains = None
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()["applications"]["auto17"]["deployments"]["Slow"]
        if st["replicas"] == 1 and st["target"] == 1:
            break
        time.sleep(0.2)
    else:
        pytest.fail("never scaled back down to 1 replica")
    deadline = time.time() + 15
    while time.time() < deadline:
        base_drains = _drain_count("Slow")
        if base_drains >= 1:
            break
        time.sleep(0.5)
    assert base_drains >= 1, "scale-down did not route through drain"

    # The decision metrics reach the cluster-merged /metrics (they
    # count in the controller process, so they must ride the export).
    deadline = time.time() + 20
    found = ""
    while time.time() < deadline:
        try:
            text = rt.metrics_text()
        except Exception:  # noqa: BLE001 - head mid-flush
            text = ""
        found = [line for line in text.splitlines()
                 if line.startswith("ray_tpu_serve_autoscale_decisions"
                                    "_total")
                 and 'direction="up"' in line]
        if found:
            break
        time.sleep(0.5)
    assert found, "autoscale decision counter never reached /metrics"
    serve.delete("auto17")


def test_scale_to_zero_and_back(serve_instance):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=0, max_replicas=2, target_ongoing_requests=2,
            initial_replicas=1, upscale_delay_s=0.1,
            downscale_delay_s=0.2, metrics_interval_s=0.1,
            scale_to_zero_idle_s=1.0, ema_tau_s=0.2,
            cold_start_grace_s=2.0))
    class Echo:
        def __call__(self, x):
            return x + 1

    h = serve.run(Echo.bind(), name="zero17", route_prefix=None)
    assert h.remote(1).result(timeout=30) == 2

    # Idle past the opt-in window: the group drains to ZERO replicas.
    deadline = time.time() + 30
    while time.time() < deadline:
        st = serve.status()["applications"]["zero17"]["deployments"]["Echo"]
        if st["replicas"] == 0 and st["target"] == 0:
            break
        time.sleep(0.2)
    else:
        pytest.fail(f"never reached zero replicas: {st}")

    # A blocked caller's router reports pending demand on its refresh
    # -> scale-from-zero brings one replica back and the call lands.
    assert h.remote(5).result(timeout=60) == 6
    st = serve.status()["applications"]["zero17"]["deployments"]["Echo"]
    assert st["replicas"] >= 1
    serve.delete("zero17")


# -------------------------------------------------------------------- chaos
def _revive_controller(timeout_s: float = 40.0):
    """Wait out the crashed controller's death, then re-create it under
    the same name (what ``serve.start`` would do) and re-point the
    cached client handle at the successor."""
    from ray_tpu.serve import api as sapi

    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        try:
            ctrl = sapi._get_or_create_controller()
            rt.get(ctrl.status.remote(), timeout=5)
            with sapi._client_lock:
                sapi._client["controller"] = ctrl
            return ctrl
        except Exception as e:  # noqa: BLE001 - name not reaped yet
            last = e
            time.sleep(0.3)
    raise TimeoutError(f"controller did not revive: {last!r}")


def _live_replica_names(app_name: str) -> set:
    from ray_tpu.util.state import list_actors

    prefix = f"SERVE_REPLICA:{app_name}:"
    return {a["name"] for a in list_actors()
            if a["state"] == "ALIVE"
            and (a.get("name") or "").startswith(prefix)}


def _membership_names(ctrl, app_name: str, dname: str) -> set:
    from ray_tpu.serve.autoscaler import replica_actor_name

    info = rt.get(ctrl.get_replicas.remote(app_name, dname), timeout=15)
    return {replica_actor_name(app_name, rid)
            for rid in (info or {"replicas": {}})["replicas"]}


def _assert_converged(app_name: str, dname: str, want_n: int,
                      timeout_s: float = 40.0):
    """Membership == the journaled target AND the cluster's live named
    replica actors == membership (zero orphans, zero ghosts)."""
    from ray_tpu.serve import api as sapi

    deadline = time.time() + timeout_s
    state = None
    while time.time() < deadline:
        try:
            ctrl = sapi._controller()
            members = _membership_names(ctrl, app_name, dname)
            census = _live_replica_names(app_name)
            state = (sorted(members), sorted(census))
            if len(members) == want_n and census == members:
                return
        except Exception as e:  # noqa: BLE001 - controller mid-revival
            state = repr(e)
        time.sleep(0.4)
    pytest.fail(f"no convergence to {want_n} replicas: {state}")


def test_controller_crash_mid_scale_up_converges(serve_instance):
    """SIGKILL the controller after a scale-up replica went live but
    BEFORE membership/journal confirmation: the successor adopts the
    journaled fleet (no orphan, no double scale-up) and client calls
    never fail — routers degrade to cached membership while the
    controller is down, and the replicas are detached actors."""
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1,
            upscale_delay_s=0.2, downscale_delay_s=30.0,
            metrics_interval_s=0.1, ema_tau_s=0.3))
    class Slow:
        def __call__(self, x):
            time.sleep(0.25)
            return x

    h = serve.run(Slow.bind(), name="crashup", route_prefix=None)
    assert h.remote(0).result(timeout=30) == 0

    ctrl = rt.get_actor("SERVE_CONTROLLER", timeout=10)
    assert rt.get(ctrl.inject_crash.remote("scale_up_created"),
                  timeout=10)

    failures, done = [], []

    def hammer():
        for i in range(14):
            try:
                h.remote(i).result(timeout=90)
            except Exception as e:  # noqa: BLE001 - counted, asserted 0
                failures.append(repr(e))
        done.append(1)

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()

    # The load forces an upscale; the armed crash point kills the
    # controller the moment the new replica reports ready.
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            rt.get(ctrl.status.remote(), timeout=3)
        except Exception:  # noqa: BLE001 - the crash landed
            break
        time.sleep(0.2)
    else:
        pytest.fail("controller never hit the armed crash point")

    ctrl2 = _revive_controller()
    # Journal replay is asynchronous on the successor's reconcile
    # thread; poll until the app reappears.
    info, deadline = None, time.time() + 30
    while info is None and time.time() < deadline:
        info = rt.get(ctrl2.get_replicas.remote("crashup", "Slow"),
                      timeout=15)
        time.sleep(0.3)
    assert info is not None, "journaled app was not recovered"

    for t in threads:
        t.join(timeout=120)
    assert len(done) == len(threads)
    assert failures == [], failures

    # Converge to the journaled desired state: membership matches the
    # live actor census exactly (no orphans), within the configured
    # bounds, and the adopted scale-up replica was not re-created.
    st = serve.status()["applications"]["crashup"]["deployments"]["Slow"]
    assert 1 <= st["target"] <= 3
    _assert_converged("crashup", "Slow", st["target"])
    serve.delete("crashup")
    assert _live_replica_names("crashup") == set()


def test_controller_crash_mid_drain_converges(serve_instance):
    """SIGKILL the controller after scale-down victims were journaled
    CONDEMNED but before their drain ran: the successor re-drains them
    from the journal and converges to the new target — and the calls
    in flight during the whole sequence all succeed."""
    @serve.deployment(num_replicas=3)
    class Echo:
        def __call__(self, x):
            time.sleep(0.05)
            return x * 2

    h = serve.run(Echo.bind(), name="crashdown", route_prefix=None)
    assert h.remote(2).result(timeout=30) == 4
    assert len(_live_replica_names("crashdown")) == 3

    ctrl = rt.get_actor("SERVE_CONTROLLER", timeout=10)
    assert rt.get(ctrl.inject_crash.remote("drain_condemned"), timeout=10)

    failures, stop = [], []

    def trickle():
        while not stop:
            try:
                h.remote(1).result(timeout=60)
            except Exception as e:  # noqa: BLE001 - counted, asserted 0
                failures.append(repr(e))
            time.sleep(0.05)

    t = threading.Thread(target=trickle)
    t.start()
    try:
        # Redeploy at num_replicas=1: the scale-down journals its two
        # victims condemned, then the armed point kills the controller.
        with pytest.raises(Exception):
            serve.run(Echo.options(num_replicas=1).bind(),
                      name="crashdown", route_prefix=None)
        _revive_controller()
        _assert_converged("crashdown", "Echo", 1)
    finally:
        stop.append(1)
        t.join(timeout=60)
    assert failures == [], failures
    serve.delete("crashdown")
    assert _live_replica_names("crashdown") == set()


# -------------------------------------------------------------------- smoke
def test_cluster_smoke_benchmark():
    """Satellite CI hook: ``benchmarks/serve_cluster.py --smoke`` runs a
    short diurnal curve with one replica kill and one controller kill
    mid-ramp and asserts convergence, zero broken streams, and zero
    orphans."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(root, "benchmarks", "serve_cluster.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env, cwd=root)
    assert proc.returncode == 0, proc.stdout + "\n" + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith("{")]
    chaos = [r for r in rows if r["metric"].endswith("autoscale_chaos")]
    assert chaos, rows
    row = chaos[0]
    assert row["smoke"] is True
    assert row["broken_streams"] == 0
    assert row["orphans"] == 0
    assert row["kills"] >= 1
    assert row["converged"] is True
    # Flight-recorder acceptance (ISSUE 19): --smoke implies
    # --blackbox, and the killed-replica request's reconstruction —
    # merged from the DEAD process's ring — must contain the kill, the
    # resume, and the token-identity verdict with one correlation id.
    bb = [r for r in rows if r["metric"] == "serve_cluster_blackbox"]
    assert bb, rows
    story = bb[0]
    assert story["request"], story
    kinds = set(story["story_kinds"])
    assert "chaos.kill" in kinds, story
    assert "router.resume" in kinds or "engine.resume" in kinds, story
    assert "client.verdict" in kinds, story
    assert story["torn"] == 0 or story["torn"] <= story["rings"], story
