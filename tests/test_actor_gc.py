"""Actor handle GC: actors die when every handle goes out of scope.

Mirrors the reference's actor out-of-scope coverage
(``python/ray/tests/test_actor_lifecycle.py`` / gcs_actor_manager
handle-out-of-scope death): anonymous actors are collected after their
last handle drops (freeing their resource charge), named/detached actors
persist, and borrowed handles keep actors alive.
"""
import gc
import time

import pytest

import ray_tpu as rt_mod


def _alive_count(rt):
    return sum(1 for a in rt.state("actors") if a["state"] == "ALIVE")


def _wait_for(predicate, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.2)
    return False


def test_actor_gc_on_handle_drop(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Ephemeral:
        def ping(self):
            return 1

    # Quiesce: leftover leases from earlier tests release on a ~2s TTL;
    # take the baseline only once availability is stable.
    before_cpu = rt.available_resources()["CPU"]
    deadline = time.time() + 20
    while time.time() < deadline:
        time.sleep(2.6)
        now_cpu = rt.available_resources()["CPU"]
        if now_cpu == before_cpu:
            break
        before_cpu = now_cpu
    h = Ephemeral.remote()
    assert rt.get(h.ping.remote()) == 1
    assert rt.available_resources()["CPU"] == before_cpu - 1
    del h
    gc.collect()
    # Grace period (1s) + kill + charge release.
    assert _wait_for(
        lambda: rt.available_resources()["CPU"] == before_cpu), \
        rt.available_resources()


def test_named_actor_survives_handle_drop(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Persistent:
        def ping(self):
            return "pong"

    Persistent.options(name="gc_survivor").remote()
    gc.collect()
    time.sleep(2.5)  # longer than the GC grace period
    h = rt.get_actor("gc_survivor")
    assert rt.get(h.ping.remote()) == "pong"
    rt.kill(h)


def test_borrowed_handle_keeps_actor_alive(rt_cluster):
    rt = rt_cluster

    @rt.remote
    class Target:
        def ping(self):
            return 42

    @rt.remote
    class Holder:
        def hold(self, h):
            self.h = h
            return True

        def use(self):
            import ray_tpu as rt2

            return rt2.get(self.h.ping.remote())

    t = Target.remote()
    holder = Holder.remote()
    assert rt.get(holder.hold.remote(t)) is True
    del t
    gc.collect()
    time.sleep(2.5)  # past the grace period
    # The holder's borrowed handle must have kept the target alive.
    assert rt.get(holder.use.remote()) == 42
    rt.kill(holder)
    gc.collect()


def test_actors_no_longer_leak_cpus(rt_fresh):
    """The probe from the round-2 verdict: >8 sequential actors on 8 CPUs
    now works because dropped handles free their charge."""
    rt = rt_fresh

    @rt.remote
    class A:
        def ping(self):
            return 1

    for i in range(10):
        h = A.remote()
        assert rt.get(h.ping.remote()) == 1
        del h  # dropped each round; GC keeps the pool from exhausting
