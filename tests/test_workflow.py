"""Workflow tests (reference: ``python/ray/workflow/tests/`` —
run/resume/continuation/cancel/event semantics)."""
import time

import pytest

import ray_tpu as rt
from ray_tpu import workflow


@pytest.fixture
def wf(rt_cluster, tmp_path):
    workflow.init(str(tmp_path / "wf"))
    yield workflow


@rt.remote
def add(a, b):
    return a + b


@rt.remote
def double(x):
    return 2 * x


def test_run_dag(wf):
    # (1 + 2) * 2 + 3
    dag = add.bind(double.bind(add.bind(1, 2)), 3)
    assert wf.run(dag, workflow_id="sum") == 9
    assert wf.get_status("sum") == wf.SUCCESSFUL
    assert wf.get_output("sum") == 9
    assert "sum" in wf.list_all()


def test_diamond_parallel_deps(wf):
    @rt.remote
    def fan(x):
        return x + 1

    @rt.remote
    def join(a, b, c):
        return a + b + c

    src = add.bind(1, 1)
    dag = join.bind(fan.bind(src), fan.bind(src), double.bind(src))
    assert wf.run(dag, workflow_id="diamond") == 10  # 3 + 3 + 4


def test_resume_skips_checkpointed_tasks(wf, tmp_path):
    marker = tmp_path / "ran"

    @rt.remote
    def count_runs(x):
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        return x

    @rt.remote
    def boom(x, should_fail_file):
        import os

        if os.path.exists(should_fail_file):
            raise RuntimeError("injected")
        return x * 10

    fail_flag = tmp_path / "fail"
    fail_flag.write_text("1")
    dag = boom.bind(count_runs.bind(7), str(fail_flag))
    with pytest.raises(workflow.WorkflowExecutionError):
        wf.run(dag, workflow_id="crashy")
    assert wf.get_status("crashy") == wf.FAILED
    assert marker.read_text() == "1"

    fail_flag.unlink()
    assert wf.resume("crashy") == 70
    # count_runs was checkpointed — resume must not re-run it.
    assert marker.read_text() == "1"
    assert wf.get_status("crashy") == wf.SUCCESSFUL


def test_max_retries_and_catch_exceptions(wf, tmp_path):
    flaky_file = tmp_path / "attempts"

    @rt.remote
    def flaky():
        n = int(flaky_file.read_text()) if flaky_file.exists() else 0
        flaky_file.write_text(str(n + 1))
        if n < 2:
            raise ValueError("try again")
        return "ok"

    node = flaky.options(**workflow.options(max_retries=3)).bind()
    assert wf.run(node, workflow_id="retry") == "ok"
    assert flaky_file.read_text() == "3"

    @rt.remote
    def always_fails():
        raise KeyError("nope")

    node = always_fails.options(
        **workflow.options(catch_exceptions=True)).bind()
    value, err = wf.run(node, workflow_id="caught")
    assert value is None and isinstance(err, Exception)


def test_catch_exceptions_with_continuation(wf):
    @rt.remote
    def extend():
        return workflow.continuation(add.bind(1, 2))

    node = extend.options(**workflow.options(catch_exceptions=True)).bind()
    value, err = wf.run(node, workflow_id="caught-cont")
    assert value == 3 and err is None


def test_catch_exceptions_with_failing_continuation(wf):
    @rt.remote
    def boom():
        raise RuntimeError("sub-dag failure")

    @rt.remote
    def extend():
        return workflow.continuation(boom.bind())

    node = extend.options(**workflow.options(catch_exceptions=True)).bind()
    value, err = wf.run(node, workflow_id="caught-cont-fail")
    assert value is None and isinstance(err, Exception)


def test_cancel_terminal_is_noop(wf):
    wf.run(add.bind(1, 1), workflow_id="done")
    wf.cancel("done")  # must not clobber the SUCCESSFUL outcome
    assert wf.get_status("done") == wf.SUCCESSFUL
    assert wf.get_output("done") == 2


def test_continuation(wf):
    @rt.remote
    def fib(n):
        if n <= 1:
            return n
        return workflow.continuation(add.bind(fib.bind(n - 1),
                                              fib.bind(n - 2)))

    assert wf.run(fib.bind(6), workflow_id="fib") == 8


def test_cancel(wf):
    @rt.remote
    def slow(x):
        time.sleep(0.3)
        return x

    # Chain long enough that cancel lands mid-run.
    node = slow.bind(0)
    for i in range(20):
        node = slow.bind(node)
    wid = wf.run_async(node, workflow_id="tocancel")
    time.sleep(0.4)
    wf.cancel(wid)
    with pytest.raises(workflow.WorkflowCancellationError):
        wf.get_output(wid)
    assert wf.get_status(wid) == wf.CANCELED


def test_sleep_is_durable(wf):
    @rt.remote
    def after(_sleep, x):
        return x

    t0 = time.time()
    assert wf.run(after.bind(workflow.sleep(0.2), 5),
                  workflow_id="zzz") == 5
    assert time.time() - t0 >= 0.2
    # Checkpointed deadline: resuming a finished run is instant.
    t1 = time.time()
    assert wf.resume("zzz") == 5
    assert time.time() - t1 < 0.2


def test_wait_for_event(wf, tmp_path):
    sentinel = str(tmp_path / "event")

    class FileEvent(workflow.EventListener):
        def poll_for_event(self, path):
            import os
            import time as _t

            while not os.path.exists(path):
                _t.sleep(0.02)
            return open(path).read()

    (tmp_path / "event").write_text("fired")
    ev = workflow.wait_for_event(FileEvent, sentinel)
    assert wf.run(ev, workflow_id="evt") == "fired"


def test_metadata_and_delete(wf):
    wf.run(add.bind(1, 1), workflow_id="meta", metadata={"owner": "test"})
    md = wf.get_metadata("meta")
    assert md["status"] == "SUCCESSFUL" and md["owner"] == "test"
    wf.delete("meta")
    with pytest.raises(workflow.WorkflowNotFoundError):
        wf.get_status("meta")
    assert "meta" not in wf.list_all()


def test_resume_all_and_stale_running(wf, tmp_path):
    # Simulate a crashed owner: storage says RUNNING, no local thread.
    store = workflow.api._store()
    store.create("stale", add.bind(2, 3), {})
    assert wf.get_status("stale") == wf.RESUMABLE
    resumed = wf.resume_all()
    assert "stale" in resumed
    assert wf.get_output("stale") == 5
