"""Memory monitor + OOM worker-killing (reference:
``src/ray/common/memory_monitor.h:52``,
``src/ray/raylet/worker_killing_policy.h:1``): a task ballooning past
the node threshold is killed, its retry completes, the node survives,
and the kill is visible in metrics + state API."""
import os

import numpy as np
import pytest

from ray_tpu._private.memory_monitor import (
    MemorySnapshot,
    kill_threshold_bytes,
    sample_memory,
)


def test_sample_memory_sane():
    snap = sample_memory()
    assert 0 < snap.used_bytes < snap.total_bytes
    assert 0.0 < snap.used_fraction < 1.0


def test_threshold_math():
    snap = MemorySnapshot(used_bytes=50, total_bytes=100)
    assert kill_threshold_bytes(snap, 0.95) == 95
    # min_free tightens the fraction threshold
    assert kill_threshold_bytes(snap, 0.95, min_free_bytes=20) == 80
    assert kill_threshold_bytes(snap, 0.95, min_free_bytes=-1) == 95


def test_env_cap_limits_total(monkeypatch):
    real = sample_memory()
    cap = real.total_bytes // 2
    monkeypatch.setenv("RT_MEMORY_LIMIT_BYTES", str(cap))
    assert sample_memory().total_bytes == cap


def test_oom_kill_and_retry(monkeypatch, tmp_path):
    """The chaos gate: a ballooning retriable task is OOM-killed by the
    monitor; the retry (which allocates nothing) completes; the node
    survives; the kill shows up in the state API and metrics."""
    import ray_tpu as rt

    monkeypatch.setenv("RT_MEMORY_MONITOR_REFRESH_MS", "100")
    monkeypatch.setenv("RT_MEMORY_MONITOR_KILL_GRACE_S", "1.0")
    sentinel = str(tmp_path / "attempt.marker")

    if rt.is_initialized():
        rt.shutdown()  # a session fixture may have left a cluster up
    rt.init(num_cpus=2, num_tpus=0)
    try:
        # Baseline AFTER the cluster is up (worker/head overhead must
        # not eat the margin) and generous headroom: under a loaded
        # full-suite run the host baseline drifts, and a thin margin
        # turns drift into spurious kills or missed ones.
        headroom = 1024 * 2**20
        snap = sample_memory()
        limit = snap.used_bytes + 2 * headroom
        threshold = (snap.used_bytes + headroom) / limit
        monkeypatch.setenv("RT_MEMORY_LIMIT_BYTES", str(limit))
        monkeypatch.setenv("RT_MEMORY_USAGE_THRESHOLD",
                           f"{threshold:.6f}")

        @rt.remote(max_retries=3)
        def balloon(sentinel):
            import time as _t

            if os.path.exists(sentinel):
                return "retried-ok"  # second attempt: no allocation
            with open(sentinel, "w") as f:
                f.write("1")
            hog = []
            for _ in range(40):  # 40 × 50MiB of incompressible pages
                hog.append(np.random.bytes(50 * 2**20))
                _t.sleep(0.05)
            _t.sleep(60)  # hold until the monitor kills us
            return "survived"  # must not happen

        result = rt.get(balloon.remote(sentinel), timeout=180)
        assert result == "retried-ok"
        # state API shows the kill with its policy verdict
        kills = rt.state("oom_kills")
        assert len(kills) >= 1
        assert kills[0]["kind"] == "leased task"
        assert kills[0]["used_bytes"] > kills[0]["threshold_bytes"]
        # node survived: normal work still schedules
        assert rt.get(rt.remote(lambda: 7).remote(), timeout=30) == 7
    finally:
        rt.shutdown()


def test_oom_retry_exhaustion_surfaces_error(monkeypatch):
    """Under UNRECLAIMABLE pressure (threshold below baseline usage),
    every retry gets killed too; the caller sees WorkerCrashedError
    after the budget drains (the reference surfaces OutOfMemoryError
    to the caller the same way) instead of hanging forever."""
    import ray_tpu as rt
    from ray_tpu.exceptions import TaskError, WorkerCrashedError

    snap = sample_memory()
    # threshold below CURRENT usage → every sample reports pressure
    monkeypatch.setenv("RT_MEMORY_LIMIT_BYTES", str(snap.used_bytes * 2))
    monkeypatch.setenv("RT_MEMORY_USAGE_THRESHOLD", "0.01")
    monkeypatch.setenv("RT_MEMORY_MONITOR_REFRESH_MS", "100")
    monkeypatch.setenv("RT_MEMORY_MONITOR_KILL_GRACE_S", "0.2")

    if rt.is_initialized():
        rt.shutdown()  # a session fixture may have left a cluster up
    rt.init(num_cpus=1, num_tpus=0)
    try:
        @rt.remote(max_retries=1)
        def steady():
            import time as _t

            _t.sleep(30.0)  # killed well before this returns
            return "done"

        with pytest.raises((WorkerCrashedError, TaskError)):
            rt.get(steady.remote(), timeout=120)
        # ≥1 kill recorded; the surfaced error itself proves the retry
        # budget drained (under load, a retry may die to a slow lease
        # rather than a second kill — both are valid exhaustion paths)
        assert len(rt.state("oom_kills")) >= 1
    finally:
        rt.shutdown()
