"""Multi-node runtime: node daemons, policies, cross-node objects, node FT.

Mirrors the reference's multi-node test strategy
(``python/ray/tests/test_multi_node.py``, ``test_placement_group*.py``
over ``cluster_utils.Cluster``): a real head + real node-daemon
subprocesses, so node kills are process kills.
"""
import time

import numpy as np
import pytest

from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster2():
    """Head (0 CPU) + two 2-CPU nodes, driver connected."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    c = Cluster(head_resources={"CPU": 0})
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2)
    rt = c.connect()
    yield c, rt
    c.shutdown()


def _node_of():
    import ray_tpu as rt

    @rt.remote
    def whereami():
        from ray_tpu.core.worker import CoreWorker

        return CoreWorker.current().node_id

    return whereami


def test_shared_shm_domain_nodes_use_shm():
    """``add_node(shared_shm=True)``: co-hosted daemons join the
    session's shm domain, so cross-node object exchange rides shared
    memory (one-daemon-per-host fast path) instead of TCP."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    c = Cluster(head_resources={"CPU": 0})
    c.add_node(num_cpus=1, shared_shm=True)
    c.add_node(num_cpus=1, shared_shm=True)
    rt = c.connect()
    try:
        nodes = [n for n in c.list_nodes() if not n.get("is_head")]
        assert len({n["hostname"] for n in nodes}) == 1  # one domain
        # a large (shm-tier) object made on node 1 is consumed on node
        # 2 — PINNED to distinct nodes, so the exchange really crosses
        # daemons (over the shared shm domain, not TCP)
        n1, n2 = c._nodes
        strat = rt.NodeAffinitySchedulingStrategy

        @rt.remote
        def produce():
            return np.arange(1_000_000, dtype=np.int64)

        @rt.remote
        def consume(a):
            return int(a.sum())

        ref = produce.options(
            scheduling_strategy=strat(n1.node_id)).remote()
        assert rt.get(consume.options(
            scheduling_strategy=strat(n2.node_id)).remote(ref),
            timeout=60) == 499999500000
    finally:
        c.shutdown()


def test_node_label_scheduling():
    """NODE_LABEL strategy (reference:
    ``node_label_scheduling_policy.h``): hard labels select, soft labels
    prefer, and an unsatisfiable hard selector fails the lease."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    c = Cluster(head_resources={"CPU": 0})
    c.add_node(num_cpus=2, labels={"zone": "a", "disk": "ssd"})
    c.add_node(num_cpus=2, labels={"zone": "b"})
    rt = c.connect()
    try:
        whereami = _node_of()
        strat = rt.NodeLabelSchedulingStrategy
        ssd, zb = c._nodes

        on_ssd = rt.get(whereami.options(
            scheduling_strategy=strat(hard={"disk": "ssd"})).remote())
        assert on_ssd == ssd.node_id

        on_b = rt.get(whereami.options(
            scheduling_strategy=strat(hard={"zone": "b"})).remote())
        assert on_b == zb.node_id

        # Soft-only: prefers the match but never blocks.
        pref = rt.get(whereami.options(
            scheduling_strategy=strat(soft={"zone": "b"})).remote())
        assert pref == zb.node_id

        # Unsatisfiable hard selector: lease times out as an error.
        with pytest.raises(Exception):
            rt.get(whereami.options(
                scheduling_strategy=strat(hard={"zone": "mars"})).remote(),
                timeout=10)
    finally:
        c.shutdown()


def test_spread_uses_both_nodes(cluster2):
    c, rt = cluster2
    whereami = _node_of()
    ids = rt.get([whereami.options(scheduling_strategy="SPREAD").remote()
                  for _ in range(8)])
    assert len({x for x in ids if x}) == 2, ids


def test_node_affinity_hard_and_soft(cluster2):
    c, rt = cluster2
    n1, n2 = c._nodes
    whereami = _node_of()
    strat = rt.NodeAffinitySchedulingStrategy
    assert rt.get(whereami.options(
        scheduling_strategy=strat(n1.node_id)).remote()) == n1.node_id
    assert rt.get(whereami.options(
        scheduling_strategy=strat(n2.node_id)).remote()) == n2.node_id


def test_cross_node_object_transfer(cluster2):
    """A large (shm-tier) object created on node 1 is consumed on node 2 and
    by the driver: the cross-shm-domain path ships bytes over TCP."""
    c, rt = cluster2
    n1, n2 = c._nodes
    strat = rt.NodeAffinitySchedulingStrategy

    @rt.remote
    def make():
        return np.arange(1 << 20, dtype=np.float32)  # 4 MB

    @rt.remote
    def consume(a):
        return float(a.sum())

    ref = make.options(scheduling_strategy=strat(n1.node_id)).remote()
    expected = float(np.arange(1 << 20, dtype=np.float32).sum())
    assert rt.get(consume.options(
        scheduling_strategy=strat(n2.node_id)).remote(ref)) == expected
    assert float(rt.get(ref).sum()) == expected


def test_strict_spread_placement_group(cluster2):
    c, rt = cluster2
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}],
                            strategy="STRICT_SPREAD")
    pg.ready(timeout=30)
    whereami = _node_of()
    homes = rt.get([
        whereami.options(scheduling_strategy=rt.PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=i)).remote()
        for i in range(2)])
    assert homes[0] != homes[1], homes
    rt.remove_placement_group(pg)


def test_strict_spread_infeasible_with_one_node():
    """STRICT_SPREAD with more bundles than nodes must fail, not degrade."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    c = Cluster(head_resources={"CPU": 0})
    c.add_node(num_cpus=4)
    rt = c.connect()
    try:
        pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}],
                                strategy="STRICT_SPREAD")
        with pytest.raises(Exception):
            pg.ready(timeout=3)
    finally:
        c.shutdown()


def test_strict_pack_stays_on_one_node(cluster2):
    c, rt = cluster2
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    pg.ready(timeout=30)
    whereami = _node_of()
    homes = rt.get([
        whereami.options(scheduling_strategy=rt.PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=i)).remote()
        for i in range(2)])
    assert homes[0] == homes[1], homes
    rt.remove_placement_group(pg)


def test_actor_restarts_on_surviving_node(cluster2):
    c, rt = cluster2
    n1, n2 = c._nodes
    strat = rt.NodeAffinitySchedulingStrategy

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def node(self):
            from ray_tpu.core.worker import CoreWorker

            return CoreWorker.current().node_id

    a = Counter.options(
        max_restarts=2,
        scheduling_strategy=strat(n2.node_id, soft=True)).remote()
    assert rt.get(a.incr.remote()) == 1
    home = rt.get(a.node.remote())
    assert home == n2.node_id

    c.remove_node(n2, graceful=False)

    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            new_home = rt.get(a.node.remote(), timeout=10)
            if new_home and new_home != home:
                break
        except Exception:
            time.sleep(0.2)
    else:
        pytest.fail("actor never restarted on the surviving node")
    # State was lost (fresh instance), but the handle keeps working.
    assert rt.get(a.incr.remote()) >= 1


def test_node_death_replaces_pg_bundle(cluster2):
    """A bundle on a dead node is re-placed on a surviving node
    (reference: gcs_placement_group_manager rescheduling)."""
    c, rt = cluster2
    n1, n2 = c._nodes
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="SPREAD")
    pg.ready(timeout=30)
    c.remove_node(n2, graceful=False)
    # After the kill, the PG must become fully placed again (both bundles on
    # the surviving node — SPREAD is best-effort).
    deadline = time.time() + 30
    whereami = _node_of()
    while time.time() < deadline:
        try:
            homes = rt.get([
                whereami.options(
                    scheduling_strategy=rt.PlacementGroupSchedulingStrategy(
                        pg, placement_group_bundle_index=i)).remote()
                for i in range(2)], timeout=15)
            assert all(h == n1.node_id for h in homes), homes
            break
        except AssertionError:
            raise
        except Exception:
            time.sleep(0.3)
    else:
        pytest.fail("PG bundle was never re-placed after node death")
    rt.remove_placement_group(pg)


def test_gang_train_job_across_nodes(cluster2):
    """2-worker gang data-parallel train job spanning both nodes
    (SURVEY §7: gang-schedule across a slice's hosts)."""
    c, rt = cluster2
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        from ray_tpu import train as train_session

        for step in range(3):
            train_session.report({"step": step, "loss": 1.0 / (step + 1)})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1},
                                     placement_strategy="STRICT_SPREAD"),
        run_config=RunConfig(name="multinode-gang"),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 2
