"""Numerics of the Pallas flash-attention kernel vs the XLA einsum path.

Runs in the Pallas interpreter on the virtual CPU platform (exact f32),
so tolerances are tight; on-TPU both paths share bf16 MXU rounding.
"""
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models.gpt import GPTConfig, _attention_xla
from ray_tpu.ops.flash_attention import flash_attention


def _rand_qkv(key, B, S, H, hd, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    shape = (B, S, H, hd)
    return (jax.random.normal(k1, shape, dtype),
            jax.random.normal(k2, shape, dtype),
            jax.random.normal(k3, shape, dtype))


@pytest.mark.parametrize("S,causal", [(256, True), (256, False), (512, True)])
def test_flash_matches_xla_forward(S, causal):
    B, H, hd = 2, 4, 64
    cfg = GPTConfig(n_head=H, d_model=H * hd)
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), B, S, H, hd)
    out = flash_attention(q, k, v, causal=causal)
    if causal:
        ref = _attention_xla(q, k, v, cfg)
    else:
        import math
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        logits = logits / math.sqrt(hd)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                         preferred_element_type=jnp.float32)
    err = float(jnp.max(jnp.abs(out - ref)))
    tol = 2e-3 if jax.devices()[0].platform == "tpu" else 1e-4
    assert err < max(tol, 1e-4), err


def test_flash_gradients_match_xla():
    B, S, H, hd = 2, 256, 2, 64
    cfg = GPTConfig(n_head=H, d_model=H * hd)
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), B, S, H, hd)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_attention_xla(q, k, v, cfg) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        rel = float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9))
        tol = 2e-2 if jax.devices()[0].platform == "tpu" else 1e-4
        assert rel < tol, (name, rel)


def test_flash_uneven_blocks():
    # S=128 forces block <= 128 via the adaptive block picker.
    B, S, H, hd = 1, 128, 2, 32
    cfg = GPTConfig(n_head=H, d_model=H * hd)
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), B, S, H, hd)
    out = flash_attention(q, k, v, causal=True)
    ref = _attention_xla(q, k, v, cfg)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-3


def test_gpt_trains_with_flash_backend_multidevice_mesh():
    """flash backend on a multi-device mesh routes through shard_map
    (GSPMD cannot partition Mosaic kernels; regression for the auto
    backend on real multi-chip slices)."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    cfg = dataclasses.replace(
        gpt.CONFIGS["nano"], attn_backend="flash", max_seq=256)
    init, step, _, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = jax.device_put(
        rng.integers(0, cfg.vocab_size, (8, 257)).astype(np.int32), batch_sh)
    state, metrics = step(state, {"tokens": toks})
    assert jnp.isfinite(metrics["loss"])


def test_gpt_trains_with_flash_backend():
    """nano GPT trains a step with attn_backend='flash' on the CPU mesh."""
    import dataclasses

    import numpy as np

    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    cfg = dataclasses.replace(
        gpt.CONFIGS["nano"], attn_backend="flash", max_seq=256)
    mesh = create_mesh({"dp": 1}, devices=[jax.devices()[0]])
    init, step, _, batch_sh = gpt.make_train_step(cfg, mesh)
    state = init(jax.random.PRNGKey(0))
    toks = jax.device_put(
        np.random.randint(0, cfg.vocab_size, (4, 257), np.int32), batch_sh)
    state, metrics = step(state, {"tokens": toks})
    assert float(metrics["loss"]) > 0 and jnp.isfinite(metrics["loss"])
