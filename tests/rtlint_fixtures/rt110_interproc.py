"""RT110 fixture: interprocedural lock/driver contracts at call edges
(rtflow, ISSUE 15) — the static twin of rtsan's RS102/RS103. Never
imported."""
import threading


class Interproc:
    """holds= contracts checked at every resolved call edge."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump(self):  # rtlint: holds=_lock
        self._n += 1

    def ok_lexical(self):
        with self._lock:
            self._bump()

    def ok_transitive(self):  # rtlint: holds=_lock
        # The caller's own holds= contract credits the edge.
        self._bump()

    def ok_manual(self):
        self._lock.acquire()
        try:
            self._bump()
        finally:
            self._lock.release()

    def bad_caller(self):
        self._bump()  # FIRES RT110

    def suppressed_caller(self):
        # rtlint: disable=RT110 single-threaded test harness path
        self._bump()

    def _flush_locked(self):
        self._n = 0

    def ok_locked_convention(self):
        with self._lock:
            self._flush_locked()

    def bad_locked_convention(self):
        self._flush_locked()  # FIRES RT110


class DriverContract:
    """owner=driver propagation: driver code and thread registrations
    may enter; anything else is a cross-thread dispatch hazard."""

    # rtlint: owner=driver entry=driver
    def _run(self):
        self._step()                     # owner -> owner: clean

    # rtlint: owner=driver
    def _step(self):
        return 1

    def start(self):
        # The repo's driver registration idiom: a thread edge is THE
        # legitimate entry into owner=driver code.
        t = threading.Thread(target=self._run, daemon=True)
        return t

    def rogue(self):
        return self._step()  # FIRES RT110

    def suppressed_rogue(self):
        # rtlint: disable=RT110 ownership transfer: driver joined above
        return self._step()
