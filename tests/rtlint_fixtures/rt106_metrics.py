"""RT106 fixture: prometheus metric-name conventions at construction
sites (shared implementation with MetricsRegistry.register). Never
imported."""
from collections import Counter as CollectionsCounter


class Counter:      # stand-ins for ray_tpu._private.metrics types
    def __init__(self, name, description=""):
        self.name = name


class Gauge(Counter):
    pass


class Histogram(Counter):
    pass


good = (
    Counter("serve_requests_shed_total"),
    Gauge("serve_engine_pages_free"),
    Histogram("serve_queue_wait_seconds"),
    Histogram("serve_batch_size"),          # not a duration: no suffix
)

bad_counter = Counter("requests_shed")  # FIRES RT106
bad_histogram = Histogram("decode_latency")  # FIRES RT106
bad_grammar = Gauge("pages free")  # FIRES RT106
bad_kw = Counter(name="retries")  # FIRES RT106

suppressed = Counter("legacy_shed")  # rtlint: disable=RT106 grandfathered wire name

# collections.Counter is not a metric: clean.
histogram_of_chars = CollectionsCounter("not_a_metric_name")
