"""RT105 fixture: retryable-wire consistency. Self-contained — defines
its own ``_PUSHBACK_CAUSES`` and exception classes. Never imported."""

_PUSHBACK_CAUSES = ("ListedRetryableError", "ListedNotRetryableError",
                    "InheritedRetryableError", "UnknownElsewhereError")


class ListedRetryableError(RuntimeError):
    retryable = True


class ListedNotRetryableError(RuntimeError):  # FIRES RT105
    """Listed in _PUSHBACK_CAUSES but missing retryable = True."""


class InheritedRetryableError(ListedRetryableError):
    """retryable inherited from the base: clean."""


class UnlistedRetryableError(RuntimeError):  # FIRES RT105
    """Sets retryable = True but is not in _PUSHBACK_CAUSES."""

    retryable = True


# rtlint: disable=RT105 local-only error, never crosses the wire
class SuppressedRetryableError(RuntimeError):
    retryable = True


class ExplicitlyNotRetryable(RuntimeError):
    """retryable = False is an explicit opt-out: clean."""

    retryable = False


class PlainError(RuntimeError):
    """No retryable attribute, not listed: clean."""
