"""RT101 fixture: lock-guard inference (never imported).

Lines tagged ``# FIRES`` must produce exactly one RT101 finding each;
every other line must stay clean. The test derives expectations from
these tags, so line numbers never need maintaining.
"""
import threading


class Positive:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0          # __init__ writes never count

    def guarded(self):
        with self._lock:
            self._count += 1

    def unguarded(self):
        self._count += 1  # FIRES RT101

    def unguarded_item(self):
        self._stats = {}  # FIRES RT101

    def guarded_item(self):
        with self._lock:
            self._stats["x"] = 1


class PositiveItem:
    """Subscript stores count as writes to the attribute; Condition
    attrs count as locks."""

    def __init__(self):
        self._cond = threading.Condition()
        self._vals = {}

    def guarded(self):
        with self._cond:
            self._vals["a"] = 1

    def unguarded(self):
        self._vals["b"] = 2  # FIRES RT101


class Suppressed:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def guarded(self):
        with self._lock:
            self._n = 1

    def justified(self):
        self._n = 2              # rtlint: disable=RT101 single writer

    def justified_above(self):
        # rtlint: disable=RT101 wrapped statement, directive above
        self._n = 3

    def whole_method(self):  # rtlint: disable=RT101 ctor-only path
        self._n = 4
        self._n = 5

    def multi_rule(self):
        # The suppressed rule is SECOND in the comma list — pins the
        # documented disable=RTxxx,RTyyy grammar.
        self._n = 6              # rtlint: disable=RT103,RT101 multi


def _fixture_deco(f):
    return f


class DecoratorSuppressed:
    """A ``disable=`` on a DECORATOR line covers the decorated def
    (ISSUE 15 satellite: previously only the ``def`` line or the line
    directly above it attached, so decorated functions could not be
    suppressed at their signature)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._d = 0

    def guarded(self):
        with self._lock:
            self._d = 1

    @_fixture_deco  # rtlint: disable=RT101 single writer behind deco
    def on_decorator_line(self):
        self._d = 2

    # rtlint: disable=RT101 directive above the decorator stack
    @_fixture_deco
    @_fixture_deco
    def above_decorators(self):
        self._d = 3

    @_fixture_deco
    def unsuppressed(self):
        self._d = 4  # FIRES RT101


class Negative:
    """All writes guarded, or no lock at all — no findings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._a = 0
        self._plain = 0

    def one(self):
        with self._lock:
            self._a = 1

    def two(self):
        with self._lock:
            self._a += 2

    def lockless_attr(self):
        self._plain = 3          # never guarded anywhere: no finding


class NegativeConventions:
    """_locked suffix, holds=, owner=driver, and manual acquire all
    count as guarded."""

    def __init__(self):
        self._lock = threading.Lock()
        self._x = 0

    def guarded(self):
        with self._lock:
            self._x = 1

    def _bump_locked(self):
        self._x += 1             # *_locked: callers hold the lock

    def annotated(self):  # rtlint: holds=_lock
        self._x += 1

    def driver_owned(self):  # rtlint: owner=driver
        self._x += 1

    def manual(self):
        if self._lock.acquire(blocking=False):
            try:
                self._x += 1
            finally:
                self._lock.release()
